"""Serving runtime: cache TTL/LRU/invalidation, coalescing, serving stats."""

import threading
import time

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.serving import ServingConfig
from vizier_tpu.serving import ServingStats
from vizier_tpu.serving.coalescer import RequestCoalescer
from vizier_tpu.serving.designer_cache import DesignerStateCache
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2

STUDY = "owners/o/studies/s"


def _study_config(algorithm="DEFAULT", num_params=2):
    config = vz.StudyConfig(algorithm=algorithm)
    for d in range(num_params):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _make_service(policy_factory=None, serving_config=None):
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(
        servicer, policy_factory, serving_config=serving_config
    )
    servicer.set_pythia(pythia)
    return servicer, pythia


def _create_study(servicer, config=None, name=STUDY):
    study = pc.study_to_proto(config or _study_config(), name)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
    )


def _complete_some_trials(servicer, n=3, name=STUDY):
    from vizier_tpu.service.protos import study_pb2

    for i in range(n):
        created = servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(parent=name, trial=study_pb2.Trial())
        )
        req = vizier_service_pb2.CompleteTrialRequest(name=created.name)
        m = req.final_measurement.metrics.add()
        m.name, m.value = "obj", 0.1 * i
        servicer.CompleteTrial(req)


class TestServingStats:
    def test_increment_and_snapshot(self):
        stats = ServingStats()
        stats.increment("cache_hits")
        stats.increment("warm_trains", 3)
        snap = stats.snapshot()
        assert snap["cache_hits"] == 1
        assert snap["warm_trains"] == 3
        assert snap["cold_trains"] == 0

    def test_unknown_counter_rejected(self):
        with pytest.raises(KeyError):
            ServingStats().increment("cache_hit")  # singular: a typo


class TestDesignerStateCache:
    def test_miss_then_hit(self):
        cache = DesignerStateCache()
        built = []

        def factory():
            built.append(1)
            return object()

        e1 = cache.get_or_create("s1", factory)
        e2 = cache.get_or_create("s1", factory)
        assert e1 is e2
        assert len(built) == 1
        assert cache.stats.get("cache_misses") == 1
        assert cache.stats.get("cache_hits") == 1

    def test_ttl_eviction(self):
        clock = [0.0]
        cache = DesignerStateCache(ttl_seconds=10.0, time_fn=lambda: clock[0])
        first = cache.get_or_create("s1", object)
        clock[0] = 5.0
        assert cache.get_or_create("s1", object) is first  # within TTL
        clock[0] = 16.0  # idle > TTL since last use at t=5
        fresh = cache.get_or_create("s1", object)
        assert fresh is not first
        assert cache.stats.get("cache_evictions_ttl") == 1

    def test_lru_eviction(self):
        cache = DesignerStateCache(max_entries=2)
        cache.get_or_create("s1", object)
        cache.get_or_create("s2", object)
        cache.get_or_create("s1", object)  # s1 now most recent
        cache.get_or_create("s3", object)  # evicts s2 (least recent)
        assert cache.study_names() == ["s1", "s3"]
        assert cache.stats.get("cache_evictions_lru") == 1

    def test_invalidate(self):
        cache = DesignerStateCache()
        cache.get_or_create("s1", object)
        assert cache.invalidate("s1")
        assert not cache.invalidate("s1")  # already gone
        assert len(cache) == 0
        assert cache.stats.get("cache_invalidations") == 1

    def test_entry_holds_warm_params_and_ids(self):
        cache = DesignerStateCache()
        entry = cache.get_or_create("s1", object)
        entry.warm_params = {"amplitude": 1.0}
        entry.incorporated_trial_ids.update([1, 2])
        again = cache.get_or_create("s1", object)
        assert again.warm_params == {"amplitude": 1.0}
        assert again.incorporated_trial_ids == {1, 2}


class TestRequestCoalescer:
    def test_concurrent_callers_share_one_computation(self):
        coalescer = RequestCoalescer()
        calls = []
        release = threading.Event()
        results = []

        def compute():
            calls.append(1)
            release.wait(timeout=10)
            return {"v": 42}

        def run():
            results.append(coalescer.coalesce("k", compute, clone=dict))

        threads = [threading.Thread(target=run) for _ in range(5)]
        for t in threads:
            t.start()
        # Wait until the leader is inside compute and followers queued.
        deadline = time.time() + 10
        while len(coalescer.inflight_keys()) < 1 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # let followers reach the wait
        release.set()
        for t in threads:
            t.join(timeout=10)
        assert len(calls) == 1
        assert len(results) == 5
        assert all(r == {"v": 42} for r in results)
        # Followers got clones, not the shared object.
        assert len({id(r) for r in results}) == 5
        assert coalescer._stats.get("coalesced_requests") == 4

    def test_sequential_calls_do_not_share(self):
        coalescer = RequestCoalescer()
        calls = []
        coalescer.coalesce("k", lambda: calls.append(1))
        coalescer.coalesce("k", lambda: calls.append(1))
        assert len(calls) == 2

    def test_leader_error_propagates_to_followers(self):
        coalescer = RequestCoalescer()
        entered = threading.Event()
        release = threading.Event()
        errors = []

        def compute():
            entered.set()
            release.wait(timeout=10)
            raise RuntimeError("boom")

        def leader():
            try:
                coalescer.coalesce("k", compute)
            except RuntimeError as e:
                errors.append(str(e))

        def follower():
            entered.wait(timeout=10)
            try:
                coalescer.coalesce("k", compute)
            except RuntimeError as e:
                errors.append(str(e))

        t1 = threading.Thread(target=leader)
        t2 = threading.Thread(target=follower)
        t1.start()
        t2.start()
        entered.wait(timeout=10)
        time.sleep(0.1)
        release.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert errors == ["boom", "boom"]


class TestServingConfig:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SERVING_CACHE", "0")
        monkeypatch.setenv("VIZIER_SERVING_WARM_START", "0")
        cfg = ServingConfig.from_env()
        assert not cfg.designer_cache
        assert not cfg.warm_start
        assert cfg.coalescing

    def test_disabled(self):
        cfg = ServingConfig.disabled()
        assert not (cfg.designer_cache or cfg.warm_start or cfg.coalescing)


class TestBudgetPolicyValidation:
    def test_factory_rejects_bad_metadata_value_early(self):
        from vizier_tpu.service.policy_factory import DefaultPolicyFactory

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(
                name="o", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        problem.metadata.ns("gp_ucb_pe")["acquisition_budget_policy"] = "per_pik"
        with pytest.raises(ValueError, match="acquisition_budget_policy.*per_pik"):
            DefaultPolicyFactory()(problem, "DEFAULT", None, STUDY)


class _CountingPolicyFactory:
    """A deterministic slow policy: counts designer computations."""

    def __init__(self, delay_s: float = 1.0):
        self.computations = 0
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def __call__(self, problem, algorithm, supporter, study_name):
        outer = self

        class _P(policy_lib.Policy):
            def suggest(self, request):
                with outer._lock:
                    outer.computations += 1
                time.sleep(outer.delay_s)
                suggestions = [
                    vz.TrialSuggestion(parameters={"x0": 0.25, "x1": 0.75})
                    for _ in range(request.count)
                ]
                return policy_lib.SuggestDecision(suggestions=suggestions)

        return _P()


class TestSuggestCoalescing:
    def test_n_concurrent_suggests_one_computation(self):
        """Acceptance: N concurrent SuggestTrials -> exactly 1 designer
        computation; every caller receives a valid suggestion."""
        factory = _CountingPolicyFactory(delay_s=1.5)
        servicer, pythia = _make_service(policy_factory=factory)
        _create_study(servicer)

        n = 6
        ops = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait(timeout=10)
            ops[i] = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=STUDY, suggestion_count=1, client_id=f"client-{i}"
                )
            )

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        assert factory.computations == 1
        ids = set()
        for op in ops:
            assert op is not None and op.done and not op.error
            assert len(op.response.trials) == 1
            trial = op.response.trials[0]
            ids.add(trial.id)
            # Identical results: every caller got the shared computation's
            # suggested point (as its own distinct trial).
            values = {p.name: p.value.double_value for p in trial.parameters}
            assert values == {"x0": 0.25, "x1": 0.75}
        assert len(ids) == n  # distinct trials, one per caller
        snap = pythia.serving_stats()
        assert snap["coalesced_requests"] == n - 1
        assert snap["coalesced_computations"] == 1

    def test_coalescing_disabled_by_config(self):
        factory = _CountingPolicyFactory(delay_s=0.3)
        servicer, pythia = _make_service(
            policy_factory=factory,
            serving_config=ServingConfig(coalescing=False),
        )
        _create_study(servicer)
        n = 3
        ops = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait(timeout=10)
            ops[i] = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=STUDY, suggestion_count=1, client_id=f"client-{i}"
                )
            )

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for op in ops:
            assert op.done and not op.error
        assert factory.computations == n
        assert pythia.serving_stats()["coalesced_requests"] == 0


@pytest.fixture(scope="module")
def fast_gp_kwargs():
    """Keeps the real-GP serving tests' designers cheap on CPU."""
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib

    return dict(
        max_acquisition_evaluations=300,
        ard_restarts=2,
        ard_optimizer=lbfgs_lib.LbfgsOptimizer(maxiter=5),
        # These tests assert warm/cold counter plumbing at single-digit
        # trial counts; disable the convergence-protecting engage floor so
        # warm seeding starts on the second train as the assertions expect.
        warm_start_min_trials=0,
    )


class _FastGPFactory:
    """DEFAULT -> a cheap VizierGPUCBPEBandit, routed through serving."""

    def __init__(self, serving_runtime, designer_kwargs):
        self._serving = serving_runtime
        self._kwargs = designer_kwargs

    def __call__(self, problem, algorithm, supporter, study_name):
        from vizier_tpu.designers import gp_ucb_pe
        from vizier_tpu.serving.policy import CachedDesignerStatePolicy

        kwargs = dict(self._kwargs)
        cfg = self._serving.config
        kwargs["use_warm_start_ard"] = cfg.warm_start
        if cfg.warm_start:
            kwargs["warm_ard_restarts"] = cfg.warm_ard_restarts
        return CachedDesignerStatePolicy(
            supporter,
            lambda p, **kw: gp_ucb_pe.VizierGPUCBPEBandit(p, **kwargs),
            self._serving,
            study_name,
            use_seeding=True,
        )


def _gp_service(fast_gp_kwargs, serving_config=None):
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer, serving_config=serving_config)
    pythia._policy_factory = _FastGPFactory(pythia.serving_runtime, fast_gp_kwargs)
    servicer.set_pythia(pythia)
    return servicer, pythia


class TestServingWithRealDesigner:
    def test_warm_cold_counters_and_cache_reuse(self, fast_gp_kwargs):
        servicer, pythia = _gp_service(fast_gp_kwargs)
        _create_study(servicer)
        _complete_some_trials(servicer, 3)

        for step in range(3):
            op = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=STUDY, suggestion_count=1, client_id=f"w{step}"
                )
            )
            assert op.done and not op.error, op.error
            req = vizier_service_pb2.CompleteTrialRequest(
                name=op.response.trials[0].name
            )
            m = req.final_measurement.metrics.add()
            m.name, m.value = "obj", 0.5
            servicer.CompleteTrial(req)

        snap = pythia.serving_stats()
        # First suggest builds + cold-trains; later suggests hit the cached
        # designer and warm-train from its previous optimum.
        assert snap["cache_misses"] == 1
        assert snap["cache_hits"] == 2
        assert snap["cold_trains"] == 1
        assert snap["warm_trains"] == 2
        assert snap["cached_studies"] == 1
        # The cache entry mirrors the trained unconstrained ARD params.
        entry = pythia.serving_runtime.designer_cache.get_or_create(
            STUDY, lambda: None
        )
        assert entry.warm_params is not None

    def test_delete_study_invalidates_cache(self, fast_gp_kwargs):
        servicer, pythia = _gp_service(fast_gp_kwargs)
        _create_study(servicer)
        _complete_some_trials(servicer, 3)
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=STUDY, suggestion_count=1, client_id="w0"
            )
        )
        assert op.done and not op.error, op.error
        assert pythia.serving_stats()["cached_studies"] == 1
        servicer.DeleteStudy(vizier_service_pb2.DeleteStudyRequest(name=STUDY))
        snap = pythia.serving_stats()
        assert snap["cached_studies"] == 0
        assert snap["cache_invalidations"] == 1

    def test_warm_start_disabled_stays_cold(self, fast_gp_kwargs):
        servicer, pythia = _gp_service(
            fast_gp_kwargs, serving_config=ServingConfig(warm_start=False)
        )
        _create_study(servicer)
        _complete_some_trials(servicer, 3)
        for step in range(2):
            op = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=STUDY, suggestion_count=1, client_id=f"w{step}"
                )
            )
            assert op.done and not op.error, op.error
            req = vizier_service_pb2.CompleteTrialRequest(
                name=op.response.trials[0].name
            )
            m = req.final_measurement.metrics.add()
            m.name, m.value = "obj", 0.5
            servicer.CompleteTrial(req)
        snap = pythia.serving_stats()
        assert snap["warm_trains"] == 0
        assert snap["cold_trains"] == 2
