"""ServingStats vocabulary check under concurrency (satellite of PR 3).

The pre-observability implementation checked field membership against a
mutable dict outside the lock; the registry-backed version builds an
immutable field→counter map once, making the check race-free by
construction. This exercises the claim: concurrent valid increments stay
exact while concurrent *invalid* increments every single time raise
KeyError and never mint a counter.
"""

import threading

import pytest

from vizier_tpu.serving.stats import ServingStats


class TestVocabularyCheckUnderConcurrency:
    def test_concurrent_valid_and_invalid_increments(self):
        stats = ServingStats()
        n_threads, per_thread = 8, 300
        key_errors = []
        other_errors = []
        barrier = threading.Barrier(n_threads * 2)

        def valid_worker():
            barrier.wait(timeout=10)
            for _ in range(per_thread):
                stats.increment("cache_hits")

        def invalid_worker():
            barrier.wait(timeout=10)
            for _ in range(per_thread):
                try:
                    stats.increment("cache_hit")  # singular: a typo
                except KeyError as e:
                    key_errors.append(e)
                except Exception as e:  # pragma: no cover - the bug
                    other_errors.append(e)

        threads = [threading.Thread(target=valid_worker) for _ in range(n_threads)]
        threads += [threading.Thread(target=invalid_worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        assert not other_errors
        # Every invalid increment was rejected; none slipped through a race.
        assert len(key_errors) == n_threads * per_thread
        # Valid increments were neither lost nor double-counted.
        assert stats.get("cache_hits") == n_threads * per_thread
        # No counter was minted for the typo.
        snap = stats.snapshot()
        assert "cache_hit" not in snap
        assert set(snap) == set(ServingStats.FIELDS)

    def test_unknown_field_message_unchanged(self):
        with pytest.raises(KeyError, match="Unknown serving counter"):
            ServingStats().increment("nope")

    def test_reset_and_registry_exposure(self):
        stats = ServingStats()
        stats.increment("fallbacks", 4)
        assert "vizier_serving_fallbacks_total 4" in (
            stats.registry.prometheus_text()
        )
        stats.reset()
        assert stats.get("fallbacks") == 0
