"""Serving-path batching: config knobs, executor routing, compile cache."""

import threading

import jax
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.serving import ServingConfig, ServingRuntime
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_service
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

_FAST_GP_KWARGS = None


def _fast_gp_kwargs():
    global _FAST_GP_KWARGS
    if _FAST_GP_KWARGS is None:
        from vizier_tpu.optimizers import lbfgs as lbfgs_lib

        _FAST_GP_KWARGS = dict(
            max_acquisition_evaluations=200,
            ard_restarts=2,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=10),
            # Few-trial integration runs: keep warm seeding engaged below
            # the production floor so warm-path wiring is exercised.
            warm_start_min_trials=0,
        )
    return _FAST_GP_KWARGS


class _FastGPFactory:
    """DEFAULT -> a cheap VizierGPUCBPEBandit routed through serving."""

    def __init__(self, serving_runtime):
        self._serving = serving_runtime

    def _gp_designer_kwargs(self):
        """Same shape as DefaultPolicyFactory's hook (PythiaServicer.prewarm
        reads it), but with the cheap test budgets folded in."""
        kwargs = dict(_fast_gp_kwargs())
        cfg = self._serving.config
        kwargs["use_warm_start_ard"] = cfg.warm_start
        if cfg.warm_start:
            kwargs["warm_ard_restarts"] = cfg.warm_ard_restarts
        return kwargs

    def __call__(self, problem, algorithm, supporter, study_name):
        from vizier_tpu.designers import gp_ucb_pe
        from vizier_tpu.serving.policy import CachedDesignerStatePolicy

        kwargs = self._gp_designer_kwargs()
        return CachedDesignerStatePolicy(
            supporter,
            lambda p, **kw: gp_ucb_pe.VizierGPUCBPEBandit(p, **kwargs),
            self._serving,
            study_name,
            use_seeding=True,
        )


def _study_config():
    config = vz.StudyConfig(algorithm="DEFAULT")
    for d in range(2):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _gp_service(serving_config=None):
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer, serving_config=serving_config)
    pythia._policy_factory = _FastGPFactory(pythia.serving_runtime)
    servicer.set_pythia(pythia)
    return servicer, pythia


def _create_study_with_trials(servicer, name, n=3):
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o", study=pc.study_to_proto(_study_config(), name)
        )
    )
    for i in range(n):
        created = servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(parent=name, trial=study_pb2.Trial())
        )
        req = vizier_service_pb2.CompleteTrialRequest(name=created.name)
        m = req.final_measurement.metrics.add()
        m.name, m.value = "obj", 0.07 * (i + 1)
        servicer.CompleteTrial(req)


class TestConfigKnobs:
    def test_defaults_on_and_env_off_switch(self, monkeypatch):
        assert ServingConfig().batching is True
        monkeypatch.setenv("VIZIER_BATCHING", "0")
        assert ServingConfig.from_env().batching is False
        monkeypatch.setenv("VIZIER_BATCHING", "1")
        monkeypatch.setenv("VIZIER_BATCH_MAX_SIZE", "16")
        monkeypatch.setenv("VIZIER_BATCH_MAX_WAIT_MS", "2.5")
        cfg = ServingConfig.from_env()
        assert cfg.batching and cfg.batch_max_size == 16
        assert cfg.batch_max_wait_ms == pytest.approx(2.5)
        assert ServingConfig.disabled().batching is False

    def test_compile_cache_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("VIZIER_COMPILE_CACHE_DIR", str(tmp_path))
        assert ServingConfig.from_env().compilation_cache_dir == str(tmp_path)
        monkeypatch.delenv("VIZIER_COMPILE_CACHE_DIR")
        assert ServingConfig.from_env().compilation_cache_dir is None

    def test_batching_off_means_no_executor(self):
        runtime = ServingRuntime(ServingConfig(batching=False))
        assert runtime.batch_executor is None
        runtime.shutdown()  # no-op, must not raise

    def test_batching_on_builds_executor(self):
        runtime = ServingRuntime(ServingConfig(batch_max_size=4))
        try:
            assert runtime.batch_executor is not None
            assert runtime.batch_executor.max_batch_size == 4
        finally:
            runtime.shutdown()


class TestCompilationCacheWiring:
    def test_runtime_points_jax_at_the_cache_dir(self, tmp_path):
        before = jax.config.jax_compilation_cache_dir
        try:
            runtime = ServingRuntime(
                ServingConfig(
                    batching=False, compilation_cache_dir=str(tmp_path)
                )
            )
            assert runtime.compilation_cache_active
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        finally:
            jax.config.update("jax_compilation_cache_dir", before)

    def test_no_dir_leaves_jax_alone(self):
        before = jax.config.jax_compilation_cache_dir
        runtime = ServingRuntime(ServingConfig(batching=False))
        assert not runtime.compilation_cache_active
        assert jax.config.jax_compilation_cache_dir == before


class TestServicePathBatching:
    def test_concurrent_studies_share_one_batched_dispatch(self):
        servicer, pythia = _gp_service(
            ServingConfig(batch_max_size=2, batch_max_wait_ms=5000.0)
        )
        studies = ["owners/o/studies/a", "owners/o/studies/b"]
        for s in studies:
            _create_study_with_trials(servicer, s)

        ops, errors = {}, {}

        def run(study, wid):
            try:
                ops[study] = servicer.SuggestTrials(
                    vizier_service_pb2.SuggestTrialsRequest(
                        parent=study, suggestion_count=1, client_id=wid
                    )
                )
            except BaseException as e:  # noqa: BLE001
                errors[study] = e

        threads = [
            threading.Thread(target=run, args=(s, f"w{i}"))
            for i, s in enumerate(studies)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        for s in studies:
            assert ops[s].done and not ops[s].error, ops[s].error
            assert len(ops[s].response.trials) == 1
        snap = pythia.serving_stats()
        assert snap["batch_flushes"] >= 1
        assert snap["batched_suggests"] == 2
        pythia.shutdown()

    def test_batching_off_restores_per_study_path(self):
        servicer, pythia = _gp_service(ServingConfig(batching=False))
        study = "owners/o/studies/solo"
        _create_study_with_trials(servicer, study)
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=study, suggestion_count=1, client_id="w0"
            )
        )
        assert op.done and not op.error, op.error
        snap = pythia.serving_stats()
        assert snap["batch_flushes"] == 0
        assert snap["batched_suggests"] == 0

    def test_single_study_flushes_alone_via_timeout(self):
        servicer, pythia = _gp_service(
            ServingConfig(batch_max_size=8, batch_max_wait_ms=5.0)
        )
        study = "owners/o/studies/lonely"
        _create_study_with_trials(servicer, study)
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=study, suggestion_count=1, client_id="w0"
            )
        )
        assert op.done and not op.error, op.error
        snap = pythia.serving_stats()
        # Singleton flush -> the sequential per-study path (bit-identical
        # to batching off), accounted as a flush but not a batched slot.
        assert snap["batch_flushes"] == 1
        assert snap["batched_suggests"] == 0
        pythia.shutdown()


class TestPrewarmAPI:
    def test_servicer_prewarm_compiles_bucket_grid(self):
        servicer, pythia = _gp_service(
            ServingConfig(batch_max_size=2, batching_prewarm_max_trials=8)
        )
        report = pythia.prewarm(_study_config())
        assert report, "expected at least one prewarmed bucket"
        assert {r["batch_size"] for r in report} == {1, 2}
        assert all(r["status"] == "ok" for r in report)
        pythia.shutdown()

    def test_prewarm_noop_when_batching_off(self):
        servicer, pythia = _gp_service(ServingConfig(batching=False))
        assert pythia.prewarm(_study_config()) == []

    def test_auto_prewarm_flag_spawns_once_per_shape(self):
        runtime = ServingRuntime(
            ServingConfig(
                batching_prewarm=True,
                batching_prewarm_max_trials=8,
                # max size 1 keeps the background compile tiny: prewarm's
                # batch-size grid {1, max} degenerates to {1, 1}.
                batch_max_size=1,
            )
        )
        try:
            from vizier_tpu.designers import gp_ucb_pe

            problem = _study_config().to_problem()
            factory = lambda p, **kw: gp_ucb_pe.VizierGPUCBPEBandit(  # noqa: E731
                p, **_fast_gp_kwargs()
            )
            assert runtime.maybe_prewarm_batching_async(problem, factory)
            # Same search-space shape: already queued, no second thread.
            assert not runtime.maybe_prewarm_batching_async(problem, factory)
        finally:
            runtime.shutdown()
