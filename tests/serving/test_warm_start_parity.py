"""Warm-started ARD must not change regret: rank-sum parity at 5 seeds.

A cheap CI-scale version of the full A/B in ``tools/warm_start_ab.py``
(WARM_START_AB.json): the warm arm trains with 1 warm-seeded restart after
the first suggest, the cold arm always runs the full restart budget from
random inits, on the same shifted-sphere instances. Deterministic given
the pinned seeds, so the gate is stable.
"""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.experimenters import experimenter_factory
from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib

SEEDS = (1, 2, 3, 4, 5)
DIM = 4
TRIALS = 12
BATCH = 4


def _rank_sum_p(a, b) -> float:
    """Two-sided Mann-Whitney p (normal approximation), H0: same dist."""
    from scipy import stats

    a, b = np.asarray(a, float), np.asarray(b, float)
    ranks = stats.rankdata(np.concatenate([a, b]))
    n, m = len(a), len(b)
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    mu, sigma = n * m / 2.0, np.sqrt(n * m * (n + m + 1) / 12.0)
    return float(2.0 * (1.0 - stats.norm.cdf(abs(u - mu) / max(sigma, 1e-9))))


def _run_arm(seed: int, warm: bool) -> float:
    exp = experimenter_factory.shifted_bbob_instance("Sphere", seed, dim=DIM)
    designer = VizierGPUCBPEBandit(
        exp.problem_statement(),
        rng_seed=seed,
        num_seed_trials=4,
        max_acquisition_evaluations=500,
        ard_restarts=2,
        ard_optimizer=lbfgs_lib.LbfgsOptimizer(maxiter=8),
        use_warm_start_ard=warm,
        warm_ard_restarts=1 if warm else None,
        # The parity claim is about the warm MECHANISM; at this CI scale
        # (12 trials) the engage floor would leave the warm arm cold and
        # make the comparison vacuous.
        warm_start_min_trials=0,
    )
    best, tid = np.inf, 0
    while tid < TRIALS:
        batch = [
            s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(BATCH))
        ]
        tid += len(batch)
        exp.evaluate(batch)
        designer.update(core_lib.CompletedTrials(batch))
        for t in batch:
            best = min(best, t.final_measurement.metrics["bbob_eval"].value)
    return best


def test_warm_vs_cold_regret_parity():
    warm_finals = [_run_arm(s, warm=True) for s in SEEDS]
    cold_finals = [_run_arm(s, warm=False) for s in SEEDS]
    p = _rank_sum_p(warm_finals, cold_finals)
    # Parity: the warm-started arm's final regrets must be statistically
    # indistinguishable from the cold arm's (deterministic given SEEDS).
    assert p > 0.05, (
        f"warm={warm_finals} cold={cold_finals} rank-sum p={p:.4f}"
    )
