"""Multi-tenant overload protection: admission, shedding, degradation.

Pins the PR's hard guarantees: a shed is typed ``TRANSIENT:
RESOURCE_EXHAUSTED`` with a retry-after hint the retry layer honors, a
shed NEVER counts against the study's circuit breaker and never reaches a
designer, degraded mode serves stamped quasi-random to low-priority
tenants only, expired-deadline requests never reach a designer
computation, and ``VIZIER_ADMISSION=0`` builds no controller at all (the
bit-identical pre-admission path).
"""

import sys
import unittest.mock

import pytest

sys.path.insert(0, "tests")

from reliability import harness  # noqa: E402

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.reliability import breaker as breaker_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.reliability import fallback as fallback_lib
from vizier_tpu.reliability import retry as retry_lib
from vizier_tpu.serving import admission as adm
from vizier_tpu.serving import runtime as runtime_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2


class CountingPolicyFactory:
    """Counts designer computations; the no-compute assertions' probe."""

    def __init__(self):
        self.computations = 0

    def __call__(self, problem, algorithm, supporter, study_name):
        outer = self

        class _P(policy_lib.Policy):
            def suggest(self, request):
                outer.computations += 1
                return policy_lib.SuggestDecision(
                    suggestions=[
                        vz.TrialSuggestion(parameters={"x": 0.5, "y": 0.0})
                        for _ in range(request.count)
                    ]
                )

        return _P()


def make_admission_stack(admission_config, factory=None):
    factory = factory or CountingPolicyFactory()
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(
        servicer, factory, admission_config=admission_config
    )
    servicer.set_pythia(pythia)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o",
            study=pc.study_to_proto(harness.study_config(), harness.STUDY),
        )
    )
    return servicer, pythia, factory


def suggest_op(servicer, client_id="c1", deadline_secs=0.0):
    return servicer.SuggestTrials(
        vizier_service_pb2.SuggestTrialsRequest(
            parent=harness.STUDY,
            suggestion_count=1,
            client_id=client_id,
            deadline_secs=deadline_secs,
        )
    )


class TestConfig:
    def test_off_by_default(self):
        assert not adm.AdmissionConfig.from_env().enabled
        runtime = runtime_lib.ServingRuntime()
        assert runtime.admission is None
        assert runtime.admission_snapshot() == {"enabled": False}
        runtime.shutdown()

    def test_env_arming(self):
        with unittest.mock.patch.dict(
            "os.environ",
            {
                "VIZIER_ADMISSION": "1",
                "VIZIER_ADMISSION_MAX_INFLIGHT": "5",
                "VIZIER_ADMISSION_WEIGHTS": "prod:8,dev:0.5,junk,bad:x",
            },
        ):
            config = adm.AdmissionConfig.from_env()
        assert config.enabled
        assert config.max_inflight == 5
        assert config.weight("prod") == 8.0
        assert config.weight("dev") == 0.5
        assert config.weight("unlisted") == 1.0
        assert config.low_priority("dev")
        assert not config.low_priority("prod")

    def test_tenant_of(self):
        assert adm.tenant_of("owners/prod/studies/s") == "prod"
        assert adm.tenant_of("owners/a/studies/s/trials/3") == "a"
        assert adm.tenant_of("not-a-resource") == adm.DEFAULT_TENANT
        assert adm.tenant_of("owners/") == adm.DEFAULT_TENANT


class TestController:
    def _controller(self, **kw):
        self.clock = [0.0]
        defaults = dict(
            enabled=True, max_inflight=4, tenant_inflight=2, window_s=5.0
        )
        defaults.update(kw)
        config = adm.AdmissionConfig(**defaults)
        return adm.AdmissionController(
            config, time_fn=lambda: self.clock[0]
        )

    def test_tenant_and_total_bounds(self):
        ctl = self._controller()
        held = [ctl.decide("a"), ctl.decide("a")]
        shed = ctl.decide("a")
        assert shed.outcome == adm.SHED
        assert shed.reason == adm.REASON_TENANT
        held.append(ctl.decide("b"))
        held.append(ctl.decide("b"))
        total = ctl.decide("c")
        assert total.outcome == adm.SHED
        assert total.reason == adm.REASON_TOTAL
        for d in held:
            ctl.release(d)
        assert ctl.inflight() == {}
        assert ctl.decide("c").admitted

    def test_shed_error_is_typed_with_retry_after(self):
        ctl = self._controller(retry_after_ms=125.0)
        hold = ctl.decide("a")
        hold2 = ctl.decide("a")
        shed = ctl.decide("a")
        err = shed.error()
        assert errors_lib.is_transient_exception(err)
        assert errors_lib.is_resource_exhausted(str(err))
        assert errors_lib.retry_after_secs(err) == pytest.approx(0.125)
        # The marker survives the op-error stringification round trip.
        text = errors_lib.format_op_error(err)
        assert errors_lib.has_transient_marker(text)
        assert errors_lib.retry_after_secs(text) == pytest.approx(0.125)
        ctl.release(hold)
        ctl.release(hold2)

    def test_deadline_infeasible_shed(self):
        config = adm.AdmissionConfig(
            enabled=True, max_inflight=8, tenant_inflight=8
        )
        ctl = adm.AdmissionController(
            config,
            compute_p50_fn=lambda: 2.0,  # 2 s computes
            queue_depth_fn=lambda: 16,  # 2 flushes queued ahead
        )
        # Estimate = 2s * (1 + 16/8) = 6s > 1s remaining -> shed.
        shed = ctl.decide("a", deadline_secs=1.0)
        assert shed.outcome == adm.SHED
        assert shed.reason == adm.REASON_DEADLINE
        # Plenty of budget -> admit.
        ok = ctl.decide("a", deadline_secs=30.0)
        assert ok.admitted
        ctl.release(ok)
        # No deadline on the wire -> never deadline-shed.
        ok2 = ctl.decide("a", deadline_secs=0.0)
        assert ok2.admitted
        ctl.release(ok2)

    def test_deadline_shed_disabled_without_latency_data(self):
        ctl = adm.AdmissionController(
            adm.AdmissionConfig(enabled=True),
            compute_p50_fn=lambda: None,
            queue_depth_fn=lambda: 1000,
        )
        decision = ctl.decide("a", deadline_secs=0.001)
        assert decision.admitted  # conservative: no data, no deadline shed
        ctl.release(decision)

    def test_state_machine_escalates_and_recovers_hysteretically(self):
        ctl = self._controller(
            max_inflight=2,
            tenant_inflight=1,
            weights=(("low", 0.5),),
            degrade_rate=0.5,
            recover_rate=0.1,
            min_decisions=4,
            window_s=5.0,
        )
        assert ctl.state == adm.HEALTHY
        hold = ctl.decide("low")
        assert ctl.decide("low").outcome == adm.SHED
        assert ctl.state == adm.SHEDDING
        for _ in range(10):
            ctl.decide("low")
        assert ctl.state == adm.DEGRADED
        # Low-priority tenant degrades, default-weight tenant computes.
        assert ctl.decide("low").outcome == adm.DEGRADE
        other = ctl.decide("other")
        assert other.admitted
        ctl.release(other)
        ctl.release(hold)
        # Recovery needs a FULL calm window: not immediately...
        self.clock[0] += 2.0
        d = ctl.decide("other")
        ctl.release(d)
        assert ctl.state == adm.DEGRADED
        # ... but after window_s of calm it steps down one level at a time.
        self.clock[0] += 6.0
        d = ctl.decide("other")
        ctl.release(d)
        assert ctl.state == adm.SHEDDING
        self.clock[0] += 6.0
        d = ctl.decide("other")
        ctl.release(d)
        assert ctl.state == adm.HEALTHY
        transitions = ctl.snapshot()["transitions"]
        assert [t["to"] for t in transitions] == [
            adm.SHEDDING, adm.DEGRADED, adm.SHEDDING, adm.HEALTHY,
        ]

    def test_snapshot_accounting(self):
        ctl = self._controller()
        a = ctl.decide("a")
        b = ctl.decide("b")
        hold = ctl.decide("a")
        ctl.decide("a")  # tenant shed
        snap = ctl.snapshot()
        assert snap["state"] == adm.SHEDDING
        assert snap["inflight"] == {"a": 2, "b": 1}
        assert snap["admits_by_tenant"] == {"a": 2, "b": 1}
        assert snap["sheds_by_tenant"] == {"a": {adm.REASON_TENANT: 1}}
        assert snap["total_sheds"] == 1
        for d in (a, b, hold):
            ctl.release(d)

    def test_in_flight_scope_sets_tenant_contextvar(self):
        ctl = self._controller()
        decision = ctl.decide("a")
        assert adm.current_tenant() is None
        with ctl.in_flight(decision):
            assert adm.current_tenant() == "a"
        assert adm.current_tenant() is None
        assert ctl.inflight() == {}


class TestPythiaBoundary:
    def test_shed_is_typed_and_never_trips_breaker(self):
        config = adm.AdmissionConfig(
            enabled=True, max_inflight=1, tenant_inflight=1
        )
        servicer, pythia, factory = make_admission_stack(config)
        runtime = pythia.serving_runtime
        hold = runtime.admission.decide("o")
        assert hold.admitted
        for _ in range(5):
            op = suggest_op(servicer)
            assert op.done
            assert "RESOURCE_EXHAUSTED" in op.error
            assert errors_lib.has_transient_marker(op.error)
            assert errors_lib.retry_after_secs(op.error) is not None
        # No designer ran, no breaker state moved, no fallback stamped.
        assert factory.computations == 0
        snap = runtime.snapshot()
        assert snap["admission_sheds"] == 5
        assert snap["designer_failures"] == 0
        assert snap["breaker_open_transitions"] == 0
        assert snap["breaker_short_circuits"] == 0
        assert snap["fallbacks"] == 0
        assert runtime.breakers.get(harness.STUDY).state == breaker_lib.CLOSED
        runtime.admission.release(hold)
        op = suggest_op(servicer)
        assert not op.error
        assert factory.computations == 1
        pythia.shutdown()

    def test_degraded_serves_stamped_quasi_random_to_low_priority_only(self):
        config = adm.AdmissionConfig(
            enabled=True,
            max_inflight=4,
            tenant_inflight=4,
            weights=(("o", 0.5),),
            degraded_floor=1.0,
            min_decisions=2,
            degrade_rate=0.3,
        )
        servicer, pythia, factory = make_admission_stack(config)
        ctl = pythia.serving_runtime.admission
        holds = [ctl.decide("x") for _ in range(4)]
        for _ in range(10):
            ctl.decide("x")
        assert ctl.state == adm.DEGRADED
        op = suggest_op(servicer)
        assert not op.error
        assert factory.computations == 0  # no GP compute burned
        trial = pc.trial_from_proto(op.response.trials[0])
        assert fallback_lib.is_fallback_suggestion(trial.metadata)
        assert (
            trial.metadata.ns(adm.ADMISSION_NAMESPACE).get(adm.ADMISSION_KEY)
            == adm.ADMISSION_VALUE
        )
        snap = pythia.serving_runtime.snapshot()
        assert snap["admission_degraded"] == 1
        for h in holds:
            ctl.release(h)
        pythia.shutdown()

    def test_admission_off_builds_no_controller(self):
        servicer, pythia, factory = make_admission_stack(
            adm.AdmissionConfig.disabled()
        )
        assert pythia.serving_runtime.admission is None
        op = suggest_op(servicer)
        assert not op.error
        assert factory.computations == 1
        snap = pythia.serving_runtime.snapshot()
        assert snap["admission_sheds"] == 0
        pythia.shutdown()


class TestExpiredDeadline:
    def test_ingress_short_circuit_no_compute(self):
        factory = CountingPolicyFactory()
        servicer, pythia, _ = harness.make_stack(factory)
        op = suggest_op(servicer, deadline_secs=-2.0)
        assert op.done
        assert "DEADLINE_EXCEEDED" in op.error
        assert errors_lib.has_transient_marker(op.error)
        assert factory.computations == 0
        # Nothing persisted: the synthetic op is not in the datastore and
        # consumed no operation number.
        assert not servicer.datastore.list_suggestion_operations(
            harness.STUDY, "c1"
        )
        stats = pythia.serving_stats()
        assert stats["deadline_exceeded"] == 1
        pythia.shutdown()

    def test_pythia_expired_wire_budget_never_reaches_designer(self):
        from vizier_tpu.service.protos import pythia_service_pb2

        factory = CountingPolicyFactory()
        servicer, pythia, _ = harness.make_stack(factory)
        study = servicer.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=harness.STUDY)
        )
        preq = pythia_service_pb2.PythiaSuggestRequest(
            count=1,
            algorithm=study.study_spec.algorithm,
            study_name=harness.STUDY,
            deadline_secs=-0.25,
        )
        preq.study_descriptor.config.CopyFrom(study.study_spec)
        preq.study_descriptor.guid = harness.STUDY
        response = pythia.Suggest(preq)
        assert "DEADLINE_EXCEEDED" in response.error
        assert factory.computations == 0
        pythia.shutdown()

    def test_client_sends_expired_marker_when_budget_gone(self):
        captured = {}

        class CapturingStub:
            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                method = getattr(self._inner, name)
                if name == "SuggestTrials":
                    def wrapped(request):
                        captured["deadline_secs"] = request.deadline_secs
                        return method(request)

                    return wrapped
                return method

        factory = CountingPolicyFactory()
        servicer, pythia, _ = harness.make_stack(factory)
        from vizier_tpu.service import vizier_client as client_lib

        client = client_lib.VizierClient(
            CapturingStub(servicer), harness.STUDY, "c1"
        )
        with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
            client.get_suggestions(1, deadline_secs=-1.0)
        assert captured["deadline_secs"] < 0
        assert factory.computations == 0
        pythia.shutdown()


class TestRetryAfterHonored:
    def test_retry_policy_floors_backoff_at_hint(self):
        slept = []
        policy = retry_lib.RetryPolicy(
            max_attempts=3,
            base_delay_secs=1e-4,
            max_delay_secs=2e-4,
            sleep_fn=slept.append,
        )
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise adm.shed_error("t", adm.REASON_TOTAL, 150.0)
            return "served"

        assert policy.call(flaky) == "served"
        assert len(slept) == 2
        assert all(delay >= 0.15 for delay in slept)

    def test_plain_transient_keeps_jittered_schedule(self):
        slept = []
        policy = retry_lib.RetryPolicy(
            max_attempts=2,
            base_delay_secs=1e-4,
            max_delay_secs=2e-4,
            sleep_fn=slept.append,
        )
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise errors_lib.TransientError("TRANSIENT: plain")
            return "ok"

        assert policy.call(flaky) == "ok"
        assert slept and slept[0] <= 2e-4

    def test_client_shed_retries_do_not_burn_attempt_budget(self):
        """A shed with retry-after is backpressure: the client keeps
        honoring the pacing hint past its fixed attempts and succeeds once
        the gate reopens."""
        from vizier_tpu.reliability import config as rcfg
        from vizier_tpu.service import vizier_client as client_lib

        config = adm.AdmissionConfig(
            enabled=True, max_inflight=1, tenant_inflight=1,
            retry_after_ms=1.0,
        )
        servicer, pythia, factory = make_admission_stack(config)
        ctl = pythia.serving_runtime.admission
        hold = ctl.decide("o")
        releases = {"left": 8}
        original = ctl.decide

        def releasing_decide(*args, **kwargs):
            # Reopen the gate only after MORE sheds than the client's
            # fixed attempt budget (3) would survive.
            if releases["left"] > 0:
                releases["left"] -= 1
                if releases["left"] == 0:
                    ctl.release(hold)
            return original(*args, **kwargs)

        ctl.decide = releasing_decide
        client = client_lib.VizierClient(
            servicer, harness.STUDY, "c1",
            reliability=rcfg.ReliabilityConfig(
                retry_max_attempts=3,
                retry_base_delay_secs=1e-4,
                retry_max_delay_secs=1e-3,
            ),
        )
        trials = client.get_suggestions(1)
        assert len(trials) == 1
        assert factory.computations == 1
        pythia.shutdown()
