"""Service throughput at the reference's stress configs, over real gRPC.

Usage: python tools/service_throughput.py [--out SERVICE_THROUGHPUT.json]
       [--side repo|reference|both]
       [--replicas N [--replica-mode inprocess|subprocess]]

Reference ``performance_test.py:44-89`` runs clients×trials configs
{1×10, 2×10, 10×10, 50×5, 100×5} on RANDOM_SEARCH over a 2-D space and
logs wall time only. This tool runs the same topology — one shared study
per config, one thread per client, each doing its own suggest→complete
loop over a real localhost gRPC channel — against BOTH this repo's
``DefaultVizierServer`` and the reference's (the runnable copy that
``tools/build_reference_copy.sh`` puts at /tmp/refvizier, RAM datastore),
and writes a two-column JSON report with wall time and trials/sec.

The reference side runs in a subprocess so its ``vizier`` package import
and proto registrations stay isolated; per-worker clients are created
BEFORE the timed section on both sides, so the clock covers only the
suggest→complete loops.

``--replicas N`` additionally runs the sharded-tier A/B (a "distributed"
section in the JSON; the single-replica report above is byte-compatible
with the original schema): the SAME multi-study workload measured against
(a) one ``DefaultVizierServer`` over localhost gRPC — today's deployment —
and (b) N replicas behind the study-affinity router
(``vizier_tpu.distributed``). ``--replica-mode inprocess`` (default) uses
``ReplicaManager`` — clients route straight to the owning replica's
servicer with no central frontend hop, replicas share one Pythia fleet;
``subprocess`` starts N ``replica_main`` gRPC server processes and routes
over real channels (the multi-host shape; on a single-core container it
cannot beat one server — the processes timeshare the core).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

CONFIGS = ((1, 10), (2, 10), (10, 10), (50, 5), (100, 5))
# The published stress set above is short enough that channel setup and
# first-RPC costs dominate the small configs; the steady-state config
# measures sustained per-trial service latency.
STEADY_STATE = (1, 200)
REFCOPY = "/tmp/refvizier"


REPEATS = 3  # best-of-N per config: throughput = least-interference run
# The distributed A/B arms run short (~0.1 s for the tier), so scheduler
# noise on a small host dominates single runs; more best-of repeats per
# arm, same least-interference methodology.
DIST_REPEATS = 5


def run_repo() -> list:
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    from vizier_tpu.service import clients as clients_lib
    from vizier_tpu.service import vizier_server
    from vizier_tpu.testing import stress

    server = vizier_server.DefaultVizierServer(host="localhost")
    clients_lib.environment_variables.server_endpoint = server.endpoint
    rows = []
    try:
        # Warmup: channel connect + proto/codec first-call costs land on a
        # throwaway study, so the timed configs measure the service.
        warm = clients_lib.Study.from_study_config(
            stress.stress_study_config(), owner="perf", study_id="warmup"
        )
        stress.run_stress_round(warm, 1, 3)
        for num_clients, trials_each in CONFIGS + (STEADY_STATE,):
            total = num_clients * trials_each
            best_wall = float("inf")
            for rep in range(REPEATS):
                study = clients_lib.Study.from_study_config(
                    stress.stress_study_config(),
                    owner="perf",
                    study_id=f"tp-{num_clients}x{trials_each}-r{rep}",
                )
                wall, completed, _ = stress.run_stress_round(
                    study, num_clients, trials_each
                )
                assert completed == total, (completed, total)
                best_wall = min(best_wall, wall)
            row = {
                "side": "repo",
                "clients": num_clients,
                "trials_each": trials_each,
                "total_trials": total,
                "completed": total,
                "wall_s": round(best_wall, 3),
                "trials_per_s": round(total / best_wall, 1),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT
        server.stop(0)
    return rows


def _ensure_refcopy() -> None:
    # The shims this diff relies on are part of the build; an isdir check
    # would accept a stale copy from an older build script.
    marker = os.path.join(
        REFCOPY, "vizier/_src/service/vizier_service_pb2_grpc.py"
    )
    if not os.path.exists(marker):
        subprocess.run(
            ["bash", os.path.join(_REPO_ROOT, "tools/build_reference_copy.sh")],
            check=True,
        )


def run_reference() -> list:
    """Identical topology against the reference's DefaultVizierServer."""
    import concurrent.futures as cf

    # Defensive: direct `--side reference` invocations must not initialize
    # the axon backend (a dead TPU tunnel hangs jax init on this image).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _ensure_refcopy()
    sys.path.insert(0, REFCOPY)
    from vizier._src.service import vizier_client, vizier_server
    from vizier.service import pyvizier as svz

    server = vizier_server.DefaultVizierServer(database_url=None)
    vizier_client.environment_variables.server_endpoint = server.endpoint

    def study_config():
        sc = svz.StudyConfig()
        sc.search_space.root.add_float_param("x", 0.0, 1.0)
        sc.search_space.root.add_float_param("y", 0.0, 1.0)
        sc.metric_information.append(
            svz.MetricInformation(
                name="obj", goal=svz.ObjectiveMetricGoal.MINIMIZE
            )
        )
        sc.algorithm = svz.Algorithm.RANDOM_SEARCH
        return sc

    # Warmup mirrors the repo side: throwaway study absorbs first-RPC costs.
    warm = vizier_client.create_or_load_study(
        owner_id="perf",
        study_id="warmup",
        study_config=study_config(),
        client_id="w",
    )
    for _ in range(3):
        (t,) = warm.get_suggestions(suggestion_count=1)
        warm.complete_trial(
            t.id, svz.Measurement(metrics={"obj": 0.0})
        )

    rows = []
    for num_clients, trials_each in CONFIGS + (STEADY_STATE,):
        total = num_clients * trials_each
        best_wall = float("inf")
        for rep in range(REPEATS):
            study_id = f"tp-{num_clients}x{trials_each}-r{rep}"
            # Per-worker clients before the clock, mirroring the repo side
            # (where the study client exists before run_stress_round).
            clients = [
                vizier_client.create_or_load_study(
                    owner_id="perf",
                    study_id=study_id,
                    study_config=study_config(),
                    client_id=f"worker_{i}",
                )
                for i in range(num_clients)
            ]

            def worker(client):
                for _ in range(trials_each):
                    (trial,) = client.get_suggestions(suggestion_count=1)
                    x = trial.parameters["x"].value
                    y = trial.parameters["y"].value
                    m = svz.Measurement(
                        metrics={"obj": (x - 0.3) ** 2 + (y - 0.7) ** 2}
                    )
                    client.complete_trial(trial.id, m)

            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=num_clients) as pool:
                list(pool.map(worker, clients))
            wall = time.perf_counter() - t0
            completed = sum(
                1
                for t in clients[0].list_trials()
                if t.status == svz.TrialStatus.COMPLETED
            )
            assert completed == total, (completed, total)
            best_wall = min(best_wall, wall)
        row = {
            "side": "reference",
            "clients": num_clients,
            "trials_each": trials_each,
            "total_trials": total,
            "completed": total,
            "wall_s": round(best_wall, 3),
            "trials_per_s": round(total / best_wall, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


# -- sharded-tier A/B --------------------------------------------------------

# The distributed workload: study-affinity routing only pays off with many
# studies, so the A/B drives STUDIES concurrent studies with
# CLIENTS_PER_STUDY worker threads each, identical on both arms.
DIST_STUDIES = 8
DIST_CLIENTS_PER_STUDY = 2
DIST_TRIALS_EACH = 25


def _dist_workload(stub, tag: str) -> dict:
    """Runs the multi-study workload against ``stub``; returns the row."""
    import concurrent.futures as cf

    from vizier_tpu import pyvizier as vz
    from vizier_tpu.service import proto_converters as pc
    from vizier_tpu.service import vizier_client
    from vizier_tpu.service.protos import vizier_service_pb2
    from vizier_tpu.testing import stress

    study_names, clients = [], []
    for s in range(DIST_STUDIES):
        name = f"owners/perf/studies/{tag}-s{s}"
        stub.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(
                parent="owners/perf",
                study=pc.study_to_proto(stress.stress_study_config(), name),
            )
        )
        study_names.append(name)
        for w in range(DIST_CLIENTS_PER_STUDY):
            clients.append(vizier_client.VizierClient(stub, name, f"worker_{w}"))

    def worker(client):
        for _ in range(DIST_TRIALS_EACH):
            (trial,) = client.get_suggestions(1)
            x = trial.parameters["x"].value
            y = trial.parameters["y"].value
            client.complete_trial(
                trial.id,
                vz.Measurement(metrics={"obj": (x - 0.3) ** 2 + (y - 0.7) ** 2}),
            )

    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(max_workers=len(clients)) as pool:
        list(pool.map(worker, clients))
    wall = time.perf_counter() - t0

    from vizier_tpu.service.protos import study_pb2

    total = DIST_STUDIES * DIST_CLIENTS_PER_STUDY * DIST_TRIALS_EACH
    completed = 0
    for name in study_names:
        response = stub.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=name)
        )
        completed += sum(
            1 for t in response.trials if t.state == study_pb2.Trial.SUCCEEDED
        )
    assert completed == total, (completed, total)
    return {
        "studies": DIST_STUDIES,
        "clients_per_study": DIST_CLIENTS_PER_STUDY,
        "trials_each": DIST_TRIALS_EACH,
        "total_trials": total,
        "completed": completed,
        "wall_s": round(wall, 3),
        "trials_per_s": round(total / wall, 1),
        "study_names": study_names,
    }


def _best_of(fn, repeats: int) -> dict:
    best = None
    for rep in range(repeats):
        row = fn(rep)
        if best is None or row["trials_per_s"] > best["trials_per_s"]:
            best = row
    return best


def run_distributed(num_replicas: int, mode: str) -> dict:
    """The sharded-tier A/B: single gRPC server vs N routed replicas.

    Each arm runs in its OWN subprocess: neither arm's thread pools, gRPC
    channels, or allocator state can pollute the other's measurement (on a
    1-core host, teardown noise from a prior arm is a real bias in either
    direction).
    """
    from vizier_tpu.distributed import config as dist_config_lib

    report = {
        "config": {
            "replicas": num_replicas,
            "mode": mode,
            "studies": DIST_STUDIES,
            "clients_per_study": DIST_CLIENTS_PER_STUDY,
            "trials_each": DIST_TRIALS_EACH,
            "repeats": DIST_REPEATS,
            "distributed": dist_config_lib.DistributedConfig.from_env().as_dict(),
        },
    }
    for arm in ("multi_replica", "single_server"):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.abspath(__file__),
                "--dist-arm",
                arm,
                "--replicas",
                str(num_replicas),
                "--replica-mode",
                mode,
            ],
            capture_output=True,
            text=True,
            cwd=_REPO_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"distributed arm {arm} failed:\n{proc.stderr[-3000:]}"
            )
        payload = json.loads(
            [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
        )
        report.update(payload)
        print(json.dumps(payload), flush=True)
    report["speedup_vs_single_server"] = round(
        report["multi_replica"]["trials_per_s"]
        / report["single_server"]["trials_per_s"],
        2,
    )
    print(
        json.dumps(
            {"speedup_vs_single_server": report["speedup_vs_single_server"]}
        ),
        flush=True,
    )
    return report


def run_dist_arm(arm: str, num_replicas: int, mode: str) -> None:
    """Child-process entry: one A/B arm, result JSON on stdout (last line)."""
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    if arm == "single_server":
        from vizier_tpu.service import grpc_stubs, vizier_server

        server = vizier_server.DefaultVizierServer(host="localhost")
        try:
            stub = grpc_stubs.create_vizier_stub(server.endpoint)
            _dist_workload(stub, "warm-single")  # first-RPC costs off the clock
            single = _best_of(
                lambda rep: _dist_workload(stub, f"single-r{rep}"), DIST_REPEATS
            )
        finally:
            server.stop(0)
        single.pop("study_names")
        print(json.dumps({"single_server": single}), flush=True)
        return

    if mode == "inprocess":
        row, per_replica = _run_inprocess_tier(num_replicas)
    else:
        row, per_replica = _run_subprocess_tier(num_replicas)
    print(
        json.dumps({"multi_replica": row, "per_replica": per_replica}),
        flush=True,
    )


def _per_replica_breakdown(stub_stats: dict, assignments: dict) -> dict:
    """Merges router request counters with the study->replica map."""
    out = {}
    for rid, stats in stub_stats["replicas"].items():
        out[rid] = {
            "state": stats["state"],
            "requests": int(stats["requests"]),
            "failures": int(stats["failures"]),
            "studies": sorted(assignments.get(rid, [])),
        }
    return out


def _run_inprocess_tier(num_replicas: int):
    from vizier_tpu.distributed import ReplicaManager

    manager = ReplicaManager(num_replicas)
    try:
        _dist_workload(manager.stub, "warm-tier")
        best = _best_of(
            lambda rep: _dist_workload(manager.stub, f"tier-r{rep}"), DIST_REPEATS
        )
        assignments = {rid: [] for rid in manager.router.replica_ids}
        for name in best.pop("study_names"):
            assignments[manager.router.replica_for(name)].append(name)
        per_replica = _per_replica_breakdown(manager.stub.stats(), assignments)
    finally:
        manager.shutdown()
    return best, per_replica


def _run_subprocess_tier(num_replicas: int):
    from vizier_tpu.distributed import router_stub as router_stub_lib
    from vizier_tpu.service import grpc_stubs

    procs, endpoints = [], []
    try:
        for i in range(num_replicas):
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "vizier_tpu.distributed.replica_main",
                    "--replica-id",
                    f"replica-{i}",
                ],
                stdout=subprocess.PIPE,
                text=True,
                cwd=_REPO_ROOT,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            procs.append(proc)
        for proc in procs:
            line = proc.stdout.readline().strip()
            assert line.startswith("READY "), line
            endpoints.append(line.split(" ", 1)[1])
        stub = router_stub_lib.RoutedVizierStub(
            {
                f"replica-{i}": (lambda ep=ep: grpc_stubs.create_vizier_stub(ep))
                for i, ep in enumerate(endpoints)
            }
        )
        _dist_workload(stub, "warm-tier")
        best = _best_of(
            lambda rep: _dist_workload(stub, f"tier-r{rep}"), DIST_REPEATS
        )
        assignments = {rid: [] for rid in stub.router.replica_ids}
        for name in best.pop("study_names"):
            assignments[stub.router.replica_for(name)].append(name)
        per_replica = _per_replica_breakdown(stub.stats(), assignments)
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            proc.wait(timeout=10)
    return best, per_replica


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--side", choices=("repo", "reference", "both"), default="both"
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=0,
        help="also run the sharded-tier A/B with N replicas (0 = skip)",
    )
    ap.add_argument(
        "--replica-mode",
        choices=("inprocess", "subprocess"),
        default="inprocess",
    )
    ap.add_argument(
        "--dist-arm",
        choices=("single_server", "multi_replica"),
        default=None,
        help=argparse.SUPPRESS,  # child-process entry for run_distributed
    )
    args = ap.parse_args()

    if args.dist_arm:
        run_dist_arm(args.dist_arm, max(1, args.replicas), args.replica_mode)
        return

    if args.side == "reference":
        rows = run_reference()
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"reference": rows}, f, indent=1)
            print(f"wrote {args.out}")
        return

    report = {
        "topology": (
            "one DefaultVizierServer per side, real localhost gRPC, "
            "per-worker clients created before the clock"
        ),
        "algorithm": "RANDOM_SEARCH",
        "repo": run_repo(),
    }
    if args.side == "both":
        _ensure_refcopy()
        # Subprocess keeps the reference's `vizier` import + proto
        # registrations out of this process.
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--side", "reference"],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise RuntimeError(f"reference side failed:\n{proc.stderr[-3000:]}")
        report["reference"] = [
            json.loads(line)
            for line in proc.stdout.splitlines()
            if line.startswith("{")
        ]
        report["speedup_vs_reference"] = {
            f"{r['clients']}x{r['trials_each']}": round(
                r["trials_per_s"] / ref["trials_per_s"], 2
            )
            for r, ref in zip(report["repo"], report["reference"])
        }
        print(json.dumps(report["speedup_vs_reference"]))

    if args.replicas:
        report["distributed"] = run_distributed(args.replicas, args.replica_mode)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
