"""Service throughput at the reference's stress configs, over real gRPC.

Usage: python tools/service_throughput.py [--out SERVICE_THROUGHPUT.json]

Reference ``performance_test.py:44-89`` runs clients×trials configs
{1×10, 2×10, 10×10, 50×5, 100×5} on RANDOM_SEARCH over a 2-D space and
logs wall time only. This tool runs the same topology against this repo's
``DefaultVizierServer`` (one shared study per config, one thread per
client, each doing its own suggest→complete loop over a real localhost
gRPC channel) and prints a JSON report with wall time and trials/sec.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _honor_platform_env

_honor_platform_env()


CONFIGS = ((1, 10), (2, 10), (10, 10), (50, 5), (100, 5))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from vizier_tpu.service import clients as clients_lib
    from vizier_tpu.service import vizier_server
    from vizier_tpu.testing import stress

    server = vizier_server.DefaultVizierServer(host="localhost")
    clients_lib.environment_variables.server_endpoint = server.endpoint
    report = {"topology": "one DefaultVizierServer, real localhost gRPC",
              "algorithm": "RANDOM_SEARCH", "configs": []}
    try:
        for num_clients, trials_each in CONFIGS:
            study = clients_lib.Study.from_study_config(
                stress.stress_study_config(),
                owner="perf",
                study_id=f"tp-{num_clients}x{trials_each}",
            )
            wall, completed, _ = stress.run_stress_round(
                study, num_clients, trials_each
            )
            total = num_clients * trials_each
            row = {
                "clients": num_clients,
                "trials_each": trials_each,
                "total_trials": total,
                "completed": completed,
                "wall_s": round(wall, 3),
                "trials_per_s": round(total / wall, 1),
            }
            report["configs"].append(row)
            print(json.dumps(row), flush=True)
            assert completed == total, (completed, total)
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT
        server.stop(0)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
