"""Service throughput at the reference's stress configs, over real gRPC.

Usage: python tools/service_throughput.py [--out SERVICE_THROUGHPUT.json]
       [--side repo|reference|both]

Reference ``performance_test.py:44-89`` runs clients×trials configs
{1×10, 2×10, 10×10, 50×5, 100×5} on RANDOM_SEARCH over a 2-D space and
logs wall time only. This tool runs the same topology — one shared study
per config, one thread per client, each doing its own suggest→complete
loop over a real localhost gRPC channel — against BOTH this repo's
``DefaultVizierServer`` and the reference's (the runnable copy that
``tools/build_reference_copy.sh`` puts at /tmp/refvizier, RAM datastore),
and writes a two-column JSON report with wall time and trials/sec.

The reference side runs in a subprocess so its ``vizier`` package import
and proto registrations stay isolated; per-worker clients are created
BEFORE the timed section on both sides, so the clock covers only the
suggest→complete loops.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

CONFIGS = ((1, 10), (2, 10), (10, 10), (50, 5), (100, 5))
# The published stress set above is short enough that channel setup and
# first-RPC costs dominate the small configs; the steady-state config
# measures sustained per-trial service latency.
STEADY_STATE = (1, 200)
REFCOPY = "/tmp/refvizier"


REPEATS = 3  # best-of-N per config: throughput = least-interference run


def run_repo() -> list:
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    from vizier_tpu.service import clients as clients_lib
    from vizier_tpu.service import vizier_server
    from vizier_tpu.testing import stress

    server = vizier_server.DefaultVizierServer(host="localhost")
    clients_lib.environment_variables.server_endpoint = server.endpoint
    rows = []
    try:
        # Warmup: channel connect + proto/codec first-call costs land on a
        # throwaway study, so the timed configs measure the service.
        warm = clients_lib.Study.from_study_config(
            stress.stress_study_config(), owner="perf", study_id="warmup"
        )
        stress.run_stress_round(warm, 1, 3)
        for num_clients, trials_each in CONFIGS + (STEADY_STATE,):
            total = num_clients * trials_each
            best_wall = float("inf")
            for rep in range(REPEATS):
                study = clients_lib.Study.from_study_config(
                    stress.stress_study_config(),
                    owner="perf",
                    study_id=f"tp-{num_clients}x{trials_each}-r{rep}",
                )
                wall, completed, _ = stress.run_stress_round(
                    study, num_clients, trials_each
                )
                assert completed == total, (completed, total)
                best_wall = min(best_wall, wall)
            row = {
                "side": "repo",
                "clients": num_clients,
                "trials_each": trials_each,
                "total_trials": total,
                "completed": total,
                "wall_s": round(best_wall, 3),
                "trials_per_s": round(total / best_wall, 1),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT
        server.stop(0)
    return rows


def _ensure_refcopy() -> None:
    # The shims this diff relies on are part of the build; an isdir check
    # would accept a stale copy from an older build script.
    marker = os.path.join(
        REFCOPY, "vizier/_src/service/vizier_service_pb2_grpc.py"
    )
    if not os.path.exists(marker):
        subprocess.run(
            ["bash", os.path.join(_REPO_ROOT, "tools/build_reference_copy.sh")],
            check=True,
        )


def run_reference() -> list:
    """Identical topology against the reference's DefaultVizierServer."""
    import concurrent.futures as cf

    # Defensive: direct `--side reference` invocations must not initialize
    # the axon backend (a dead TPU tunnel hangs jax init on this image).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _ensure_refcopy()
    sys.path.insert(0, REFCOPY)
    from vizier._src.service import vizier_client, vizier_server
    from vizier.service import pyvizier as svz

    server = vizier_server.DefaultVizierServer(database_url=None)
    vizier_client.environment_variables.server_endpoint = server.endpoint

    def study_config():
        sc = svz.StudyConfig()
        sc.search_space.root.add_float_param("x", 0.0, 1.0)
        sc.search_space.root.add_float_param("y", 0.0, 1.0)
        sc.metric_information.append(
            svz.MetricInformation(
                name="obj", goal=svz.ObjectiveMetricGoal.MINIMIZE
            )
        )
        sc.algorithm = svz.Algorithm.RANDOM_SEARCH
        return sc

    # Warmup mirrors the repo side: throwaway study absorbs first-RPC costs.
    warm = vizier_client.create_or_load_study(
        owner_id="perf",
        study_id="warmup",
        study_config=study_config(),
        client_id="w",
    )
    for _ in range(3):
        (t,) = warm.get_suggestions(suggestion_count=1)
        warm.complete_trial(
            t.id, svz.Measurement(metrics={"obj": 0.0})
        )

    rows = []
    for num_clients, trials_each in CONFIGS + (STEADY_STATE,):
        total = num_clients * trials_each
        best_wall = float("inf")
        for rep in range(REPEATS):
            study_id = f"tp-{num_clients}x{trials_each}-r{rep}"
            # Per-worker clients before the clock, mirroring the repo side
            # (where the study client exists before run_stress_round).
            clients = [
                vizier_client.create_or_load_study(
                    owner_id="perf",
                    study_id=study_id,
                    study_config=study_config(),
                    client_id=f"worker_{i}",
                )
                for i in range(num_clients)
            ]

            def worker(client):
                for _ in range(trials_each):
                    (trial,) = client.get_suggestions(suggestion_count=1)
                    x = trial.parameters["x"].value
                    y = trial.parameters["y"].value
                    m = svz.Measurement(
                        metrics={"obj": (x - 0.3) ** 2 + (y - 0.7) ** 2}
                    )
                    client.complete_trial(trial.id, m)

            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=num_clients) as pool:
                list(pool.map(worker, clients))
            wall = time.perf_counter() - t0
            completed = sum(
                1
                for t in clients[0].list_trials()
                if t.status == svz.TrialStatus.COMPLETED
            )
            assert completed == total, (completed, total)
            best_wall = min(best_wall, wall)
        row = {
            "side": "reference",
            "clients": num_clients,
            "trials_each": trials_each,
            "total_trials": total,
            "completed": total,
            "wall_s": round(best_wall, 3),
            "trials_per_s": round(total / best_wall, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--side", choices=("repo", "reference", "both"), default="both"
    )
    args = ap.parse_args()

    if args.side == "reference":
        rows = run_reference()
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"reference": rows}, f, indent=1)
            print(f"wrote {args.out}")
        return

    report = {
        "topology": (
            "one DefaultVizierServer per side, real localhost gRPC, "
            "per-worker clients created before the clock"
        ),
        "algorithm": "RANDOM_SEARCH",
        "repo": run_repo(),
    }
    if args.side == "both":
        _ensure_refcopy()
        # Subprocess keeps the reference's `vizier` import + proto
        # registrations out of this process.
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--side", "reference"],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise RuntimeError(f"reference side failed:\n{proc.stderr[-3000:]}")
        report["reference"] = [
            json.loads(line)
            for line in proc.stdout.splitlines()
            if line.startswith("{")
        ]
        report["speedup_vs_reference"] = {
            f"{r['clients']}x{r['trials_each']}": round(
                r["trials_per_s"] / ref["trials_per_s"], 2
            )
            for r, ref in zip(report["repo"], report["reference"])
        }
        print(json.dumps(report["speedup_vs_reference"]))

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
