#!/usr/bin/env python
"""Hot-tenant overload A/B: admission plane ON vs OFF → OVERLOAD_AB.json.

Drives the loadgen ``hot_tenant`` scenario — one Zipf-head tenant floods
the fleet with GP compute at a saturating OPEN-LOOP rate (``time_scale=1``,
real arrival pacing: studies arrive whether or not the fleet keeps up)
while three light tenants run occasional GP studies — through the REAL
serving stack twice:

- **ON** — ``VIZIER_ADMISSION=1``: per-tenant in-flight caps, weighted
  deficit-round-robin flush selection, deadline-aware shedding, and the
  healthy→shedding→degraded state machine (the hot tenant's sub-floor
  weight routes it to stamped quasi-random under sustained saturation);
- **OFF** — the identical workload with the plane gated off: FIFO
  everything, no caps — the collapse arm.

Assertions (exit nonzero on any failure):

- ON: zero lost/errored studies; light tenants' suggest p99 within the
  scenario's SLO budget; sheds NONZERO and confined to the hot tenant;
  sheds never trip a circuit breaker (breaker transition counters stay 0).
- OFF: the light tenants' p99 collapses past the SLO budget (the damage
  the plane exists to prevent).
- ``VIZIER_ADMISSION=0`` bit-identity: the gated-off engine arm replays
  the parity cohort trajectory-identical to the sequential reference —
  the off switch is the pre-admission tree.

Usage:
    python tools/overload_ab.py                # full A/B -> OVERLOAD_AB.json
    python tools/overload_ab.py --studies 16 --budget-ms 1500
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu.loadgen import driver as driver_lib  # noqa: E402
from vizier_tpu.loadgen import models  # noqa: E402
from vizier_tpu.loadgen import report as report_lib  # noqa: E402

LIGHT = ("light-a", "light-b", "light-c")


def _suggest_latencies_ms(result, tenants):
    return sorted(
        r.latency_s * 1e3
        for r in result.records
        if r.op == "suggest" and r.error is None and r.tenant in tenants
    )


def _p99_ms(values):
    if not values:
        return 0.0
    rank = 0.99 * (len(values) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(values) - 1)
    frac = rank - lo
    return round(values[lo] * (1 - frac) + values[hi] * frac, 3)


def _arm_summary(result, config):
    outcomes = report_lib._outcome_tables(result)
    light = _suggest_latencies_ms(result, set(LIGHT))
    hot = _suggest_latencies_ms(result, {"hot"})
    stats = {
        k: v
        for k, v in sorted(result.serving_stats.items())
        if isinstance(v, int) and v
    }
    return {
        "wall_s": result.wall_s,
        "lost_studies": result.lost_studies(),
        "errored_studies": result.errored_studies(),
        "light_suggest_p99_ms": _p99_ms(light),
        "light_suggests": len(light),
        "hot_suggest_p99_ms": _p99_ms(hot),
        "hot_suggests": len(hot),
        "by_tenant": outcomes["by_tenant"],
        "admission": result.admission,
        "open_loop_capped": result.open_loop_capped,
        "breaker_transitions": stats.get("breaker_open_transitions", 0),
        "serving_stats": stats,
        "slo_breaching": sorted(result.slo.get("breaching", []))
        if result.slo.get("armed")
        else [],
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--studies", type=int, default=0,
                        help="override the scenario study count")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--budget-ms", type=float, default=0.0,
                        help="override the light-tenant p99 SLO budget")
    parser.add_argument(
        "--out",
        default=str(
            pathlib.Path(__file__).resolve().parent.parent
            / "OVERLOAD_AB.json"
        ),
    )
    args = parser.parse_args()

    from vizier_tpu.service import vizier_client

    vizier_client.environment_variables.polling_delay_secs = 0.005

    overrides = {"seed": args.seed}
    if args.studies:
        overrides["num_studies"] = args.studies
    if args.budget_ms:
        overrides["p99_budget_ms"] = args.budget_ms
    config = models.hot_tenant_config(**overrides)
    scenario = models.build_scenario(config)
    budget_ms = config.p99_budget_ms
    print(
        f"[overload_ab] hot_tenant scenario: {len(scenario.studies)} studies "
        f"/ {scenario.total_trials} trials, open-loop time_scale="
        f"{config.time_scale}, light-p99 budget {budget_ms} ms",
        flush=True,
    )

    t0 = time.time()
    # Warmup arm (unmeasured): the same workload once, closed-loop, to
    # pay every XLA compile the padding-bucket grid needs — jit caches
    # are process-wide, so the measured arms then compare pure serving
    # behavior, not who compiled first.
    warm_config = dataclasses.replace(
        config,
        time_scale=0.0,
        planes=dataclasses.replace(config.planes, admission=False, slo=False),
    )
    warm = driver_lib.run(models.build_scenario(warm_config), arm="warmup")
    print(f"[overload_ab] warmup arm done in {warm.wall_s}s", flush=True)

    on = driver_lib.run(scenario, arm="admission_on")
    print(f"[overload_ab] ON arm done in {on.wall_s}s", flush=True)

    off_config = dataclasses.replace(
        config,
        planes=dataclasses.replace(config.planes, admission=False),
    )
    off_scenario = models.build_scenario(off_config)
    off = driver_lib.run(off_scenario, arm="admission_off")
    print(f"[overload_ab] OFF arm done in {off.wall_s}s", flush=True)

    # VIZIER_ADMISSION=0 bit-identity vs HEAD: the gated-off engine arm
    # must replay the cohort exactly as the sequential reference does —
    # the off switch leaves the pre-admission tree untouched.
    reference = driver_lib.run_reference(scenario)
    gated = driver_lib.run_gated_off(scenario)
    bit = report_lib._bit_identity_section(gated, reference)
    print(
        f"[overload_ab] bit-identity cohort: {bit['studies_compared']} "
        f"studies, identical={bit['identical']}",
        flush=True,
    )

    on_summary = _arm_summary(on, config)
    off_summary = _arm_summary(off, off_config)
    on_sheds = (on.admission or {}).get("sheds_by_tenant", {})
    shed_tenants = sorted(t for t, r in on_sheds.items() if sum(r.values()))
    total_sheds = sum(sum(r.values()) for r in on_sheds.values())

    assertions = []

    def check(name, ok, detail):
        assertions.append({"name": name, "ok": bool(ok), "detail": detail})

    check(
        "on_zero_lost_studies",
        not on_summary["lost_studies"] and not on_summary["errored_studies"],
        f"lost={on_summary['lost_studies']} "
        f"errored={on_summary['errored_studies']}",
    )
    check(
        "on_light_p99_within_slo",
        0 < on_summary["light_suggest_p99_ms"] <= budget_ms,
        f"light p99 {on_summary['light_suggest_p99_ms']} ms "
        f"(budget {budget_ms} ms, {on_summary['light_suggests']} suggests)",
    )
    check(
        "on_sheds_nonzero_confined_to_hot",
        total_sheds > 0 and shed_tenants == ["hot"],
        f"sheds={total_sheds} by tenant {on_sheds}",
    )
    check(
        "on_sheds_never_trip_breaker",
        on_summary["breaker_transitions"] == 0,
        f"breaker_open_transitions={on_summary['breaker_transitions']} "
        f"with {total_sheds} sheds",
    )
    check(
        "off_light_p99_collapses",
        off_summary["light_suggest_p99_ms"] > budget_ms,
        f"light p99 {off_summary['light_suggest_p99_ms']} ms OFF vs "
        f"{on_summary['light_suggest_p99_ms']} ms ON (budget {budget_ms})",
    )
    check(
        "admission_off_bit_identical",
        bit["identical"],
        f"compared={bit['studies_compared']} mismatched={bit['mismatched']}",
    )

    ratio = (
        round(
            off_summary["light_suggest_p99_ms"]
            / on_summary["light_suggest_p99_ms"],
            2,
        )
        if on_summary["light_suggest_p99_ms"]
        else None
    )
    report = {
        "version": 1,
        "what": (
            "hot-tenant overload A/B: saturating open-loop loadgen "
            "scenario through the real serving stack, admission plane "
            "ON vs OFF; light-tenant p99 + zero lost studies + sheds "
            "confined to the hot tenant with the plane ON, collapse "
            "with it OFF, VIZIER_ADMISSION=0 bit-identical to HEAD"
        ),
        "scenario": {
            "config": config.as_dict(),
            "fingerprint": on.scenario_fingerprint,
        },
        "slo_budget_ms": budget_ms,
        "light_p99_off_over_on": ratio,
        "arms": {"admission_on": on_summary, "admission_off": off_summary},
        "bit_identity": bit,
        "assertions": assertions,
        "ok": all(a["ok"] for a in assertions),
        "wall_seconds_total": round(time.time() - t0, 1),
    }
    out_path = pathlib.Path(args.out)
    out_path.write_text(json.dumps(report, indent=2) + "\n")
    for a in assertions:
        print(f"  [{'ok' if a['ok'] else 'FAIL'}] {a['name']}: {a['detail']}")
    print(f"[overload_ab] wrote {out_path} (ok={report['ok']})")
    if not report["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
