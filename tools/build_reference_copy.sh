#!/bin/bash
# Builds a runnable copy of the read-only reference at /tmp/refvizier:
#  - copies the tree (the original at /root/reference must stay untouched),
#  - compiles its protos against the googleapis protos shipped inside
#    site-packages (no network),
#  - patches vizier/pyvizier/converters/__init__.py to tolerate the absence
#    of equinox/tfp (those deps are not in this image and installs are
#    banned, so the reference's GP stack cannot run; random / grid /
#    quasi-random / NSGA2 / harmonica / eagle all work).
# Used by parity_suite.py to measure the reference behaviorally (VERDICT r1
# item #4 / BASELINE.md: the reference publishes no numbers, so it must be
# run as its own baseline).
set -e

REF=${1:-/root/reference}
DST=${2:-/tmp/refvizier}
SP=$(python -c "import site; print(site.getsitepackages()[0])")

rm -rf "$DST"
mkdir -p "$DST"
cp -r "$REF/vizier" "$DST/"

# google/longrunning ships its proto under a different filename.
INC=/tmp/protoinc
mkdir -p "$INC/google/longrunning"
cp "$SP/google/longrunning/operations_proto.proto" \
   "$INC/google/longrunning/operations.proto"

export DST
cd "$DST/vizier/_src/service"
protoc -I. -I"$INC" -I"$SP" --python_out=. \
  key_value.proto study.proto vizier_oss.proto \
  vizier_service.proto pythia_service.proto

python - << 'EOF'
import os
import pathlib

DST = pathlib.Path(os.environ['DST'])

# grpcio-tools (the *_pb2_grpc generator) is absent from this image; emit
# descriptor-driven shims that provide the same Stub / Servicer /
# add_*_to_server surface the reference's service modules import.
_SHIM = '''"""Descriptor-driven stand-in for the grpcio-tools generated module."""
import grpc
from vizier._src.service import {pb2} as _pb2

try:
    from google.protobuf import message_factory

    def _cls(desc):
        return message_factory.GetMessageClass(desc)
except (ImportError, AttributeError):  # protobuf < 4
    from google.protobuf.message_factory import MessageFactory

    def _cls(desc):
        return MessageFactory().GetPrototype(desc)

_SVC = _pb2.DESCRIPTOR.services_by_name["{service}"]


class {service}Stub:
    def __init__(self, channel):
        for m in _SVC.methods:
            setattr(
                self,
                m.name,
                channel.unary_unary(
                    "/%s/%s" % (_SVC.full_name, m.name),
                    request_serializer=_cls(m.input_type).SerializeToString,
                    response_deserializer=_cls(m.output_type).FromString,
                ),
            )


class {service}Servicer:
    pass


def _unimplemented(name):
    def method(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details("Method %s not implemented." % name)
        raise NotImplementedError(name)

    return method


for _m in _SVC.methods:
    setattr({service}Servicer, _m.name, _unimplemented(_m.name))


def add_{service}Servicer_to_server(servicer, server):
    handlers = {{
        m.name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, m.name),
            request_deserializer=_cls(m.input_type).FromString,
            response_serializer=_cls(m.output_type).SerializeToString,
        )
        for m in _SVC.methods
    }}
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SVC.full_name, handlers),)
    )
'''

svc_dir = DST / 'vizier/_src/service'
for pb2, service in (
    ('vizier_service_pb2', 'VizierService'),
    ('pythia_service_pb2', 'PythiaService'),
):
    (svc_dir / f'{pb2}_grpc.py').write_text(
        _SHIM.format(pb2=pb2, service=service)
    )

# sqlalchemy is absent from this image; the servicer only touches it when
# a SQL database_url is passed. Stub the import so database_url=None (RAM
# datastore) works — that is the config the reference's own performance
# test uses in-memory equivalently.
for rel, imports in (
    ('vizier_service.py', ('import sqlalchemy as sqla',
                           'from vizier._src.service import sql_datastore')),
    ('sql_datastore.py', ('import sqlalchemy as sqla',)),
):
    p = svc_dir / rel
    src = p.read_text()
    for old in imports:
        if old in src and f'try:\n  {old}' not in src:
            name = old.rsplit(' ', 1)[-1]
            src = src.replace(
                old,
                f'try:\n  {old}\n'
                'except ModuleNotFoundError:  # absent image dep; RAM datastore only\n'
                f'  {name} = None',
            )
    p.write_text(src)

p = DST / 'vizier/pyvizier/converters/__init__.py'
src = p.read_text()
if 'ModuleNotFoundError' not in src:
    out = []
    for line in src.splitlines():
        gated = any(
            m in line
            for m in ('jnp_converters', 'padding', 'feature_mapper', 'embedder', 'spatio')
        )
        if gated and line.startswith('from'):
            out.append(
                f"try:\n    {line}\nexcept ModuleNotFoundError:"
                "  # equinox/tfp absent in this image\n    pass"
            )
        else:
            out.append(line)
    p.write_text('\n'.join(out) + '\n')
print(f'reference copy ready at {DST}')
EOF
