#!/bin/bash
# Builds a runnable copy of the read-only reference at /tmp/refvizier:
#  - copies the tree (the original at /root/reference must stay untouched),
#  - compiles its protos against the googleapis protos shipped inside
#    site-packages (no network),
#  - patches vizier/pyvizier/converters/__init__.py to tolerate the absence
#    of equinox/tfp (those deps are not in this image and installs are
#    banned, so the reference's GP stack cannot run; random / grid /
#    quasi-random / NSGA2 / harmonica / eagle all work).
# Used by parity_suite.py to measure the reference behaviorally (VERDICT r1
# item #4 / BASELINE.md: the reference publishes no numbers, so it must be
# run as its own baseline).
set -e

REF=${1:-/root/reference}
DST=${2:-/tmp/refvizier}
SP=$(python -c "import site; print(site.getsitepackages()[0])")

rm -rf "$DST"
mkdir -p "$DST"
cp -r "$REF/vizier" "$DST/"

# google/longrunning ships its proto under a different filename.
INC=/tmp/protoinc
mkdir -p "$INC/google/longrunning"
cp "$SP/google/longrunning/operations_proto.proto" \
   "$INC/google/longrunning/operations.proto"

cd "$DST/vizier/_src/service"
protoc -I. -I"$INC" -I"$SP" --python_out=. \
  key_value.proto study.proto vizier_oss.proto \
  vizier_service.proto pythia_service.proto

python - << 'EOF'
import pathlib
p = pathlib.Path('/tmp/refvizier/vizier/pyvizier/converters/__init__.py')
src = p.read_text()
if 'ModuleNotFoundError' not in src:
    out = []
    for line in src.splitlines():
        gated = any(
            m in line
            for m in ('jnp_converters', 'padding', 'feature_mapper', 'embedder', 'spatio')
        )
        if gated and line.startswith('from'):
            out.append(
                f"try:\n    {line}\nexcept ModuleNotFoundError:"
                "  # equinox/tfp absent in this image\n    pass"
            )
        else:
            out.append(line)
    p.write_text('\n'.join(out) + '\n')
print('reference copy ready at /tmp/refvizier')
EOF
