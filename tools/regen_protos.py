#!/usr/bin/env python
"""Regenerates proto message stubs by descriptor surgery (no protoc needed).

``build_protos.sh`` requires a ``protoc`` binary that this image does not
ship. The pb2 modules are nothing but a serialized ``FileDescriptorProto``
handed to the protobuf builder, so schema additions can be applied directly
to those bytes with the protobuf runtime itself: parse the serialized file,
append the new ``FieldDescriptorProto``s, reserialize, and rewrite the pb2
module around the new bytes.

Two declarative tables drive the surgery, both mirroring what the
``.proto`` sources say, and applying either twice is a no-op:

- ``_NEW_FIELDS`` — additive fields on EXISTING messages (the PR 2/3
  deadline/trace-context additions);
- ``_NEW_FILES``  — whole new message files synthesized from scratch (the
  ``FileDescriptorProto`` is built field by field with the protobuf
  runtime and serialized exactly as protoc would have): the
  ``replication_service`` surface lands this way.

Run from the repo root:

    python tools/regen_protos.py
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

from google.protobuf import descriptor_pb2

PROTO_DIR = pathlib.Path(__file__).resolve().parent.parent / (
    "vizier_tpu/service/protos"
)

# file stem -> message name -> [(field name, number, type enum)]
_DOUBLE = descriptor_pb2.FieldDescriptorProto.TYPE_DOUBLE
_STRING = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
_BOOL = descriptor_pb2.FieldDescriptorProto.TYPE_BOOL
_BYTES = descriptor_pb2.FieldDescriptorProto.TYPE_BYTES
_UINT32 = descriptor_pb2.FieldDescriptorProto.TYPE_UINT32
_UINT64 = descriptor_pb2.FieldDescriptorProto.TYPE_UINT64
_MESSAGE = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
_OPTIONAL = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
_REPEATED = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
_NEW_FIELDS = {
    "vizier_service": {
        # deadline_secs: remaining client deadline budget in seconds (0 = no
        # deadline); relative rather than absolute so it is clock-skew immune.
        # trace_context: "<trace_id>-<span_id>" of the caller's active span
        # ('' = untraced); see vizier_tpu.observability.tracing.
        "SuggestTrialsRequest": [
            ("deadline_secs", 4, _DOUBLE),
            ("trace_context", 5, _STRING),
        ],
    },
    "pythia_service": {
        "PythiaSuggestRequest": [
            ("deadline_secs", 5, _DOUBLE),
            ("trace_context", 6, _STRING),
        ],
    },
}

# -- whole-file synthesis -----------------------------------------------------
#
# file stem -> ordered message table. Field spec:
#   (name, number, type, label, message type name or None)
# Message-typed fields reference siblings in the same file by bare name.
_R = "ReplicationRecord"
_NEW_FILES = {
    # The cross-process replication surface (vizier_tpu.ReplicationService,
    # served by replica_main next to VizierService; see
    # vizier_tpu/distributed/replication_service.py). DeliverAppends /
    # Baseline carry the standby-log write protocol (epoch-fenced;
    # ``value`` is the acked last-seq on acceptance, the fencing epoch on
    # rejection); Fence raises an origin's epoch without data (the revive/
    # failover cutover); Heartbeat is the lease-renewal probe and
    # piggybacks the receiver's fencing/resync counters; ExportStandby /
    # ExportState / ApplyRecords are the recovery-plan plumbing a manager
    # drives failover and revive copy-back through; Resync and FlushStream
    # poke the replica's origin-side streamer.
    "replication_service": {
        "package": "vizier_tpu",
        "messages": {
            _R: [
                ("seq", 1, _UINT64, _OPTIONAL, None),
                ("opcode", 2, _UINT32, _OPTIONAL, None),
                ("payload", 3, _BYTES, _OPTIONAL, None),
            ],
            "DeliverAppendsRequest": [
                ("origin", 1, _STRING, _OPTIONAL, None),
                ("epoch", 2, _UINT64, _OPTIONAL, None),
                ("records", 3, _MESSAGE, _REPEATED, _R),
                ("reset", 4, _BOOL, _OPTIONAL, None),
                ("baseline_seq", 5, _UINT64, _OPTIONAL, None),
            ],
            "DeliverAppendsResponse": [
                ("accepted", 1, _BOOL, _OPTIONAL, None),
                ("value", 2, _UINT64, _OPTIONAL, None),
            ],
            "FenceRequest": [
                ("origin", 1, _STRING, _OPTIONAL, None),
                ("epoch", 2, _UINT64, _OPTIONAL, None),
            ],
            "FenceResponse": [
                ("epoch", 1, _UINT64, _OPTIONAL, None),
            ],
            "HeartbeatRequest": [
                ("sender", 1, _STRING, _OPTIONAL, None),
            ],
            "HeartbeatResponse": [
                ("replica_id", 1, _STRING, _OPTIONAL, None),
                ("seq", 2, _UINT64, _OPTIONAL, None),
                ("fenced_rejections", 3, _UINT64, _OPTIONAL, None),
                ("resyncs", 4, _UINT64, _OPTIONAL, None),
            ],
            "ExportStandbyRequest": [
                ("origin", 1, _STRING, _OPTIONAL, None),
            ],
            "ExportStandbyResponse": [
                ("present", 1, _BOOL, _OPTIONAL, None),
                ("baseline_seq", 2, _UINT64, _OPTIONAL, None),
                ("epoch", 3, _UINT64, _OPTIONAL, None),
                ("records", 4, _MESSAGE, _REPEATED, _R),
            ],
            "ExportStateRequest": [
                ("studies", 1, _STRING, _REPEATED, None),
            ],
            "ExportStateResponse": [
                ("seq", 1, _UINT64, _OPTIONAL, None),
                ("records", 2, _MESSAGE, _REPEATED, _R),
            ],
            "ApplyRecordsRequest": [
                ("records", 1, _MESSAGE, _REPEATED, _R),
            ],
            "ApplyRecordsResponse": [
                ("applied", 1, _UINT32, _OPTIONAL, None),
            ],
            "ResyncRequest": [
                ("successor", 1, _STRING, _OPTIONAL, None),
            ],
            "ResyncResponse": [
                ("requested", 1, _BOOL, _OPTIONAL, None),
            ],
            "FlushStreamRequest": [
                ("timeout_secs", 1, _DOUBLE, _OPTIONAL, None),
            ],
            "FlushStreamResponse": [
                ("flushed", 1, _BOOL, _OPTIONAL, None),
            ],
        },
    },
}

_HEADER = '''\
# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# (Regenerated by tools/regen_protos.py — descriptor surgery in lieu of
# protoc, which is not available in this image.)
# source: {stem}.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


import study_pb2 as study__pb2
import key_value_pb2 as key__value__pb2


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({payload})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, '{stem}_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
# @@protoc_insertion_point(module_scope)
'''


_HEADER_STANDALONE = '''\
# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# (Synthesized by tools/regen_protos.py — descriptor surgery in lieu of
# protoc, which is not available in this image.)
# source: {stem}.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({payload})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, '{stem}_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
# @@protoc_insertion_point(module_scope)
'''


def _json_name(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part.capitalize() for part in rest)


def _synthesize(stem: str, spec: dict) -> bytes:
    """Builds the serialized ``FileDescriptorProto`` for a ``_NEW_FILES``
    entry — exactly what protoc would have emitted for the ``.proto``."""
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = f"{stem}.proto"
    fdp.package = spec["package"]
    fdp.syntax = "proto3"
    for message_name, fields in spec["messages"].items():
        message = fdp.message_type.add(name=message_name)
        for name, number, ftype, label, type_name in fields:
            field = message.field.add(
                name=name,
                number=number,
                type=ftype,
                label=label,
                json_name=_json_name(name),
            )
            if type_name is not None:
                field.type_name = f".{spec['package']}.{type_name}"
    return fdp.SerializeToString()


def regen_new_file(stem: str) -> bool:
    """Writes (or refreshes) a synthesized ``<stem>_pb2.py``.

    Returns True when the module was (re)written (False = already
    byte-identical to the declared schema).
    """
    spec = _NEW_FILES[stem]
    payload = _synthesize(stem, spec)
    pb2_path = PROTO_DIR / f"{stem}_pb2.py"
    if pb2_path.exists():
        current = _extract_serialized(pb2_path.read_text(), stem)
        if current == payload:
            return False
        existing = descriptor_pb2.FileDescriptorProto.FromString(current)
        declared = descriptor_pb2.FileDescriptorProto.FromString(payload)
        for message in existing.message_type:
            target = next(
                (m for m in declared.message_type if m.name == message.name),
                None,
            )
            if target is None:
                raise SystemExit(
                    f"{stem}.{message.name} exists on disk but not in the "
                    "declared schema; refusing to drop a message."
                )
            for field in message.field:
                new = next(
                    (f for f in target.field if f.name == field.name), None
                )
                if new is None or new.number != field.number or (
                    new.type != field.type
                ):
                    raise SystemExit(
                        f"{stem}.{message.name}.{field.name} changed "
                        "number/type; refusing to rewrite it (wire "
                        "compatibility)."
                    )
    pb2_path.write_text(
        _HEADER_STANDALONE.format(stem=stem, payload=repr(payload))
    )
    return True


def _extract_serialized(source: str, stem: str) -> bytes:
    match = re.search(r"AddSerializedFile\(\s*(b'(?:[^'\\]|\\.)*')\s*\)", source)
    if match is None:
        raise SystemExit(f"{stem}_pb2.py: serialized descriptor literal not found")
    return ast.literal_eval(match.group(1))


def regen(stem: str) -> bool:
    """Applies the declared field additions to ``<stem>_pb2.py``.

    Returns True when the module was rewritten (False = already current).
    """
    pb2_path = PROTO_DIR / f"{stem}_pb2.py"
    fdp = descriptor_pb2.FileDescriptorProto.FromString(
        _extract_serialized(pb2_path.read_text(), stem)
    )

    changed = False
    for message in fdp.message_type:
        for name, number, ftype in _NEW_FIELDS.get(stem, {}).get(message.name, []):
            existing = {f.name: f for f in message.field}
            if name in existing:
                if existing[name].number != number or existing[name].type != ftype:
                    raise SystemExit(
                        f"{stem}.{message.name}.{name} exists with a different "
                        "number/type; refusing to rewrite it."
                    )
                continue
            if any(f.number == number for f in message.field):
                raise SystemExit(
                    f"{stem}.{message.name}: field number {number} is taken."
                )
            message.field.add(
                name=name,
                number=number,
                type=ftype,
                label=descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL,
                json_name=_json_name(name),
            )
            changed = True

    if changed:
        pb2_path.write_text(
            _HEADER.format(stem=stem, payload=repr(fdp.SerializeToString()))
        )
    return changed


def main() -> None:
    rewritten = [stem for stem in sorted(_NEW_FIELDS) if regen(stem)]
    rewritten += [
        stem for stem in sorted(_NEW_FILES) if regen_new_file(stem)
    ]
    if rewritten:
        print(f"Rewrote: {', '.join(f'{s}_pb2.py' for s in rewritten)}")
    else:
        print("All pb2 modules already current.")


if __name__ == "__main__":
    sys.exit(main())
