#!/usr/bin/env python
"""Batching A/B: suggestion throughput with the cross-study batch executor
on vs off, K concurrent same-bucket studies.

Both arms run the SAME workload: K studies with identical search-space
shapes (thus one padding bucket), each driven by its own client thread; a
round issues one suggest per study concurrently, then completes one trial
per study so the next round trains on fresh data (the steady serving
shape). Per-study designers and budgets are identical across arms; only
the dispatch strategy differs:

- **batching_on** — suggests route through ``parallel.BatchExecutor``:
  same-bucket computations coalesce into ONE vmapped device program per
  flush (occupancy ≈ K), after a prewarm pass that precompiles the
  batched programs so measured rounds pay no XLA compile;
- **batching_off** — every suggest dispatches its own per-study programs
  (the seed path), same thread structure.

Evidence lands in ``BATCHING_AB.json``: per-suggest latency p50/p95/p99,
suggestions/sec, mean batch occupancy, and the speedup ratio. Acceptance:
>= 2x throughput at 8 concurrent same-bucket studies.

**Mesh arm** (``--devices N``): the multi-BUCKET shape the single-device
executor is worst at. ``--buckets B`` study groups with distinct shape
buckets (distinct acquisition budgets -> distinct jit statics), each group
``--studies-per-bucket`` studies, all driven concurrently. Both arms run
the identical workload through a BatchExecutor; they differ only in the
execution plane:

- **single_device** — the seed executor: one scheduler thread executes
  every flush on one device, each partial flush padded to
  ``max_batch_size``;
- **mesh** — ``parallel.mesh``: N devices carved into placements
  (``--shard-devices`` per submesh), buckets sticky-assigned across them,
  per-placement workers dispatching concurrently, flushes padded at shard
  granularity.

Evidence lands in ``MESH_AB.json``. Acceptance: >= 2x aggregate flush
throughput at 8 simulated devices with >= 8 concurrent buckets, plus the
``VIZIER_MESH=0`` bit-identity check against the seed executor.

Usage:  python tools/batching_ab.py [--studies 8] [--rounds 6] [--out BATCHING_AB.json]
        python tools/batching_ab.py --devices 8 [--buckets 8] [--studies-per-bucket 2]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")


def _peek_int_flag(name: str, default: int) -> int:
    """Reads an int flag from argv BEFORE heavyweight imports (the mesh arm
    must set --xla_force_host_platform_device_count before jax's backend
    initializes)."""
    for i, arg in enumerate(sys.argv):
        if arg == name and i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
        if arg.startswith(name + "="):
            return int(arg.split("=", 1)[1])
    return default


_DEVICES = _peek_int_flag("--devices", 0)
if _DEVICES:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_DEVICES}"
        ).strip()

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu import pyvizier as vz  # noqa: E402
from vizier_tpu.algorithms import core as core_lib  # noqa: E402
from vizier_tpu.designers import gp_ucb_pe  # noqa: E402
from vizier_tpu.optimizers import lbfgs as lbfgs_lib  # noqa: E402
from vizier_tpu.parallel.batch_executor import BatchExecutor  # noqa: E402
from vizier_tpu.parallel.mesh import MeshConfig  # noqa: E402
from vizier_tpu.serving.stats import ServingStats  # noqa: E402


def _problem(dim: int) -> vz.ProblemStatement:
    p = vz.ProblemStatement()
    for d in range(dim):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _sphere(parameters: dict) -> float:
    return -sum((v - 0.3) ** 2 for v in parameters.values())


class _Study:
    """One study: a designer plus its completed-trial frontier."""

    def __init__(self, problem, seed, designer_kwargs):
        self.designer = gp_ucb_pe.VizierGPUCBPEBandit(
            problem, rng_seed=seed, **designer_kwargs
        )
        self.next_id = 1
        self.seed = seed

    def feed(self, n: int) -> None:
        import numpy as np

        rng = np.random.default_rng(self.seed * 1000 + self.next_id)
        trials = []
        for _ in range(n):
            params = {
                f"x{d}": float(rng.uniform())
                for d in range(len(self.designer.problem.search_space.parameters))
            }
            t = vz.Trial(parameters=params, id=self.next_id)
            t.complete(vz.Measurement(metrics={"obj": _sphere(params)}))
            trials.append(t)
            self.next_id += 1
        self.designer.update(core_lib.CompletedTrials(trials))

    def complete_suggestion(self, suggestion) -> None:
        params = dict(suggestion.parameters.as_dict())
        t = vz.Trial(parameters=params, id=self.next_id)
        t.complete(vz.Measurement(metrics={"obj": _sphere(params)}))
        self.next_id += 1
        self.designer.update(core_lib.CompletedTrials([t]))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    rank = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _run_arm(
    *,
    batching: bool,
    studies: int,
    rounds: int,
    warmup_rounds: int,
    start_trials: int,
    problem,
    designer_kwargs,
    max_wait_ms: float,
) -> dict:
    pool = [_Study(problem, seed=s + 1, designer_kwargs=designer_kwargs) for s in range(studies)]
    for st in pool:
        st.feed(start_trials)
    stats = ServingStats()
    executor = (
        BatchExecutor(
            max_batch_size=studies,
            max_wait_ms=max_wait_ms,
            stats=stats,
            metrics=stats.registry,
        )
        if batching
        else None
    )

    latencies: list = []
    lat_lock = threading.Lock()

    def one_suggest(st: _Study, record: bool):
        t0 = time.perf_counter()
        if executor is not None:
            out = executor.suggest(st.designer, 1)
        else:
            out = st.designer.suggest(1)
        dt = time.perf_counter() - t0
        if record:
            with lat_lock:
                latencies.append(dt)
        return out

    # Continuous traffic, the serving shape: one client thread per study,
    # each running suggest -> complete cycles back to back with NO global
    # round barrier. Batches form from whatever computations coincide
    # (shape buckets make every trial count in the run batch-compatible),
    # and host-side prepare/decode pipelines against in-flight device work.
    barrier = threading.Barrier(studies + 1)

    def client(st: _Study):
        for _ in range(warmup_rounds):
            st.complete_suggestion(one_suggest(st, record=False)[0])
        barrier.wait()  # compiles paid; measurement starts together
        for _ in range(rounds):
            st.complete_suggestion(one_suggest(st, record=True)[0])

    threads = [threading.Thread(target=client, args=(st,)) for st in pool]
    for t in threads:
        t.start()
    barrier.wait()
    t_start = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    if executor is not None:
        executor.close()

    latencies.sort()
    snap = stats.snapshot()
    total = studies * rounds
    occupancy = (
        snap["batched_suggests"] / snap["batch_flushes"]
        if snap.get("batch_flushes")
        else 1.0
    )
    return {
        "batching": batching,
        "suggest_p50_ms": round(_percentile(latencies, 50) * 1e3, 1),
        "suggest_p95_ms": round(_percentile(latencies, 95) * 1e3, 1),
        "suggest_p99_ms": round(_percentile(latencies, 99) * 1e3, 1),
        "throughput_suggestions_per_sec": round(total / wall, 3),
        "wall_secs": round(wall, 2),
        "suggestions": total,
        "mean_batch_occupancy": round(occupancy, 2),
        "batch_stats": {k: v for k, v in snap.items() if k.startswith("batch")},
    }


def _make_pool(problem, buckets, studies_per_bucket, designer_kwargs_for):
    """One study pool: ``buckets`` groups with distinct shape buckets."""
    pool = []
    for b in range(buckets):
        for c in range(studies_per_bucket):
            pool.append(
                _Study(
                    problem,
                    seed=b * 100 + c + 1,
                    designer_kwargs=designer_kwargs_for(b),
                )
            )
    return pool


def _distinct_buckets(problem, buckets, designer_kwargs_for, start_trials) -> int:
    """Pre-checks that the per-group acquisition budgets really produce
    pairwise-distinct shape buckets (distinct jit statics)."""
    from vizier_tpu.compute import registry as compute_registry

    keys = set()
    for b in range(buckets):
        st = _Study(problem, seed=b + 1, designer_kwargs=designer_kwargs_for(b))
        st.feed(start_trials)
        resolved = compute_registry.resolve(st.designer, 1)
        assert resolved is not None, f"bucket group {b} is unbatchable"
        keys.add(resolved[1])
    return len(keys)


def _run_mesh_arm(
    *,
    mesh,  # MeshConfig | None (None = the single-device seed executor)
    buckets: int,
    studies_per_bucket: int,
    rounds: int,
    warmup_rounds: int,
    start_trials: int,
    problem,
    designer_kwargs_for,
    max_wait_ms: float,
    max_batch_size: int,
) -> dict:
    pool = _make_pool(problem, buckets, studies_per_bucket, designer_kwargs_for)
    for st in pool:
        st.feed(start_trials)
    stats = ServingStats()
    executor = BatchExecutor(
        max_batch_size=max_batch_size,
        max_wait_ms=max_wait_ms,
        stats=stats,
        metrics=stats.registry,
        mesh=mesh,
    )

    latencies: list = []
    lat_lock = threading.Lock()
    warm_snapshot = {}

    def one_suggest(st: _Study, record: bool):
        t0 = time.perf_counter()
        out = executor.suggest(st.designer, 1)
        dt = time.perf_counter() - t0
        if record:
            with lat_lock:
                latencies.append(dt)
        return out

    barrier = threading.Barrier(len(pool) + 1)

    def client(st: _Study):
        for _ in range(warmup_rounds):
            st.complete_suggestion(one_suggest(st, record=False)[0])
        barrier.wait()  # compiles paid; measurement starts together
        for _ in range(rounds):
            st.complete_suggestion(one_suggest(st, record=True)[0])

    threads = [threading.Thread(target=client, args=(st,)) for st in pool]
    for t in threads:
        t.start()
    barrier.wait()
    warm_snapshot = stats.snapshot()
    t_start = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    placement_flushes = executor.placement_flush_counts()
    bucket_placements = executor.bucket_placements()
    executor.close()

    latencies.sort()
    snap = stats.snapshot()
    measured = {
        k: snap.get(k, 0) - warm_snapshot.get(k, 0)
        for k in ("batch_flushes", "batched_suggests", "mesh_flushes")
    }
    total = len(pool) * rounds
    occupancy = (
        measured["batched_suggests"] / measured["batch_flushes"]
        if measured["batch_flushes"]
        else 1.0
    )
    return {
        "mesh": bool(mesh is not None and mesh.enabled),
        "suggest_p50_ms": round(_percentile(latencies, 50) * 1e3, 1),
        "suggest_p95_ms": round(_percentile(latencies, 95) * 1e3, 1),
        "suggest_p99_ms": round(_percentile(latencies, 99) * 1e3, 1),
        "throughput_suggestions_per_sec": round(total / wall, 3),
        "flush_throughput_per_sec": round(
            measured["batch_flushes"] / wall, 3
        )
        if measured["batch_flushes"]
        else 0.0,
        "wall_secs": round(wall, 2),
        "suggestions": total,
        "measured_flushes": measured["batch_flushes"],
        "mean_batch_occupancy": round(occupancy, 2),
        "placement_flushes": placement_flushes,
        "bucket_placements": bucket_placements,
        "batch_stats": {k: v for k, v in snap.items() if k.startswith(("batch", "mesh"))},
    }


def _mesh_off_bit_identity(problem, designer_kwargs) -> bool:
    """``VIZIER_MESH=0`` (MeshConfig.from_env with the switch unset) must
    route through the byte-identical seed executor: same concurrent
    workload, slot-for-slot equal suggestions."""

    def run(mesh):
        pool = [
            _Study(problem, seed=s + 1, designer_kwargs=designer_kwargs)
            for s in range(3)
        ]
        for st in pool:
            st.feed(9)
        executor = BatchExecutor(max_batch_size=8, max_wait_ms=30.0, mesh=mesh)
        outs = [None] * len(pool)

        def one(i):
            outs[i] = executor.suggest(pool[i].designer, 1)

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(len(pool))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        executor.close()
        return [s.parameters.as_dict() for out in outs for s in out]

    return run(None) == run(MeshConfig.from_env())


def run_mesh_ab(args) -> None:
    problem = _problem(args.dim)
    from vizier_tpu.converters import padding as padding_lib

    schedule = padding_lib.DEFAULT_PADDING
    end_trials = args.start_trials + args.warmup_rounds + args.rounds
    if schedule.pad_trials(args.start_trials) != schedule.pad_trials(end_trials):
        raise SystemExit(
            f"start_trials={args.start_trials} grows to {end_trials} across "
            "a padding-bucket boundary; shrink --rounds or move "
            "--start-trials."
        )

    def designer_kwargs_for(bucket_index: int) -> dict:
        # Distinct acquisition budgets -> distinct vec_opt jit statics ->
        # pairwise-distinct shape buckets with near-identical per-slot cost
        # (the budget delta is < 1%).
        return dict(
            max_acquisition_evaluations=args.max_evals + 8 * bucket_index,
            ard_restarts=args.ard_restarts,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=args.ard_maxiter),
        )

    distinct = _distinct_buckets(
        problem, args.buckets, designer_kwargs_for, args.start_trials
    )
    assert distinct == args.buckets, (distinct, args.buckets)
    mesh_config = MeshConfig(
        enabled=True,
        num_devices=args.devices,
        shard_devices=args.shard_devices,
    )
    config = dict(
        devices=args.devices,
        shard_devices=args.shard_devices,
        buckets=args.buckets,
        studies_per_bucket=args.studies_per_bucket,
        rounds=args.rounds,
        warmup_rounds=args.warmup_rounds,
        start_trials=args.start_trials,
        dim=args.dim,
        designer="VizierGPUCBPEBandit",
        max_acquisition_evaluations=args.max_evals,
        ard_maxiter=args.ard_maxiter,
        ard_restarts=args.ard_restarts,
        max_wait_ms=args.max_wait_ms,
        max_batch_size=8,
        backend=os.environ.get("JAX_PLATFORMS", ""),
        xla_flags=os.environ.get("XLA_FLAGS", ""),
    )

    arms = {}
    for name, mesh in (("single_device", None), ("mesh", mesh_config)):
        print(f"[batching_ab] running mesh arm: {name}", flush=True)
        arms[name] = _run_mesh_arm(
            mesh=mesh,
            buckets=args.buckets,
            studies_per_bucket=args.studies_per_bucket,
            rounds=args.rounds,
            warmup_rounds=args.warmup_rounds,
            start_trials=args.start_trials,
            problem=problem,
            designer_kwargs_for=designer_kwargs_for,
            max_wait_ms=args.max_wait_ms,
            max_batch_size=8,
        )
        print(f"[batching_ab] {name}: {json.dumps(arms[name])}", flush=True)

    print("[batching_ab] checking VIZIER_MESH=0 bit-identity", flush=True)
    bit_identical = _mesh_off_bit_identity(problem, designer_kwargs_for(0))

    on, off = arms["mesh"], arms["single_device"]
    flush_speedup = on["flush_throughput_per_sec"] / max(
        off["flush_throughput_per_sec"], 1e-9
    )
    speedup = on["throughput_suggestions_per_sec"] / max(
        off["throughput_suggestions_per_sec"], 1e-9
    )
    report = {
        "config": config,
        "single_device": off,
        "mesh": on,
        "verdict": {
            "flush_throughput_speedup": round(flush_speedup, 2),
            "throughput_speedup": round(speedup, 2),
            "meets_2x_at_8_devices": bool(
                flush_speedup >= 2.0
                and args.devices >= 8
                and args.buckets >= 8
            ),
            "concurrent_buckets": args.buckets,
            "mesh_off_bit_identical": bool(bit_identical),
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["verdict"], indent=2))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--studies", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--warmup-rounds", type=int, default=1)
    # 9 completed trials land in the pad_trials=16 bucket; one warmup plus
    # six measured rounds grow each study to 16 — the whole run stays on
    # one compiled program per arm (no mid-measurement bucket crossing).
    parser.add_argument("--start-trials", type=int, default=9)
    parser.add_argument("--dim", type=int, default=4)
    parser.add_argument("--max-evals", type=int, default=2000)
    parser.add_argument("--ard-maxiter", type=int, default=30)
    parser.add_argument("--ard-restarts", type=int, default=4)
    parser.add_argument("--max-wait-ms", type=float, default=50.0)
    # Mesh arm (writes MESH_AB.json instead of the classic A/B).
    parser.add_argument(
        "--devices",
        type=int,
        default=0,
        help="mesh A/B over N (simulated) devices; 0 = classic batching A/B",
    )
    parser.add_argument("--buckets", type=int, default=8)
    parser.add_argument("--studies-per-bucket", type=int, default=2)
    parser.add_argument(
        "--shard-devices",
        type=int,
        default=1,
        help="devices per placement submesh in the mesh arm",
    )
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.devices:
        args.out = args.out or "MESH_AB.json"
        run_mesh_ab(args)
        return
    args.out = args.out or "BATCHING_AB.json"

    problem = _problem(args.dim)
    # Guard the one-bucket invariant: a bucket boundary inside the measured
    # rounds would time an XLA recompile instead of steady-state serving.
    from vizier_tpu.converters import padding as padding_lib

    schedule = padding_lib.DEFAULT_PADDING
    end_trials = args.start_trials + args.warmup_rounds + args.rounds
    if schedule.pad_trials(args.start_trials) != schedule.pad_trials(end_trials):
        raise SystemExit(
            f"start_trials={args.start_trials} grows to {end_trials} across a "
            f"padding-bucket boundary ({schedule.pad_trials(args.start_trials)}"
            f" -> {schedule.pad_trials(end_trials)}); shrink --rounds or move "
            "--start-trials so the whole run stays on one compiled program."
        )
    designer_kwargs = dict(
        max_acquisition_evaluations=args.max_evals,
        ard_restarts=args.ard_restarts,
        ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=args.ard_maxiter),
    )
    # Keep every round inside ONE padding bucket so both arms stay on one
    # compiled program after warmup (start + warmup + rounds <= next bucket).
    config = dict(
        studies=args.studies,
        rounds=args.rounds,
        warmup_rounds=args.warmup_rounds,
        start_trials=args.start_trials,
        dim=args.dim,
        designer="VizierGPUCBPEBandit",
        max_acquisition_evaluations=args.max_evals,
        ard_maxiter=args.ard_maxiter,
        ard_restarts=args.ard_restarts,
        max_wait_ms=args.max_wait_ms,
        backend=os.environ.get("JAX_PLATFORMS", ""),
    )

    arms = {}
    for name, batching in (("batching_off", False), ("batching_on", True)):
        print(f"[batching_ab] running arm: {name}", flush=True)
        arms[name] = _run_arm(
            batching=batching,
            studies=args.studies,
            rounds=args.rounds,
            warmup_rounds=args.warmup_rounds,
            start_trials=args.start_trials,
            problem=problem,
            designer_kwargs=designer_kwargs,
            max_wait_ms=args.max_wait_ms,
        )
        print(f"[batching_ab] {name}: {json.dumps(arms[name])}", flush=True)

    on, off = arms["batching_on"], arms["batching_off"]
    speedup = (
        on["throughput_suggestions_per_sec"]
        / max(off["throughput_suggestions_per_sec"], 1e-9)
    )
    report = {
        "config": config,
        "batching_off": off,
        "batching_on": on,
        "verdict": {
            "throughput_speedup": round(speedup, 2),
            "meets_2x_at_8_studies": bool(
                speedup >= 2.0 and args.studies >= 8
            ),
            "mean_batch_occupancy": on["mean_batch_occupancy"],
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["verdict"], indent=2))


if __name__ == "__main__":
    main()
