#!/usr/bin/env python
"""Full-stack continuous soak: production-shaped traffic → SOAK_REPORT.json.

Drives the loadgen engine (``vizier_tpu/loadgen/``) end to end:

1. **engine arm** — the scenario's full traffic (open-loop arrivals, Zipf
   study sizes, tenant + program-kind mixes across every registered
   DesignerProgram, scripted kill/revive + chaos windows) against the
   configured target (N-replica sharded tier by default) with the
   scenario's serving planes armed (speculation + batching + mesh + SLO
   on the acceptance scenario);
2. **reference arm** — the parity cohort re-run sequentially, in-process,
   every plane gated off: the seed-path ground truth;
3. **gated-off arm** — the engine itself with every plane off on the same
   cohort, asserted bit-identical to the reference.

The assertion engine rolls all three into ``SOAK_REPORT.json`` (regret
parity rank-sum, zero lost studies, failover completeness, speculative
hit rate, fallback rate, SLO p99 verdicts, bit-identity) and this CLI
exits nonzero when any assertion fails — the regression net the
defaults-ON campaign runs behind.

Usage:
    python tools/soak.py                     # acceptance-scale soak
    python tools/soak.py --smoke             # seconds-scale CI shape
    python tools/soak.py --studies 200 --replicas 4 --mesh-devices 4
    python tools/soak.py --diff A.json B.json   # compare two reports

``--diff`` compares two SOAK_REPORTs (the defaults-ON before/after
campaign gate): per-kind latency deltas, assertion verdict changes,
speculative hit-rate / fallback-rate deltas — exits nonzero on any
regression (an assertion flipping pass→fail, a hit-rate drop, a
fallback rise).

Scenario seed/scale/studies/target/events can also come from the
``VIZIER_LOADGEN*`` environment switches (docs/guides/loadtest.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")


def _peek_int_flag(name: str, default: int) -> int:
    """Reads an int flag from argv BEFORE jax-importing modules below (the
    mesh plane needs --xla_force_host_platform_device_count set before
    jax's backend initializes)."""
    for i, arg in enumerate(sys.argv):
        if arg == name and i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
        if arg.startswith(name + "="):
            return int(arg.split("=", 1)[1])
    return default


_MESH_DEVICES = _peek_int_flag("--mesh-devices", 0)
if _MESH_DEVICES:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags
            + f" --xla_force_host_platform_device_count={_MESH_DEVICES}"
        ).strip()

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu.loadgen import driver as driver_lib  # noqa: E402
from vizier_tpu.loadgen import models  # noqa: E402
from vizier_tpu.loadgen import report as report_lib  # noqa: E402


def _stamps() -> dict:
    """Provenance stamps (same families bench.py records)."""
    import jax

    from vizier_tpu.compute import registry as compute_registry

    return {
        "backend": jax.default_backend(),
        "visible_devices": jax.device_count(),
        "compute_programs": list(compute_registry.kinds()),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="the seconds-scale CI scenario instead of the acceptance soak",
    )
    parser.add_argument("--studies", type=int, default=0,
                        help="override the scenario study count")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--scale", type=float, default=None)
    parser.add_argument("--target",
                        choices=("inprocess", "replicas", "subprocess",
                                 "shared_compute"),
                        default=None)
    parser.add_argument(
        "--compute-tier",
        action="store_true",
        help="run the subprocess fleet behind ONE shared Pythia compute "
        "server (the disaggregated tier): every replica_main frontend is "
        "spawned with --compute-endpoint, so suggest traffic crosses the "
        "remote hop and fuses in the shared batch executor. Shorthand "
        "for --target shared_compute; the scripted event track gains "
        "kill_compute/revive_compute events.",
    )
    parser.add_argument(
        "--replica-mode",
        choices=("inprocess", "subprocess"),
        default="inprocess",
        help="'subprocess' runs the replica tier as REAL replica_main "
        "processes behind the lease-based SubprocessReplicaManager "
        "(cross-process standby replication over gRPC; kill/revive are "
        "SIGKILL + fenced restart) — the severity track against real "
        "processes. Parity/bit-identity assertions are waived for this "
        "mode (per-study seeding cannot cross the process boundary); "
        "the in-process default keeps them, and stays the tier-1 shape.",
    )
    parser.add_argument("--replicas", type=int, default=0)
    parser.add_argument("--concurrency", type=int, default=0)
    parser.add_argument(
        "--events",
        default=None,
        help="event track: comma-separated kind[:arg]@fraction entries "
        "(default: the scenario's built-in kill/revive + chaos track)",
    )
    parser.add_argument(
        "--mesh-devices",
        type=int,
        default=0,
        metavar="N",
        help="simulate N XLA host devices for the mesh plane (0 = leave "
        "the backend alone)",
    )
    parser.add_argument(
        "--think-time", type=float, default=None,
        help="per-GP-trial evaluation window in seconds",
    )
    parser.add_argument(
        "--skip-reference",
        action="store_true",
        help="engine arm only (parity/bit-identity assertions then FAIL "
        "— for iterating on scenarios, not for evidence)",
    )
    parser.add_argument(
        "--diff",
        nargs=2,
        metavar=("A.json", "B.json"),
        default=None,
        help="compare two SOAK_REPORTs (A = before, B = after) instead "
        "of running a soak; exits nonzero on regression",
    )
    parser.add_argument(
        "--diff-out",
        default="",
        help="optional path for the --diff JSON result",
    )
    parser.add_argument(
        "--out",
        default=str(
            pathlib.Path(__file__).resolve().parent.parent / "SOAK_REPORT.json"
        ),
    )
    args = parser.parse_args()

    if args.diff:
        before = json.loads(pathlib.Path(args.diff[0]).read_text())
        after = json.loads(pathlib.Path(args.diff[1]).read_text())
        diff = report_lib.diff_reports(before, after)
        print(report_lib.render_diff(diff))
        if args.diff_out:
            pathlib.Path(args.diff_out).write_text(
                json.dumps(diff, indent=2) + "\n"
            )
            print(f"[soak] wrote {args.diff_out}")
        if not diff["ok"]:
            sys.exit(1)
        return

    # Fast client polling: the soak measures fleet behavior, not the
    # client's long-poll sleep cadence.
    from vizier_tpu.service import vizier_client

    vizier_client.environment_variables.polling_delay_secs = 0.005

    overrides = {}
    if args.seed is not None:
        overrides["seed"] = args.seed
    if args.scale is not None:
        overrides["scale"] = args.scale
    if args.studies:
        overrides["num_studies"] = args.studies
    if args.target:
        overrides["target"] = args.target
    if args.replica_mode == "subprocess" and overrides.get(
        "target", "replicas"
    ) != "inprocess":
        overrides["target"] = "subprocess"
    if args.compute_tier:
        overrides["target"] = "shared_compute"
    if args.replicas:
        overrides["replicas"] = args.replicas
    if args.concurrency:
        overrides["concurrency"] = args.concurrency
    if args.think_time is not None:
        overrides["think_time_s"] = args.think_time

    base = models.smoke_config if args.smoke else models.soak_config
    config = base(**{**_env_overrides(), **overrides})
    if (
        config.target in ("subprocess", "shared_compute")
        and not args.skip_reference
    ):
        # Parity/bit-identity are waived for subprocess tiers (see
        # --replica-mode help); the sequential arms would only burn the
        # wall clock the real-process severity track needs.
        args.skip_reference = True
        print(f"[soak] {config.target} tier: reference/gated arms skipped "
              "(parity assertions waived)", flush=True)
    if args.mesh_devices:
        config = dataclasses.replace(
            config,
            planes=dataclasses.replace(config.planes, mesh=True),
        )
    from vizier_tpu.analysis import registry as _registry

    env_track = _registry.env_str("VIZIER_LOADGEN_EVENTS")
    track = args.events if args.events is not None else env_track
    if track:
        config = dataclasses.replace(
            config, events=models.parse_event_track(track, config)
        )
    scenario = models.build_scenario(config)

    print(
        f"[soak] scenario {config.name!r}: {len(scenario.studies)} studies / "
        f"{scenario.total_trials} trials, kinds {scenario.kinds_present()}, "
        f"target {config.target} x{config.replicas}, planes "
        f"{config.planes.as_dict()}",
        flush=True,
    )
    t0 = time.time()
    engine = driver_lib.run(scenario, arm="engine")
    print(
        f"[soak] engine arm done in {engine.wall_s}s "
        f"(events fired: {[e['kind'] for e in engine.events_fired]})",
        flush=True,
    )
    reference = gated = None
    if not args.skip_reference:
        reference = driver_lib.run_reference(scenario)
        print(f"[soak] reference arm done in {reference.wall_s}s", flush=True)
        gated = driver_lib.run_gated_off(scenario)
        print(f"[soak] gated-off arm done in {gated.wall_s}s", flush=True)

    report = report_lib.build_report(
        scenario, engine, reference, gated, stamps=_stamps()
    )
    report["wall_seconds_total"] = round(time.time() - t0, 1)
    out_path = pathlib.Path(args.out)
    out_path.write_text(json.dumps(report, indent=2) + "\n")
    print(report_lib.render_verdict(report))
    print(f"[soak] wrote {out_path}")
    if not report["ok"]:
        sys.exit(1)


def _env_overrides() -> dict:
    """VIZIER_LOADGEN* env values as preset overrides (CLI flags win)."""
    from vizier_tpu.analysis import registry as _registry

    out = {
        "seed": _registry.env_int("VIZIER_LOADGEN_SEED", 0),
        "scale": _registry.env_float("VIZIER_LOADGEN_SCALE", 1.0),
    }
    studies = _registry.env_int("VIZIER_LOADGEN_STUDIES", 0)
    if studies:
        out["num_studies"] = studies
    target = _registry.env_str("VIZIER_LOADGEN_TARGET")
    if target:
        out["target"] = target
    return out


if __name__ == "__main__":
    main()
