"""Exports the real NASBench-101 dataset into this repo's table format.

Usage (on a machine with the `nasbench` package + its TFRecord dataset):

    python tools/export_nasbench101.py \
        --dataset /path/to/nasbench_only108.tfrecord \
        --out nasbench101_table.json

The output is the hash→metrics JSON that
``vizier_tpu.benchmarks.experimenters.nasbench101.TabularNASBench101.from_file``
serves, keyed by THIS repo's ``ModelSpec.graph_hash`` (recomputed from each
entry's matrix/ops so the lookup key and the experimenter's encoding always
agree — the upstream package's own hashes are not reused).

Both the package and the dataset are absent from this image by design; the
tool is data-gated and exits with a clear message without them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", required=True, help="NASBench-101 TFRecord path")
    ap.add_argument("--out", default="nasbench101_table.json")
    ap.add_argument(
        "--epochs", type=int, default=108, help="Training-epoch budget to export"
    )
    args = ap.parse_args()

    try:
        from nasbench import api  # type: ignore
    except ImportError:
        raise SystemExit(
            "The `nasbench` package is not installed (and is not bundled in "
            "this image). Run this export on a machine that has it plus the "
            "public dataset, then ship the JSON."
        )
    if not os.path.exists(args.dataset):
        raise SystemExit(f"Dataset not found: {args.dataset}")

    from vizier_tpu.benchmarks.experimenters import nasbench101 as nb

    nasbench = api.NASBench(args.dataset)
    table = {}
    skipped = 0
    collisions = 0
    for upstream_hash in nasbench.hash_iterator():
        fixed, computed = nasbench.get_metrics_from_hash(upstream_hash)
        spec = nb.ModelSpec(
            matrix=fixed["module_adjacency"],
            ops=list(fixed["module_operations"]),
        )
        h = spec.graph_hash()
        if h == "invalid":
            skipped += 1
            continue
        runs = computed[args.epochs]
        # Average over the dataset's repeated training runs (3 per cell).
        def avg(key):
            return float(sum(r[key] for r in runs) / len(runs))

        entry = {
            "trainable_parameters": float(fixed["trainable_parameters"]),
            "training_time": avg("final_training_time"),
            "train_accuracy": avg("final_train_accuracy"),
            "validation_accuracy": avg("final_validation_accuracy"),
            "test_accuracy": avg("final_test_accuracy"),
        }
        # The WL-style hash could in principle collide for non-isomorphic
        # cells; a silent overwrite would merge distinct cells' metrics.
        # Count and report collisions (differing metrics under one hash) so
        # a hash weakness is observable in the export log.
        prior = table.get(h)
        if prior is not None and prior != entry:
            collisions += 1
            print(f"WARNING: hash collision with differing metrics: {h}")
        table[h] = entry
    with open(args.out, "w") as f:
        json.dump(table, f)
    print(
        f"Exported {len(table)} cells to {args.out} "
        f"({skipped} skipped as disconnected, {collisions} hash collisions)."
    )


if __name__ == "__main__":
    main()
