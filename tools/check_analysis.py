#!/usr/bin/env python
"""CLI for the static-analysis suite (lock order / JAX discipline / env registry).

Usage:
    python tools/check_analysis.py                  # all passes, repo config
    python tools/check_analysis.py --pass lock_order --verbose
    python tools/check_analysis.py --paths vizier_tpu/serving --json
    python tools/check_analysis.py --dump-graph     # lock graph as text

Exit code 0 iff every finding is baselined (``--strict-baseline`` also
fails on stale baseline entries). Configuration comes from the
``[tool.vizier_analysis]`` section of pyproject.toml; flags override it.
Stdlib-only: runs without jax installed.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from vizier_tpu.analysis import suite as suite_lib  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=list(suite_lib.ALL_PASSES),
        help="Run only this pass (repeatable; default: configured passes).",
    )
    parser.add_argument(
        "--paths",
        nargs="+",
        help="Override the configured scan roots (repo-relative).",
    )
    parser.add_argument(
        "--baseline", help="Override the configured baseline file path."
    )
    parser.add_argument(
        "--repo-root", default=_REPO_ROOT, help="Repository root to scan from."
    )
    parser.add_argument(
        "--json", action="store_true", help="Machine-readable findings dump."
    )
    parser.add_argument(
        "--verbose", action="store_true", help="Also list baselined findings."
    )
    parser.add_argument(
        "--strict-baseline",
        action="store_true",
        help="Fail on stale baseline entries too.",
    )
    parser.add_argument(
        "--dump-graph",
        action="store_true",
        help="Print the static lock acquisition graph and exit status as usual.",
    )
    args = parser.parse_args(argv)

    config = suite_lib.load_config(args.repo_root)
    if args.paths:
        config.paths = list(args.paths)
    if args.baseline:
        config.baseline = args.baseline

    t0 = time.perf_counter()
    result = suite_lib.run_suite(args.repo_root, config, passes=args.passes)
    elapsed = time.perf_counter() - t0

    failed = bool(result.new_findings) or bool(result.parse_errors)
    if args.strict_baseline and result.stale_baseline:
        failed = True

    if args.json:
        payload = {
            "ok": not failed,
            "elapsed_seconds": round(elapsed, 3),
            "passes": {
                name: {
                    "new": [dataclasses.asdict(f) for f in p.new],
                    "baselined": [dataclasses.asdict(f) for f in p.accepted],
                }
                for name, p in result.passes.items()
            },
            "stale_baseline": [
                dataclasses.asdict(e) for e in result.stale_baseline
            ],
            "parse_errors": result.parse_errors,
        }
        if result.lock_result is not None:
            payload["lock_graph"] = {
                "sites": [
                    dataclasses.asdict(s) for s in result.lock_result.sites
                ],
                "edges": [
                    dataclasses.asdict(e) for e in result.lock_result.edges
                ],
            }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(suite_lib.format_report(result, verbose=args.verbose))
        if args.dump_graph and result.lock_result is not None:
            print("\nlock sites:")
            for site in result.lock_result.sites:
                mark = " (factory)" if site.factory else ""
                print(
                    f"  {site.lock_id:45s} {site.kind:9s} "
                    f"{site.path}:{site.line}{mark}"
                )
            print("lock acquisition edges (src held -> dst acquired):")
            for edge in result.lock_result.edges:
                print(f"  {edge.src} -> {edge.dst}   via {edge.via}")
        print(f"({elapsed:.2f}s)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
