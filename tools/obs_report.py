#!/usr/bin/env python
"""Renders a per-phase latency breakdown from a JSON-lines span file.

The input is what ``Tracer.dump_jsonl()`` (or the
``VIZIER_OBSERVABILITY_SPAN_LOG`` sink) writes: one span per line. The
report groups spans by name and prints count, p50/p95/p99/max wall time,
and total time — the "where does a suggest spend its time" table.

Usage:
    python tools/obs_report.py SPANS.jsonl              # per-phase table
    python tools/obs_report.py SPANS.jsonl --trace ID   # one trace's tree
    python tools/obs_report.py SPANS.jsonl --json       # machine-readable
    python tools/obs_report.py --slo METRICS.json       # SLO burn rates
    python tools/obs_report.py --fleet DUMP_DIR         # merged fleet view

``--slo`` reads a ``MetricsRegistry.snapshot()`` JSON dump and renders the
``vizier_slo_*`` gauge families (burn rates per window, breached SLOs,
per-placement mesh utilization). ``--fleet`` reads a dump directory of
per-replica ``<replica>-{spans.jsonl,metrics.json,recorder.json}`` files
(``replica_main --obs-dump-dir`` / ``ReplicaManager.dump_observability``)
and prints the merged cross-replica traces + failover timeline. Both
compose with ``--json`` (the report gains ``slo``/``fleet`` sections).

Stdlib-only; percentiles here are exact (computed from the raw span
durations, not histogram buckets — the spans ARE the samples).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

# Make the repo importable when invoked as `python tools/obs_report.py`
# (the registry-driven phase classification needs vizier_tpu; everything
# else stays stdlib-only and degrades gracefully without it).
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def load_spans(path: str) -> List[dict]:
    """Parses a JSON-lines span file; skips blank/corrupt lines loudly."""
    spans: List[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                span = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"[obs_report] skipping line {lineno}: {e}", file=sys.stderr)
                continue
            if isinstance(span, dict) and "name" in span:
                spans.append(span)
    return spans


def _percentile(sorted_values: List[float], q: float) -> float:
    """Linear-interpolated percentile of pre-sorted values (q in [0,100])."""
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return sorted_values[0]
    rank = (q / 100.0) * (len(sorted_values) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = rank - lo
    return sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac


def phase_breakdown(spans: List[dict]) -> List[dict]:
    """Per-span-name latency stats, sorted by total time descending."""
    by_name: Dict[str, List[float]] = {}
    occupancy: Dict[str, List[float]] = {}
    for span in spans:
        duration = span.get("duration_secs")
        if duration is None:
            continue
        by_name.setdefault(span["name"], []).append(float(duration))
        # Cross-study batching occupancy: batch_executor.flush spans carry
        # how many real studies shared the dispatch; member suggest spans
        # carry batch_occupancy. Either way it rolls into a mean per phase.
        attrs = span.get("attributes") or {}
        occ = attrs.get("occupancy", attrs.get("batch_occupancy"))
        if isinstance(occ, (int, float)):
            occupancy.setdefault(span["name"], []).append(float(occ))
    out = []
    for name, durations in by_name.items():
        durations.sort()
        row = {
            "phase": name,
            "count": len(durations),
            "p50_ms": _percentile(durations, 50) * 1e3,
            "p95_ms": _percentile(durations, 95) * 1e3,
            "p99_ms": _percentile(durations, 99) * 1e3,
            "max_ms": durations[-1] * 1e3,
            "total_ms": sum(durations) * 1e3,
        }
        occ_samples = occupancy.get(name)
        if occ_samples:
            row["mean_occupancy"] = sum(occ_samples) / len(occ_samples)
        out.append(row)
    out.sort(key=lambda row: row["total_ms"], reverse=True)
    return out


# Device-phase span prefixes per surrogate path, sourced from the
# compute-IR program registry (each registered DesignerProgram declares
# its device_phase + surrogate_family): a new program's phases classify
# correctly the moment it registers, no report edit. The static fallback
# keeps this tool stdlib-runnable on span files from machines where the
# runtime tree (jax) is not importable.
_FALLBACK_SPARSE_PHASES = ("jax.sparse_gp.", "sparse_gp.")
_FALLBACK_EXACT_PHASES = (
    "jax.gp_bandit.", "jax.gp_ucb_pe.", "gp_bandit.", "gp_ucb_pe.",
)
# device_phase ("sparse_gp.ucb_pe_suggest_batched") -> program kind, for
# the per-program-kind breakdown (populated from the registry; empty on
# fallback).
_KIND_BY_PHASE: Dict[str, str] = {}


def _phase_families():
    """(sparse_prefixes, exact_prefixes) from the program registry."""
    try:
        from vizier_tpu.compute import registry as compute_registry

        sparse, exact = set(), set()
        for program in compute_registry.programs():
            family = sparse if program.surrogate_family == "sparse" else exact
            prefix = program.device_phase.split(".")[0] + "."
            family.add(prefix)
            family.add("jax." + prefix)
            _KIND_BY_PHASE[program.device_phase] = program.kind
            _KIND_BY_PHASE["jax." + program.device_phase] = program.kind
        if sparse or exact:
            return tuple(sorted(sparse)), tuple(sorted(exact))
    except Exception:  # no jax / no tree: stay stdlib-runnable
        pass
    return _FALLBACK_SPARSE_PHASES, _FALLBACK_EXACT_PHASES


def surrogate_activity(spans: List[dict]) -> dict:
    """Which surrogate path(s) produced this span file's device phases.

    Counts device-phase spans by family so every report says whether its
    numbers came from the exact O(n³) path, the sparse inducing-point
    path, or a mix (auto-switched studies mid-file).
    """
    sparse_phases, exact_phases = _phase_families()
    counts = {"exact": 0, "sparse": 0}
    for span in spans:
        name = span.get("name", "")
        if any(name.startswith(p) for p in sparse_phases):
            counts["sparse"] += 1
        elif any(name.startswith(p) for p in exact_phases):
            counts["exact"] += 1
    if counts["sparse"] and counts["exact"]:
        mode = "mixed"
    elif counts["sparse"]:
        mode = "sparse"
    elif counts["exact"]:
        mode = "exact"
    else:
        mode = "none"
    return {"mode": mode, **counts}


def program_kind_activity(spans: List[dict]) -> Dict[str, dict]:
    """Per-program-kind flush breakdown, keyed by registered kind.

    Maps batched device-phase spans back to the DesignerProgram that
    emitted them via the registry (requires the runtime tree; empty dict
    on the stdlib fallback), so the report answers "which program kinds
    carried this workload, and how much device time each took".
    """
    _phase_families()  # populate _KIND_BY_PHASE from the registry
    if not _KIND_BY_PHASE:
        return {}
    out: Dict[str, dict] = {}
    for span in spans:
        kind = _KIND_BY_PHASE.get(span.get("name", ""))
        if kind is None:
            continue
        duration = float(span.get("duration_secs") or 0.0)
        row = out.setdefault(kind, {"flushes": 0, "total_ms": 0.0})
        row["flushes"] += 1
        row["total_ms"] += duration * 1e3
    for row in out.values():
        row["total_ms"] = round(row["total_ms"], 2)
    return out


def device_activity(spans: List[dict]) -> Dict[str, dict]:
    """Per-device (mesh placement) flush breakdown.

    Mesh-mode flush spans (``batch_executor.flush``) carry a ``device``
    attribute naming the placement that executed them; this rolls those up
    into flush count, busy time, and mean occupancy per placement — the
    "is the mesh actually balanced" view. Empty when the span file came
    from a single-device run (VIZIER_MESH=0 stamps no device attribute).
    """
    out: Dict[str, dict] = {}
    occ: Dict[str, List[float]] = {}
    for span in spans:
        if span.get("name") != "batch_executor.flush":
            continue
        attrs = span.get("attributes") or {}
        device = attrs.get("device")
        if device is None:
            continue
        row = out.setdefault(device, {"flushes": 0, "busy_ms": 0.0})
        row["flushes"] += 1
        row["busy_ms"] += float(span.get("duration_secs") or 0.0) * 1e3
        occupancy = attrs.get("occupancy")
        if isinstance(occupancy, (int, float)):
            occ.setdefault(device, []).append(float(occupancy))
    for device, row in out.items():
        row["busy_ms"] = round(row["busy_ms"], 2)
        samples = occ.get(device)
        if samples:
            row["mean_occupancy"] = round(sum(samples) / len(samples), 2)
    return out


def speculative_activity(spans: List[dict]) -> dict:
    """Hit/miss/stale serving outcomes plus pre-compute counts.

    Serve outcomes ride ``speculative.*`` events on the request-path spans
    (pythia.suggest and children); the background jobs are their own
    ``speculative.precompute`` spans with an ``outcome`` attribute. A file
    with no speculative activity reports all-zero (the default,
    VIZIER_SPECULATIVE=0).
    """
    counts = {"hit": 0, "miss": 0, "stale": 0, "precomputes": 0, "stored": 0}
    for span in spans:
        if span.get("name") == "speculative.precompute":
            counts["precomputes"] += 1
            if (span.get("attributes") or {}).get("outcome") == "stored":
                counts["stored"] += 1
        for event in span.get("events") or []:
            name = event.get("name", "")
            if name.startswith("speculative."):
                outcome = name.split(".", 1)[1]
                if outcome in ("hit", "miss", "stale"):
                    counts[outcome] += 1
    served = counts["hit"] + counts["miss"] + counts["stale"]
    counts["hit_rate"] = round(counts["hit"] / served, 4) if served else 0.0
    return counts


_LABEL_RE = None  # compiled lazily; obs_report imports stay minimal


def _parse_label_str(label_str: str) -> Dict[str, str]:
    """``{slo="x",window="60s"}`` -> {"slo": "x", "window": "60s"}."""
    global _LABEL_RE
    if _LABEL_RE is None:
        import re

        _LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    return {
        key: value.replace('\\"', '"').replace("\\\\", "\\")
        for key, value in _LABEL_RE.findall(label_str)
    }


def slo_activity(metrics_snapshot: dict) -> dict:
    """The SLO engine's export surface, from a registry snapshot dump.

    Parses the ``vizier_slo_*`` gauge families (what ``SloEngine``
    exports) into burn rates / windowed values per (slo, window), the
    breached set, and the per-placement mesh-utilization shares. A dump
    from an unarmed process reports ``{"armed": False}``.
    """
    out = {
        "armed": False,
        "burn_rates": {},
        "values": {},
        "breached": [],
        "mesh_utilization": {},
        "evaluations": 0,
    }
    if not isinstance(metrics_snapshot, dict):
        return out

    def _series(name):
        family = metrics_snapshot.get(name)
        return family.get("series", {}) if isinstance(family, dict) else {}

    for label_str, value in _series("vizier_slo_burn_rate").items():
        labels = _parse_label_str(label_str)
        out["armed"] = True
        out["burn_rates"].setdefault(labels.get("slo", "?"), {})[
            labels.get("window", "?")
        ] = value
    for label_str, value in _series("vizier_slo_value").items():
        labels = _parse_label_str(label_str)
        out["armed"] = True
        out["values"].setdefault(labels.get("slo", "?"), {})[
            labels.get("window", "?")
        ] = value
    for label_str, value in _series("vizier_slo_breached").items():
        out["armed"] = True
        if value:
            out["breached"].append(_parse_label_str(label_str).get("slo", "?"))
    for label_str, value in _series("vizier_slo_mesh_utilization").items():
        out["mesh_utilization"][
            _parse_label_str(label_str).get("device", "?")
        ] = value
    for _label_str, value in _series("vizier_slo_evaluations").items():
        out["armed"] = True
        out["evaluations"] += int(value)
    out["breached"].sort()
    return out


def load_metrics(path: str) -> dict:
    """Parses a ``MetricsRegistry.snapshot()`` JSON dump ({} on garbage)."""
    try:
        with open(path) as f:
            loaded = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"[obs_report] cannot read metrics dump {path}: {e}", file=sys.stderr)
        return {}
    return loaded if isinstance(loaded, dict) else {}


def soak_activity(report: dict) -> dict:
    """Condenses a ``SOAK_REPORT.json`` (tools/soak.py) for the report.

    Stdlib-only: traffic shape, the per-kind outcome table, SLO verdicts,
    and the assertion list — the "did the full-stack soak hold" view.
    """
    out: dict = {
        "ok": bool(report.get("ok")),
        "traffic": {},
        "by_kind": {},
        "slo_breaching": [],
        "events": [],
        "assertions": [],
    }
    traffic = report.get("traffic") or {}
    out["traffic"] = {
        "studies": traffic.get("studies", 0),
        "driven_trials": traffic.get("driven_trials", 0),
        "wall_s": traffic.get("wall_s", 0.0),
        "trials_per_s": traffic.get("achieved_trials_per_s", 0.0),
        "studies_by_kind": traffic.get("studies_by_kind", {}),
        "studies_by_tenant": traffic.get("studies_by_tenant", {}),
        "trial_budget": traffic.get("trial_budget", {}),
    }
    outcomes = (report.get("outcomes") or {}).get("by_kind") or {}
    for kind, row in sorted(outcomes.items()):
        latency = row.get("latency") or {}
        out["by_kind"][kind] = {
            "studies": row.get("studies", 0),
            "suggests": row.get("suggests", 0),
            "errors": row.get("errors", 0),
            "fallback_rate": row.get("fallback_rate", 0.0),
            "hit_rate": row.get("hit_rate", 0.0),
            "p50_ms": latency.get("p50_ms", 0.0),
            "p99_ms": latency.get("p99_ms", 0.0),
        }
    # Per-tenant table (report v2): the fairness view next to the
    # per-kind one — sheds/degraded serves are the admission plane's.
    out["by_tenant"] = {}
    tenants = (report.get("outcomes") or {}).get("by_tenant") or {}
    for tenant, row in sorted(tenants.items()):
        latency = row.get("latency") or {}
        out["by_tenant"][tenant] = {
            "studies": row.get("studies", 0),
            "suggests": row.get("suggests", 0),
            "errors": row.get("errors", 0),
            "sheds": row.get("sheds", 0),
            "degraded": row.get("degraded", 0),
            "p50_ms": latency.get("p50_ms", 0.0),
            "p99_ms": latency.get("p99_ms", 0.0),
        }
    admission = report.get("admission") or {}
    out["admission"] = {
        "armed": bool(admission.get("armed")),
        "shed_rate": admission.get("shed_rate", 0.0),
        "sheds": admission.get("sheds", 0),
        "degraded_serves": admission.get("degraded_serves", 0),
        "state": (admission.get("snapshot") or {}).get("state"),
    }
    slo = report.get("slo") or {}
    out["slo_breaching"] = sorted(slo.get("breaching", []))
    out["slo_armed"] = bool(slo.get("armed"))
    failover = report.get("failover") or {}
    out["events"] = [
        e.get("kind") for e in failover.get("events_fired", [])
    ]
    out["failovers"] = failover.get("failovers", 0)
    out["lost_studies"] = failover.get("lost_studies", [])
    parity = report.get("parity") or {}
    out["parity_ranksum_p"] = parity.get("ranksum_p")
    bit = report.get("bit_identity") or {}
    out["bit_identical"] = bit.get("identical")
    out["assertions"] = [
        {"name": a.get("name"), "ok": bool(a.get("ok"))}
        for a in report.get("assertions", [])
    ]
    return out


def load_soak(path: str) -> dict:
    """Parses a SOAK_REPORT.json ({} on garbage)."""
    try:
        with open(path) as f:
            loaded = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"[obs_report] cannot read soak report {path}: {e}", file=sys.stderr)
        return {}
    return loaded if isinstance(loaded, dict) else {}


def render_soak(soak: dict) -> str:
    traffic = soak.get("traffic", {})
    lines = [
        f"soak: {'PASS' if soak.get('ok') else 'FAIL'} — "
        f"{traffic.get('studies', 0)} studies / "
        f"{traffic.get('driven_trials', 0)} trials in "
        f"{traffic.get('wall_s', 0)}s "
        f"({traffic.get('trials_per_s', 0)} trials/s)"
    ]
    mix = traffic.get("studies_by_kind") or {}
    if mix:
        lines.append(
            "  traffic: "
            + ", ".join(f"{kind}: {n}" for kind, n in sorted(mix.items()))
        )
    by_kind = soak.get("by_kind") or {}
    if by_kind:
        header = (
            f"  {'kind':<20} {'studies':>7} {'suggests':>8} {'err':>4} "
            f"{'fb rate':>8} {'hit rate':>8} {'p50 ms':>9} {'p99 ms':>9}"
        )
        lines.append(header)
        for kind, row in sorted(by_kind.items()):
            lines.append(
                f"  {kind:<20} {row['studies']:>7d} {row['suggests']:>8d} "
                f"{row['errors']:>4d} {row['fallback_rate']:>8.3f} "
                f"{row['hit_rate']:>8.3f} {row['p50_ms']:>9.2f} "
                f"{row['p99_ms']:>9.2f}"
            )
    by_tenant = soak.get("by_tenant") or {}
    if by_tenant:
        lines.append(
            f"  {'tenant':<20} {'studies':>7} {'suggests':>8} {'err':>4} "
            f"{'sheds':>6} {'degr':>5} {'p50 ms':>9} {'p99 ms':>9}"
        )
        for tenant, row in sorted(by_tenant.items()):
            lines.append(
                f"  {tenant:<20} {row['studies']:>7d} {row['suggests']:>8d} "
                f"{row['errors']:>4d} {row['sheds']:>6d} "
                f"{row['degraded']:>5d} {row['p50_ms']:>9.2f} "
                f"{row['p99_ms']:>9.2f}"
            )
    admission = soak.get("admission") or {}
    if admission.get("armed"):
        lines.append(
            f"  admission: state {admission.get('state')}, shed rate "
            f"{admission.get('shed_rate', 0.0)} "
            f"({admission.get('sheds', 0)} sheds, "
            f"{admission.get('degraded_serves', 0)} degraded serves)"
        )
    if soak.get("slo_armed"):
        breaching = soak.get("slo_breaching") or []
        lines.append(
            f"  slo: breached {', '.join(breaching) if breaching else 'none'}"
        )
    if soak.get("events"):
        lines.append(
            f"  events: {', '.join(soak['events'])} "
            f"(failovers {soak.get('failovers', 0)}, lost studies "
            f"{soak.get('lost_studies', [])})"
        )
    verdicts = ", ".join(
        f"{a['name']}={'ok' if a['ok'] else 'FAIL'}"
        for a in soak.get("assertions", [])
    )
    if verdicts:
        lines.append(f"  assertions: {verdicts}")
    return "\n".join(lines)


def fleet_section(dump_dir: str) -> Optional[dict]:
    """The merged fleet report for a dump directory (None when the
    observability package is unimportable — the merge lives there)."""
    try:
        from vizier_tpu.observability import fleet as fleet_lib
    except Exception as e:  # stay runnable even on a broken tree
        print(f"[obs_report] fleet merge unavailable: {e}", file=sys.stderr)
        return None
    return fleet_lib.fleet_report(dump_dir)


def render_slo(slo: dict) -> str:
    if not slo.get("armed"):
        return "slo: not armed (no vizier_slo_* series in the dump)"
    lines = [
        f"slo: {len(slo['burn_rates'])} objectives, "
        f"{slo['evaluations']} evaluations, "
        f"breached: {', '.join(slo['breached']) or 'none'}"
    ]
    for name in sorted(slo["burn_rates"]):
        windows = slo["burn_rates"][name]
        values = slo.get("values", {}).get(name, {})
        per_window = ", ".join(
            f"{window}: burn {burn:.2f}"
            + (f" (value {values[window]:.4g})" if window in values else "")
            for window, burn in sorted(windows.items())
        )
        flag = " [BREACHED]" if name in slo["breached"] else ""
        lines.append(f"  {name:<28} {per_window}{flag}")
    if slo["mesh_utilization"]:
        shares = ", ".join(
            f"{device}: {share:.0%}"
            for device, share in sorted(slo["mesh_utilization"].items())
        )
        lines.append(f"  mesh utilization: {shares}")
    return "\n".join(lines)


def render_table(rows: List[dict]) -> str:
    with_occ = any("mean_occupancy" in row for row in rows)
    header = f"{'phase':<34} {'count':>6} {'p50 ms':>9} {'p95 ms':>9} {'p99 ms':>9} {'max ms':>9} {'total ms':>10}"
    if with_occ:
        header += f" {'occ':>6}"
    lines = [header, "-" * len(header)]
    for row in rows:
        line = (
            f"{row['phase']:<34} {row['count']:>6d} {row['p50_ms']:>9.2f} "
            f"{row['p95_ms']:>9.2f} {row['p99_ms']:>9.2f} {row['max_ms']:>9.2f} "
            f"{row['total_ms']:>10.2f}"
        )
        if with_occ:
            occ = row.get("mean_occupancy")
            line += f" {occ:>6.2f}" if occ is not None else f" {'-':>6}"
        lines.append(line)
    return "\n".join(lines)


def render_trace(spans: List[dict], trace_id: str) -> str:
    """One trace as an indented parent→child tree, time-ordered."""
    trace = [s for s in spans if s.get("trace_id") == trace_id]
    if not trace:
        return f"No spans for trace {trace_id!r}."
    trace.sort(key=lambda s: s.get("start_time", 0.0))
    children: Dict[Optional[str], List[dict]] = {}
    ids = {s["span_id"] for s in trace}
    for span in trace:
        parent = span.get("parent_id")
        # A parent outside the file (ring buffer rolled) renders as a root.
        children.setdefault(parent if parent in ids else None, []).append(span)

    lines: List[str] = [f"trace {trace_id}"]

    def walk(parent_key: Optional[str], depth: int) -> None:
        for span in children.get(parent_key, []):
            duration = span.get("duration_secs") or 0.0
            status = "" if span.get("status", "ok") == "ok" else " [ERROR]"
            events = span.get("events") or []
            event_note = (
                " events=" + ",".join(e["name"] for e in events) if events else ""
            )
            lines.append(
                f"{'  ' * (depth + 1)}{span['name']} "
                f"({duration * 1e3:.2f} ms){status}{event_note}"
            )
            walk(span["span_id"], depth + 1)

    walk(None, 0)
    return "\n".join(lines)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "path", nargs="?", help="JSON-lines span file (optional with --fleet/--slo)"
    )
    parser.add_argument("--trace", help="Render one trace_id as a tree")
    parser.add_argument(
        "--json", action="store_true", help="Emit the breakdown as JSON"
    )
    parser.add_argument(
        "--slo",
        metavar="METRICS_JSON",
        help="MetricsRegistry.snapshot() dump: render the vizier_slo_* "
        "burn rates / breached set",
    )
    parser.add_argument(
        "--fleet",
        metavar="DUMP_DIR",
        help="per-replica dump directory: merged cross-replica traces + "
        "failover timeline",
    )
    parser.add_argument(
        "--soak",
        metavar="SOAK_REPORT_JSON",
        help="tools/soak.py report: traffic shape, per-kind outcome "
        "table, SLO verdicts, assertion list",
    )
    args = parser.parse_args()
    if not args.path and not (args.slo or args.fleet or args.soak):
        parser.error("need a span file, --slo, --fleet, or --soak")

    slo = slo_activity(load_metrics(args.slo)) if args.slo else None
    fleet = fleet_section(args.fleet) if args.fleet else None
    soak = soak_activity(load_soak(args.soak)) if args.soak else None

    spans = load_spans(args.path) if args.path else []
    if args.trace:
        print(render_trace(spans, args.trace))
        return
    rows = phase_breakdown(spans)
    activity = surrogate_activity(spans)
    speculative = speculative_activity(spans)
    programs = program_kind_activity(spans)
    devices = device_activity(spans)
    if args.json:
        print(
            json.dumps(
                {
                    "spans": len(spans),
                    "surrogate_activity": activity,
                    "speculative_activity": speculative,
                    "program_kind_activity": programs,
                    "device_activity": devices,
                    "slo": slo,
                    "fleet": fleet,
                    "soak": soak,
                    "phases": rows,
                },
                indent=2,
            )
        )
    elif not args.path:
        if slo is not None:
            print(render_slo(slo))
        if soak is not None:
            print(render_soak(soak))
        if fleet is not None:
            try:
                from vizier_tpu.observability import fleet as fleet_lib

                print(fleet_lib.render_fleet_report(fleet))
            except Exception:
                print(json.dumps(fleet, indent=2))
    else:
        print(f"{len(spans)} spans")
        print(
            f"surrogate mode: {activity['mode']} "
            f"(exact device phases: {activity['exact']}, "
            f"sparse: {activity['sparse']})"
        )
        if programs:
            summary = ", ".join(
                f"{kind}: {row['flushes']} flushes / {row['total_ms']:.0f} ms"
                for kind, row in sorted(programs.items())
            )
            print(f"program kinds: {summary}")
        print(
            f"speculative: hit {speculative['hit']} / miss "
            f"{speculative['miss']} / stale {speculative['stale']} "
            f"(hit rate {speculative['hit_rate']:.0%}, precomputes "
            f"{speculative['precomputes']}, stored {speculative['stored']})"
        )
        if slo is not None:
            print(render_slo(slo))
        if soak is not None:
            print(render_soak(soak))
        if fleet is not None:
            try:
                from vizier_tpu.observability import fleet as fleet_lib

                print(fleet_lib.render_fleet_report(fleet))
            except Exception:
                print(json.dumps(fleet, indent=2))
        print(render_table(rows))


if __name__ == "__main__":
    main()
