#!/bin/bash
# Reproduces every round-5 evidence artifact from a clean checkout.
# Everything runs on CPU (JAX_PLATFORMS=cpu is honored via the shared
# config-level pin); on a live TPU drop the env prefix. Approximate
# runtimes are from the quiet 8-core container this round ran in.
set -e
cd "$(dirname "$0")/.."

echo "== 0. static analysis: lock order / JAX discipline / env registry (~2 s) =="
#    zero unbaselined violations (docs/guides/static_analysis.md)
python tools/check_analysis.py

echo "== 1. full test suite (~16 min; sharded recipe for 1-core boxes) =="
#    On hardware where the single-process run no longer fits the tier-1
#    wall (see ROADMAP.md "Tier-1 timing"), use the sharded recipe:
#      bash tools/tier1_sharded.sh
python -m pytest tests/ -q

echo "== 2. full-scale CPU bench for the shipped default (~30 min) =="
#    -> compare BENCH_CPU_FULLSCALE.json
JAX_PLATFORMS=cpu VIZIER_BENCH_SCALE=1.0 VIZIER_BENCH_WATCHDOG_S=14400 \
  python bench.py

echo "== 3. service throughput head-to-head + sharded-tier A/B (~8 min) =="
#    -> SERVICE_THROUGHPUT.json (builds /tmp/refvizier on first run);
#    --replicas adds the "distributed" section: 4 routed replicas vs one
#    gRPC server on the same 8-study workload (target >= 5x)
JAX_PLATFORMS=cpu python tools/service_throughput.py --replicas 4 --out /tmp/st.json

echo "== 3b. failover chaos: kill one replica mid-study (~1 min) =="
#    -> CHAOS_AB.json gains the distributed_failover arm (50/50 trials
#    complete via router failover + WAL handoff), the replicated_failover
#    arm (--no-shared-fs: the dead replica's WAL directory is DELETED at
#    the kill; 50/50 still completes via the successors' replication
#    standby logs), the subprocess_partition arm (real replica_main
#    processes with lease-based failure detection: SIGKILL the owner AND
#    a netchaos partition-then-heal window; standby recovery over gRPC,
#    fenced stale-append rejection, replication-off bit-identity), the
#    mesh_executor arm (device-program failure isolated to ONE placement
#    of an 8-device mesh), and the runtime lock-order cross-check — now
#    including the per-placement mesh dispatch workers, the replication
#    streamer threads, AND the subprocess fleet's lease/netchaos locks —
#    vs the static graph
JAX_PLATFORMS=cpu python tools/chaos_ab.py --distributed 4 --mesh-devices 8 \
  --no-shared-fs --replica-mode subprocess --partition --instrument-locks

echo "== 3b3. SLO-armed observability soak (~2 min) =="
#    -> OBSERVABILITY_E2E.json (v2): 2-replica tier with SLOs armed +
#    flight recorder on; an induced p99 breach writes a black-box dump
#    whose exemplar trace_ids resolve to complete traces in the merged
#    per-replica span dumps; the fleet merge (obs_report --fleet) stitches
#    cross-replica traces and the failover timeline from recorder events
JAX_PLATFORMS=cpu python tools/chaos_ab.py --trials 50 --slo-soak \
  --out /tmp/chaos_slo.json

echo "== 3b5. hot-tenant overload A/B (~3 min) =="
#    -> OVERLOAD_AB.json: the loadgen hot_tenant scenario (one Zipf-head
#    tenant flooding GP compute at a saturating OPEN-LOOP rate,
#    time_scale=1 real arrival pacing) with the admission plane ON vs
#    OFF; asserts light-tenant suggest p99 within the SLO budget + zero
#    lost studies + sheds nonzero and confined to the hot tenant + sheds
#    never trip a breaker with the plane ON, the p99 collapse with it
#    OFF, and VIZIER_ADMISSION=0 bit-identity vs the sequential
#    reference (docs/guides/reliability.md "Overload protection")
JAX_PLATFORMS=cpu python tools/overload_ab.py

echo "== 3b4. full-stack loadgen soak (slow arm, ~20 min) =="
#    -> SOAK_REPORT.json: >=1000 Zipf-sized studies across every
#    registered program kind on a 2-replica WAL-backed tier, speculation
#    + batching + mesh + SLO armed, kill/revive + chaos mid-run; asserts
#    regret parity (rank-sum vs the sequential reference arm), zero lost
#    studies, failover completeness, bounded fallback rate, SLO p99
#    verdicts, and bit-identical gated-off trajectories in one verdict
#    (docs/guides/loadtest.md; render with tools/obs_report.py --soak)
JAX_PLATFORMS=cpu python tools/soak.py --mesh-devices 2

echo "== 3b6. disaggregated compute tier A/B (~1 min) =="
#    -> COMPUTE_TIER_AB.json: 8 frontends sharing ONE real
#    pythia_server_main subprocess vs 8 self-contained replicas on the
#    same-bucket GP workload (target: shared batch-flush occupancy >= 4x
#    the self-contained arm, p50/p99 both arms), a mid-run compute-server
#    SIGKILL completing 50/50 via each frontend's local fallback, and the
#    VIZIER_COMPUTE_TIER=0 bit-identity check (wrap identity + matching
#    trajectories); the fleet merge attributes all 8 frontends on the
#    remote-hop spans (docs/guides/running_the_service.md
#    "Disaggregated compute tier")
JAX_PLATFORMS=cpu python tools/compute_tier_ab.py

echo "== 3b2. mesh-sharded batch execution A/B (~4 min) =="
#    -> MESH_AB.json: 8 distinct concurrent shape buckets through the
#    single-device executor vs an 8-placement mesh executor on 8
#    simulated devices (target >= 2x aggregate flush throughput), plus
#    the VIZIER_MESH=0 bit-identity check against the seed executor
JAX_PLATFORMS=cpu python tools/batching_ab.py --devices 8

echo "== 3c. sparse-surrogate A/B at the north-star scale (~10 min) =="
#    -> SPARSE_AB.json: sparse SGPR vs exact O(n^3) device-side suggest
#    p50 at 1000x20-D (target >= 10x), rank-sum regret parity at 5
#    seeds, and the VIZIER_SPARSE=0 bit-identity check
JAX_PLATFORMS=cpu python tools/surrogate_ab.py

echo "== 3c2. sparse UCB-PE A/B — the service DEFAULT (~45 min) =="
#    -> SPARSE_UCB_PE_AB.json: sparse UCB-PE (pending-pick conditioning
#    through the Nystrom-augmented inducing posterior, compute-IR kind
#    gp_ucb_pe_sparse) vs exact UCB-PE full-designer suggest p50 at
#    1000x20-D (target >= 5x), rank-sum regret parity at 5 seeds, and
#    the VIZIER_SPARSE_UCB_PE=0 bit-identity check
JAX_PLATFORMS=cpu python tools/surrogate_ab.py --designer ucb_pe

echo "== 3d. speculative pre-compute A/B (~4 min) =="
#    -> SPECULATIVE_AB.json: sequential complete->suggest loop, 5 seeds;
#    speculative-hit suggest p50 < 10 ms vs the full-GP baseline,
#    hit rate >= 80%, and bit-identical trajectories (a hit is the live
#    compute run early; VIZIER_SPECULATIVE=0 stays the seed path)
JAX_PLATFORMS=cpu python tools/speculative_ab.py --trials 25 --seeds 5 --acquisition-evals 0

echo "== 4. budget-policy A/B, 5 seeds x 3 families (~45 min) =="
#    -> budget_ab_r5.json
JAX_PLATFORMS=cpu python tools/budget_policy_ab.py

echo "== 5. full designer-parity suite (~11 min) =="
#    -> regret_report_r5.json
JAX_PLATFORMS=cpu python parity_suite.py --out /tmp/regret.json

echo "== 6. multichip dryrun on an 8-device virtual mesh (~2 min) =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"

echo "all evidence reproduced"
