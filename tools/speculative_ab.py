#!/usr/bin/env python
"""Speculative pre-compute A/B: suggest latency with the background
pipeline on vs off, on the canonical sequential complete→suggest loop.

Both arms drive the SAME workload through the full in-process service
stack (VizierServicer → PythiaServicer → coalescer → cached-designer
policy → DEFAULT UCB-PE designer): one worker runs a study to ``--trials``
trials, completing each suggestion with a seeded sphere objective before
asking for the next. Per-study designers, budgets, and seeds are identical
across arms; only the speculative engine differs:

- **baseline** — every suggest pays the full GP train + acquisition on
  the request path (the current serving shape);
- **speculative** — each completion triggers a background pre-compute of
  the next batch; the worker's evaluation window is modeled by waiting
  for the engine to go idle before the next suggest (an evaluation that
  outlasts the pre-compute — the serving steady state this feature
  targets; ``--think-time`` switches to a fixed sleep instead).

Because a speculative hit is the live compute run early (same cached
designer, same RNG order), the two arms must produce **bit-identical
suggestion trajectories** — checked per seed, which simultaneously
verifies hit bit-equality and that `VIZIER_SPECULATIVE=0` is the seed
path. Regret parity across seeds is reported as a rank-sum p-value on the
final best objective values (trivially parity when every trajectory is
bit-equal, reported anyway as the headline evidence shape).

Evidence lands in ``SPECULATIVE_AB.json``: per-arm suggest p50/p95/p99,
hit-only latency percentiles, hit rate, per-seed bit-equality, regret
parity, and the speedup ratio. Acceptance: speculative-hit suggest
p50 < 10 ms, hit rate >= 80%, bit-equal trajectories at every seed.

Usage:  python tools/speculative_ab.py [--trials 25] [--seeds 5] [--out SPECULATIVE_AB.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu import pyvizier as vz  # noqa: E402
from vizier_tpu.serving import runtime as runtime_lib  # noqa: E402
from vizier_tpu.serving import speculative as spec_lib  # noqa: E402
from vizier_tpu.service import proto_converters as pc  # noqa: E402
from vizier_tpu.service import pythia_service, vizier_service  # noqa: E402
from vizier_tpu.service.protos import vizier_service_pb2  # noqa: E402


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    rank = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _pcts_ms(values):
    values = sorted(values)
    return {
        "p50_ms": round(_percentile(values, 50) * 1e3, 3),
        "p95_ms": round(_percentile(values, 95) * 1e3, 3),
        "p99_ms": round(_percentile(values, 99) * 1e3, 3),
        "max_ms": round((values[-1] if values else 0.0) * 1e3, 3),
        "samples": len(values),
    }


def _study_config(dim: int) -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="DEFAULT")
    for d in range(dim):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _sphere(trial_proto) -> float:
    return -sum(
        (p.value.double_value - 0.3) ** 2 for p in trial_proto.parameters
    )


def _build_stack(speculative: bool, acquisition_evals: int, seed: int):
    """The full in-process service stack with the REAL policy factory;
    the per-run designer rng seed (and an optional trimmed acquisition
    budget) is injected through the factory's kwargs hook so both arms of
    a seed share the exact same designer configuration."""
    from vizier_tpu.service import policy_factory as policy_factory_lib

    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer)
    runtime = runtime_lib.ServingRuntime(
        speculative=spec_lib.SpeculativeConfig(speculative=speculative)
    )
    pythia._serving = runtime

    base_factory = policy_factory_lib.DefaultPolicyFactory(
        serving_runtime=runtime
    )
    original_kwargs = base_factory._gp_designer_kwargs

    def seeded_kwargs():
        kwargs = original_kwargs()
        kwargs["rng_seed"] = seed
        if acquisition_evals:
            kwargs["max_acquisition_evaluations"] = acquisition_evals
        return kwargs

    base_factory._gp_designer_kwargs = seeded_kwargs
    pythia._policy_factory = base_factory
    pythia._bind_speculative()
    servicer.set_pythia(pythia)
    return servicer, pythia


def _run_arm(
    *,
    speculative: bool,
    seed: int,
    dim: int,
    trials: int,
    warmup: int,
    think_time: float,
    acquisition_evals: int,
) -> dict:
    servicer, pythia = _build_stack(speculative, acquisition_evals, seed)
    study_name = f"owners/ab/studies/{'spec' if speculative else 'base'}-{seed}"
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/ab",
            study=pc.study_to_proto(_study_config(dim), study_name),
        )
    )
    engine = pythia.serving_runtime.speculative_engine
    latencies, hits, trajectory, best = [], [], [], []
    best_so_far = float("-inf")
    try:
        for step in range(trials):
            t0 = time.perf_counter()
            op = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=study_name, suggestion_count=1, client_id="worker"
                )
            )
            elapsed = time.perf_counter() - t0
            if op.error:
                raise RuntimeError(f"suggest failed at step {step}: {op.error}")
            trial = op.response.trials[0]
            hit = any(
                kv.key == spec_lib.SPECULATIVE_KEY
                and kv.string_value == spec_lib.SPECULATIVE_HIT_VALUE
                for kv in trial.metadata
            )
            if step >= warmup:
                latencies.append(elapsed)
                hits.append(hit)
            trajectory.append(
                tuple(
                    sorted(
                        (p.name, round(p.value.double_value, 12))
                        for p in trial.parameters
                    )
                )
            )
            objective = _sphere(trial)
            best_so_far = max(best_so_far, objective)
            best.append(best_so_far)
            request = vizier_service_pb2.CompleteTrialRequest(name=trial.name)
            metric = request.final_measurement.metrics.add()
            metric.name, metric.value = "obj", objective
            servicer.CompleteTrial(request)
            # The evaluation window: long enough for the pre-compute to
            # land (wait_idle), or a fixed think time if requested.
            if engine is not None:
                if think_time > 0:
                    time.sleep(think_time)
                else:
                    engine.wait_idle(300.0)
        stats = {
            k: v
            for k, v in pythia.serving_stats().items()
            if k.startswith("speculative_")
        }
    finally:
        pythia.shutdown()
    hit_lat = [l for l, h in zip(latencies, hits) if h]
    miss_lat = [l for l, h in zip(latencies, hits) if not h]
    return {
        "seed": seed,
        "suggest": _pcts_ms(latencies),
        "hit_suggest": _pcts_ms(hit_lat),
        "miss_suggest": _pcts_ms(miss_lat),
        "hits": sum(hits),
        "measured": len(hits),
        "stats": stats,
        "trajectory": trajectory,
        "best_curve": [round(b, 9) for b in best],
    }


def _ranksum_p(a, b) -> float:
    """Two-sided rank-sum p-value (scipy when present, else normal approx)."""
    try:
        from scipy import stats as sps

        return float(sps.ranksums(a, b).pvalue)
    except Exception:
        import math

        n, m = len(a), len(b)
        ranked = sorted((v, 0) for v in a) + sorted((v, 1) for v in b)
        ranked.sort()
        ra = sum(i + 1 for i, (v, g) in enumerate(ranked) if g == 0)
        mu = n * (n + m + 1) / 2.0
        sigma = math.sqrt(n * m * (n + m + 1) / 12.0) or 1.0
        z = (ra - mu) / sigma
        return 2.0 * (1.0 - 0.5 * (1.0 + math.erf(abs(z) / math.sqrt(2)))) or 1.0


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--trials", type=int, default=25)
    parser.add_argument("--seeds", type=int, default=5)
    parser.add_argument("--dim", type=int, default=3)
    parser.add_argument("--warmup", type=int, default=3,
                        help="Suggests excluded from latency stats (compile).")
    parser.add_argument("--think-time", type=float, default=0.0,
                        help="Fixed evaluation sleep instead of wait_idle.")
    parser.add_argument("--acquisition-evals", type=int, default=1000,
                        help="Acquisition sweep budget (0 = designer default).")
    parser.add_argument("--out", default="SPECULATIVE_AB.json")
    args = parser.parse_args()

    arms = {"baseline": [], "speculative": []}
    bit_equal, t_start = [], time.time()
    for seed in range(1, args.seeds + 1):
        base = _run_arm(
            speculative=False, seed=seed, dim=args.dim, trials=args.trials,
            warmup=args.warmup, think_time=args.think_time,
            acquisition_evals=args.acquisition_evals,
        )
        spec = _run_arm(
            speculative=True, seed=seed, dim=args.dim, trials=args.trials,
            warmup=args.warmup, think_time=args.think_time,
            acquisition_evals=args.acquisition_evals,
        )
        equal = base["trajectory"] == spec["trajectory"]
        bit_equal.append(equal)
        arms["baseline"].append(base)
        arms["speculative"].append(spec)
        print(
            f"[seed {seed}] baseline p50 "
            f"{base['suggest']['p50_ms']:.0f} ms | speculative hit p50 "
            f"{spec['hit_suggest']['p50_ms']:.2f} ms | hits "
            f"{spec['hits']}/{spec['measured']} | bit-equal {equal}",
            flush=True,
        )

    hits_total = sum(r["hits"] for r in arms["speculative"])
    measured_total = sum(r["measured"] for r in arms["speculative"])
    base_final = [r["best_curve"][-1] for r in arms["baseline"]]
    spec_final = [r["best_curve"][-1] for r in arms["speculative"]]
    hit_p50s = [r["hit_suggest"]["p50_ms"] for r in arms["speculative"]]
    hit_p99s = [r["hit_suggest"]["p99_ms"] for r in arms["speculative"]]
    base_p50s = [r["suggest"]["p50_ms"] for r in arms["baseline"]]
    base_p99s = [r["suggest"]["p99_ms"] for r in arms["baseline"]]

    summary = {
        "workload": {
            "trials": args.trials,
            "seeds": args.seeds,
            "dim": args.dim,
            "warmup_excluded": args.warmup,
            "algorithm": "DEFAULT (GP-UCB-PE)",
            "acquisition_evals": args.acquisition_evals,
            "evaluation_model": (
                f"sleep {args.think_time}s" if args.think_time > 0
                else "wait_idle (evaluation outlasts pre-compute)"
            ),
            "backend": "cpu",
        },
        "speculative_config": spec_lib.SpeculativeConfig(
            speculative=True
        ).as_dict(),
        "baseline_suggest_p50_ms": round(
            sum(base_p50s) / len(base_p50s), 3
        ),
        "baseline_suggest_p99_ms": round(max(base_p99s), 3),
        "speculative_hit_p50_ms": round(sum(hit_p50s) / len(hit_p50s), 4),
        "speculative_hit_p99_ms": round(max(hit_p99s), 4),
        "speedup_p50": round(
            (sum(base_p50s) / len(base_p50s))
            / max(sum(hit_p50s) / len(hit_p50s), 1e-9),
            1,
        ),
        "hit_rate": round(hits_total / max(measured_total, 1), 4),
        "bit_identical_trajectories": f"{sum(bit_equal)}/{len(bit_equal)}",
        "regret_parity": {
            "baseline_final_best": base_final,
            "speculative_final_best": spec_final,
            "ranksum_p": round(_ranksum_p(base_final, spec_final), 4),
        },
        "acceptance": {
            "hit_p50_under_10ms": all(p < 10.0 for p in hit_p50s),
            "hit_rate_ge_80pct": hits_total / max(measured_total, 1) >= 0.80,
            "bit_equal_all_seeds": all(bit_equal),
        },
        "per_seed": {
            arm: [
                {k: v for k, v in row.items() if k not in ("trajectory",)}
                for row in rows
            ]
            for arm, rows in arms.items()
        },
        "wall_seconds": round(time.time() - t_start, 1),
    }
    out_path = pathlib.Path(args.out)
    out_path.write_text(json.dumps(summary, indent=2) + "\n")
    print(json.dumps({k: summary[k] for k in (
        "baseline_suggest_p50_ms", "speculative_hit_p50_ms", "speedup_p50",
        "hit_rate", "bit_identical_trajectories", "acceptance",
    )}, indent=2))
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
