"""A/B: sparse inducing-point surrogate vs the exact O(n³) GP.

Usage: python tools/surrogate_ab.py [--out SPARSE_AB.json]
       [--designer gp_bandit|ucb_pe]
       [--trials 1000] [--dim 20] [--evals 75000] [--inducing 128]
       [--exact-repeats 2] [--sparse-repeats 5]
       [--parity-trials 45] [--parity-seeds 1 2 3 4 5]

``--designer ucb_pe`` runs the same three measurements for the service
DEFAULT (GP-UCB-PE): the sparse arm conditions the greedy batch on
pending picks through the inducing-point posterior (Nyström-augmented;
``gp_ucb_pe_sparse`` compute-IR program) instead of the exact per-pick
O(n³) re-factorization; the latency arms drive the full designer suggest
(train + greedy batch) at the north-star scale, and the output defaults
to ``SPARSE_UCB_PE_AB.json``.

Three measurements, one JSON report:

1. **Device-side suggest latency** at the north-star scale (1000 trials x
   20-D, 75k acquisition evals, batch 25): per repeat, ARD train + one
   full acquisition sweep, device-synchronized.
   - exact arm: the seed path — multi-restart L-BFGS over the exact GP's
     O(n³) marginal likelihood (BENCH_CPU_FULLSCALE.json's 72 s p50);
   - sparse arm: the SAME restart budget over the SGPR collapsed bound
     with m inducing points (k-center-selected inside the program) —
     O(n·m²) train, O(m²) posterior queries in the sweep.
   Compile (step 0) is excluded from both arms.

2. **Regret parity**: full BO loops on shifted Sphere instances, the
   sparse auto-switch from the first post-seed suggest vs the exact path,
   >= 5 seeds, two-sided rank-sum on final regrets. Green when p > 0.05.

3. **Off-switch bit-identity**: with ``VIZIER_SPARSE=0`` the config built
   from the environment must reproduce the no-config exact path's
   suggestions exactly (float-equal), proving the switch is a pure
   bypass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import numpy as np


def _progress(msg: str) -> None:
    print(f"[surrogate_ab] {msg}", file=sys.stderr, flush=True)


def measure_latency(args) -> dict:
    import jax

    from vizier_tpu import types
    from vizier_tpu.converters import padding
    from vizier_tpu.designers.gp import acquisitions
    from vizier_tpu.designers.gp_bandit import _maximize_acquisition, _train_gp
    from vizier_tpu.models import gp as gp_lib
    from vizier_tpu.models import kernels
    from vizier_tpu.models import output_warpers
    from vizier_tpu.optimizers import eagle as eagle_lib
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib
    from vizier_tpu.optimizers import vectorized as vectorized_lib
    from vizier_tpu.surrogates import sparse_bandit
    from vizier_tpu.surrogates import sparse_gp

    num_trials, dim = args.trials, args.dim
    n_pad = 1 << (num_trials - 1).bit_length()
    m_pad = padding.PaddingSchedule().pad_trials(args.inducing)
    rng = np.random.default_rng(0)
    x = rng.uniform(size=(num_trials, dim)).astype(np.float32)
    y = -np.sum((x - 0.5) ** 2, axis=1) + 0.1 * rng.normal(size=num_trials)

    def make_data(step: int) -> gp_lib.GPData:
        """One fresh observation per steady-state step (row swap keeps the
        padded shapes — and therefore the jit cache — identical)."""
        xs, ys = x.copy(), y.copy()
        if step > 0:
            row = (step * 37) % num_trials
            r = np.random.default_rng(1000 + step)
            xs[row] = r.uniform(size=dim).astype(np.float32)
            ys[row] = -np.sum((xs[row] - 0.5) ** 2) + 0.1 * r.normal()
        warped = output_warpers.create_default_warper()(ys)
        features = types.ContinuousAndCategorical(
            continuous=types.PaddedArray.from_array(xs, (n_pad, dim)),
            categorical=types.PaddedArray.from_array(
                np.zeros((num_trials, 0), np.int32), (n_pad, 0), fill_value=0
            ),
        )
        labels = types.PaddedArray.from_array(
            warped[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
        )
        return gp_lib.GPData.from_model_data(types.ModelData(features, labels))

    base = gp_lib.VizierGaussianProcess(num_continuous=dim, num_categorical=0)
    sparse_model = sparse_gp.SparseGaussianProcess(base=base, num_inducing=m_pad)
    ard = lbfgs_lib.LbfgsOptimizer(maxiter=50)
    strategy = eagle_lib.VectorizedEagleStrategy(
        num_continuous=dim, category_sizes=()
    )
    vec_opt = vectorized_lib.VectorizedOptimizer(
        strategy, max_evaluations=args.evals
    )
    restarts = lbfgs_lib.DEFAULT_RANDOM_RESTARTS

    def scoring_for(predictive, data):
        best_label = jax.numpy.max(
            jax.numpy.where(data.row_mask, data.labels, -jax.numpy.inf)
        )
        return acquisitions.ScoringFunction(
            predictive=predictive,
            acquisition=acquisitions.UCB(1.8),
            best_label=best_label,
            trust_region=acquisitions.TrustRegion.from_data(data),
        )

    def prior(data):
        return kernels.MixedFeatures(data.continuous[:10], data.categorical[:10])

    def run_arm(sparse: bool, repeats: int):
        times = []
        for step in range(repeats + 1):
            data = make_data(step)
            key = jax.random.PRNGKey(step)
            k_train, k_acq = jax.random.split(key)
            t0 = time.perf_counter()
            if sparse:
                states = sparse_bandit._train_sparse_gp(
                    sparse_model, ard, data, k_train, restarts, 1, None
                )
                scoring = scoring_for(
                    sparse_gp.SparseEnsemblePredictive(states), data
                )
                result = sparse_bandit._maximize_sparse_acquisition(
                    vec_opt, scoring, k_acq, args.batch, prior(data)
                )
            else:
                states = _train_gp(model=base, optimizer=ard, data=data,
                                   rng=k_train, num_restarts=restarts,
                                   ensemble_size=1)
                scoring = scoring_for(gp_lib.EnsemblePredictive(states), data)
                result = _maximize_acquisition(
                    vec_opt, scoring, k_acq, args.batch, prior(data)
                )
            jax.block_until_ready(result)
            elapsed = (time.perf_counter() - t0) * 1000.0
            # step 0 is the compile run for both arms: excluded.
            if step > 0:
                times.append(elapsed)
            _progress(
                f"{'sparse' if sparse else 'exact'} step {step}: "
                f"{elapsed:.0f} ms{' (compile, excluded)' if step == 0 else ''}"
            )
        return times

    _progress(
        f"latency: sparse arm at {num_trials}x{dim}d, m={args.inducing} "
        f"(padded {m_pad}), {args.evals} evals"
    )
    sparse_times = run_arm(sparse=True, repeats=args.sparse_repeats)
    _progress(f"latency: exact arm ({args.exact_repeats} repeats of ~72 s)")
    exact_times = run_arm(sparse=False, repeats=args.exact_repeats)
    sparse_p50 = float(np.percentile(sparse_times, 50))
    exact_p50 = float(np.percentile(exact_times, 50))
    return {
        "config": {
            "num_trials": num_trials,
            "dim": dim,
            "max_evaluations": args.evals,
            "batch": args.batch,
            "restarts": restarts,
            "num_inducing": args.inducing,
            "num_inducing_padded": m_pad,
            "exact_repeats": args.exact_repeats,
            "sparse_repeats": args.sparse_repeats,
        },
        "exact_suggest_p50_ms": round(exact_p50, 1),
        "sparse_suggest_p50_ms": round(sparse_p50, 1),
        "exact_suggest_ms": [round(t, 1) for t in exact_times],
        "sparse_suggest_ms": [round(t, 1) for t in sparse_times],
        "speedup": round(exact_p50 / sparse_p50, 2),
    }


def _ucb_pe_designer(problem, seed, args, sparse: bool):
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
    from vizier_tpu.surrogates import SurrogateConfig

    surrogate = None
    if sparse:
        surrogate = SurrogateConfig(
            sparse_threshold_trials=1,
            hysteresis_trials=0,
            num_inducing=args.inducing,
        )
    return VizierGPUCBPEBandit(
        problem,
        rng_seed=seed,
        max_acquisition_evaluations=args.evals,
        surrogate=surrogate,
    )


def measure_latency_ucb_pe(args) -> dict:
    """End-to-end UCB-PE suggest latency (train + greedy batch) at the
    north-star scale: the full designer path, so the exact arm pays the
    O(n³) ARD *and* the per-pick O(n³) pending re-conditioning, the
    sparse arm their O(n·m²) inducing-point twins — same study data, same
    backend, same process."""
    import jax

    from vizier_tpu import pyvizier as vz
    from vizier_tpu.algorithms import core as core_lib

    num_trials, dim = args.trials, args.dim
    problem = vz.ProblemStatement()
    for d in range(dim):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )

    def make_trials(start_id, n, seed):
        rng = np.random.default_rng(seed)
        out = []
        for i in range(n):
            params = {
                f"x{d}": float(rng.uniform()) for d in range(dim)
            }
            t = vz.Trial(parameters=params, id=start_id + i)
            t.complete(
                vz.Measurement(
                    metrics={
                        "obj": float(
                            -sum((v - 0.5) ** 2 for v in params.values())
                            + 0.1 * rng.normal()
                        )
                    }
                )
            )
            out.append(t)
        return out

    base_trials = make_trials(1, num_trials, seed=0)

    def run_arm(sparse: bool, repeats: int):
        designer = _ucb_pe_designer(problem, 0, args, sparse)
        designer.update(core_lib.CompletedTrials(base_trials))
        times = []
        for step in range(repeats + 1):
            if step > 0:
                # One fresh completion per steady-state step forces a
                # retrain without leaving the 1024-row padding bucket.
                designer.update(
                    core_lib.CompletedTrials(
                        make_trials(num_trials + step, 1, seed=1000 + step)
                    )
                )
            t0 = time.perf_counter()
            out = designer.suggest(args.ucb_batch)
            assert len(out) == args.ucb_batch
            elapsed = (time.perf_counter() - t0) * 1000.0
            if step > 0:
                times.append(elapsed)
            _progress(
                f"ucb_pe {'sparse' if sparse else 'exact'} step {step}: "
                f"{elapsed:.0f} ms"
                f"{' (compile, excluded)' if step == 0 else ''}"
            )
        if sparse:
            assert designer.surrogate_counts["sparse_suggests"] > 0
            assert designer.surrogate_mode == "sparse"
        return times

    _progress(
        f"ucb_pe latency: sparse arm at {num_trials}x{dim}d, "
        f"m={args.inducing}, batch {args.ucb_batch}, {args.evals} evals"
    )
    sparse_times = run_arm(sparse=True, repeats=args.sparse_repeats)
    _progress(f"ucb_pe latency: exact arm ({args.exact_repeats} repeats)")
    exact_times = run_arm(sparse=False, repeats=args.exact_repeats)
    sparse_p50 = float(np.percentile(sparse_times, 50))
    exact_p50 = float(np.percentile(exact_times, 50))
    return {
        "config": {
            "designer": "gp_ucb_pe",
            "num_trials": num_trials,
            "dim": dim,
            "max_evaluations": args.evals,
            "batch": args.ucb_batch,
            "num_inducing": args.inducing,
            "exact_repeats": args.exact_repeats,
            "sparse_repeats": args.sparse_repeats,
        },
        "exact_suggest_p50_ms": round(exact_p50, 1),
        "sparse_suggest_p50_ms": round(sparse_p50, 1),
        "exact_suggest_ms": [round(t, 1) for t in exact_times],
        "sparse_suggest_ms": [round(t, 1) for t in sparse_times],
        "speedup": round(exact_p50 / sparse_p50, 2),
    }


def measure_parity_ucb_pe(args) -> dict:
    """Sparse-vs-exact UCB-PE regret parity: full BO loops on shifted
    Sphere instances, rank-sum on final regrets at >= 5 seeds."""
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import experimenter_factory
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
    from vizier_tpu.surrogates import SurrogateConfig

    def run_arm(seed: int, sparse: bool) -> float:
        exp = experimenter_factory.shifted_bbob_instance(
            "Sphere", seed, dim=args.parity_dim
        )
        surrogate = (
            SurrogateConfig(
                sparse_threshold_trials=1,
                hysteresis_trials=0,
                num_inducing=args.parity_inducing,
            )
            if sparse
            else None
        )
        designer = VizierGPUCBPEBandit(
            exp.problem_statement(),
            rng_seed=seed,
            max_acquisition_evaluations=args.parity_evals,
            surrogate=surrogate,
        )
        best, tid = np.inf, 0
        while tid < args.parity_trials:
            batch = [
                s.to_trial(tid + i + 1)
                for i, s in enumerate(designer.suggest(args.parity_batch))
            ]
            tid += len(batch)
            exp.evaluate(batch)
            designer.update(core_lib.CompletedTrials(batch))
            for t in batch:
                best = min(best, t.final_measurement.metrics["bbob_eval"].value)
        if sparse:
            assert designer.surrogate_counts["sparse_suggests"] > 0
        return best

    sparse_finals, exact_finals = [], []
    for seed in args.parity_seeds:
        t0 = time.perf_counter()
        sparse_finals.append(run_arm(seed, sparse=True))
        exact_finals.append(run_arm(seed, sparse=False))
        _progress(
            f"ucb_pe parity seed {seed}: sparse={sparse_finals[-1]:.4f} "
            f"exact={exact_finals[-1]:.4f} ({time.perf_counter() - t0:.0f}s)"
        )
    p = rank_sum_p(sparse_finals, exact_finals)
    return {
        "config": {
            "designer": "gp_ucb_pe",
            "fn": "Sphere(shifted)",
            "dim": args.parity_dim,
            "trials": args.parity_trials,
            "batch": args.parity_batch,
            "max_evaluations": args.parity_evals,
            "num_inducing": args.parity_inducing,
            "sparse_threshold_trials": 1,
            "seeds": list(args.parity_seeds),
        },
        "sparse_final_regrets": [round(v, 4) for v in sparse_finals],
        "exact_final_regrets": [round(v, 4) for v in exact_finals],
        "rank_sum_p": round(p, 4),
        "parity_green": p > 0.05,
    }


def check_off_bit_identity_ucb_pe() -> dict:
    """VIZIER_SPARSE_UCB_PE=0 must reproduce the no-config UCB-PE path
    bit-for-bit (even with the study above the sparse threshold)."""
    from vizier_tpu import pyvizier as vz
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
    from vizier_tpu.surrogates import SurrogateConfig

    problem = vz.ProblemStatement()
    for d in range(4):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    rng = np.random.default_rng(7)
    trials = []
    for i in range(16):
        params = {f"x{d}": float(rng.uniform()) for d in range(4)}
        t = vz.Trial(parameters=params, id=i + 1)
        t.complete(
            vz.Measurement(metrics={"obj": float(sum(params.values()))})
        )
        trials.append(t)

    prev = os.environ.get("VIZIER_SPARSE_UCB_PE")
    os.environ["VIZIER_SPARSE_UCB_PE"] = "0"
    try:
        off_cfg = SurrogateConfig.from_env()
    finally:
        if prev is None:
            os.environ.pop("VIZIER_SPARSE_UCB_PE", None)
        else:
            os.environ["VIZIER_SPARSE_UCB_PE"] = prev
    assert not off_cfg.sparse_ucb_pe
    # Force the threshold below the study so only the ucb_pe gate stands
    # between this designer and the sparse path.
    off_cfg = SurrogateConfig(
        sparse=off_cfg.sparse,
        sparse_threshold_trials=1,
        hysteresis_trials=0,
        num_inducing=8,
        sparse_ucb_pe=off_cfg.sparse_ucb_pe,
    )

    def run(surrogate):
        d = VizierGPUCBPEBandit(
            problem, rng_seed=11,
            max_acquisition_evaluations=500, surrogate=surrogate,
        )
        d.update(core_lib.CompletedTrials(trials))
        out = []
        for _ in range(2):
            out.append([s.parameters.as_dict() for s in d.suggest(2)])
        return out

    identical = run(None) == run(off_cfg)
    _progress(f"ucb_pe off-switch bit-identity: {identical}")
    return {"off_bit_identical": identical}


def rank_sum_p(a, b) -> float:
    """Two-sided Mann-Whitney p (normal approximation), H0: same dist."""
    from scipy import stats

    a, b = np.asarray(a, float), np.asarray(b, float)
    ranks = stats.rankdata(np.concatenate([a, b]))
    n, m = len(a), len(b)
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    mu, sigma = n * m / 2.0, np.sqrt(n * m * (n + m + 1) / 12.0)
    return float(2.0 * (1.0 - stats.norm.cdf(abs(u - mu) / max(sigma, 1e-9))))


def measure_parity(args) -> dict:
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import experimenter_factory
    from vizier_tpu.designers.gp_bandit import VizierGPBandit
    from vizier_tpu.surrogates import SurrogateConfig

    def run_arm(seed: int, sparse: bool) -> float:
        exp = experimenter_factory.shifted_bbob_instance(
            "Sphere", seed, dim=args.parity_dim
        )
        surrogate = (
            SurrogateConfig(
                sparse_threshold_trials=1,
                hysteresis_trials=0,
                num_inducing=args.parity_inducing,
            )
            if sparse
            else None
        )
        designer = VizierGPBandit(
            exp.problem_statement(),
            rng_seed=seed,
            num_seed_trials=5,
            max_acquisition_evaluations=args.parity_evals,
            surrogate=surrogate,
        )
        best, tid = np.inf, 0
        while tid < args.parity_trials:
            batch = [
                s.to_trial(tid + i + 1)
                for i, s in enumerate(designer.suggest(args.parity_batch))
            ]
            tid += len(batch)
            exp.evaluate(batch)
            designer.update(core_lib.CompletedTrials(batch))
            for t in batch:
                best = min(best, t.final_measurement.metrics["bbob_eval"].value)
        if sparse:
            assert designer.surrogate_counts["sparse_suggests"] > 0
        return best

    sparse_finals, exact_finals = [], []
    for seed in args.parity_seeds:
        t0 = time.perf_counter()
        sparse_finals.append(run_arm(seed, sparse=True))
        exact_finals.append(run_arm(seed, sparse=False))
        _progress(
            f"parity seed {seed}: sparse={sparse_finals[-1]:.4f} "
            f"exact={exact_finals[-1]:.4f} ({time.perf_counter() - t0:.0f}s)"
        )
    p = rank_sum_p(sparse_finals, exact_finals)
    return {
        "config": {
            "fn": "Sphere(shifted)",
            "dim": args.parity_dim,
            "trials": args.parity_trials,
            "batch": args.parity_batch,
            "max_evaluations": args.parity_evals,
            "num_inducing": args.parity_inducing,
            "sparse_threshold_trials": 1,
            "seeds": list(args.parity_seeds),
        },
        "sparse_final_regrets": [round(v, 4) for v in sparse_finals],
        "exact_final_regrets": [round(v, 4) for v in exact_finals],
        "rank_sum_p": round(p, 4),
        "parity_green": p > 0.05,
    }


def check_off_bit_identity() -> dict:
    """VIZIER_SPARSE=0 must reproduce the no-config path bit-for-bit."""
    from vizier_tpu import pyvizier as vz
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.designers.gp_bandit import VizierGPBandit
    from vizier_tpu.surrogates import SurrogateConfig

    problem = vz.ProblemStatement()
    for d in range(4):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    rng = np.random.default_rng(7)
    trials = []
    for i in range(16):
        params = {f"x{d}": float(rng.uniform()) for d in range(4)}
        t = vz.Trial(parameters=params, id=i + 1)
        t.complete(
            vz.Measurement(metrics={"obj": float(sum(params.values()))})
        )
        trials.append(t)

    prev = os.environ.get("VIZIER_SPARSE")
    os.environ["VIZIER_SPARSE"] = "0"
    try:
        off_cfg = SurrogateConfig.from_env()
    finally:
        if prev is None:
            os.environ.pop("VIZIER_SPARSE", None)
        else:
            os.environ["VIZIER_SPARSE"] = prev
    assert not off_cfg.sparse

    def run(surrogate):
        d = VizierGPBandit(
            problem, rng_seed=11, num_seed_trials=1,
            max_acquisition_evaluations=500, surrogate=surrogate,
        )
        d.update(core_lib.CompletedTrials(trials))
        out = []
        for _ in range(2):
            out.append([s.parameters.as_dict() for s in d.suggest(2)])
        return out

    identical = run(None) == run(off_cfg)
    _progress(f"off-switch bit-identity: {identical}")
    return {"off_bit_identical": identical}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--designer", choices=("gp_bandit", "ucb_pe"), default="gp_bandit"
    )
    ap.add_argument("--ucb-batch", type=int, default=5)
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--dim", type=int, default=20)
    ap.add_argument("--evals", type=int, default=75_000)
    ap.add_argument("--batch", type=int, default=25)
    ap.add_argument("--inducing", type=int, default=128)
    ap.add_argument("--exact-repeats", type=int, default=2)
    ap.add_argument("--sparse-repeats", type=int, default=5)
    ap.add_argument("--parity-trials", type=int, default=45)
    ap.add_argument("--parity-batch", type=int, default=5)
    ap.add_argument("--parity-dim", type=int, default=20)
    ap.add_argument("--parity-evals", type=int, default=2_000)
    ap.add_argument("--parity-inducing", type=int, default=16)
    ap.add_argument("--parity-seeds", type=int, nargs="+", default=[1, 2, 3, 4, 5])
    ap.add_argument("--skip-latency", action="store_true")
    ap.add_argument("--skip-parity", action="store_true")
    args = ap.parse_args()
    if args.out is None:
        args.out = (
            "SPARSE_UCB_PE_AB.json"
            if args.designer == "ucb_pe"
            else "SPARSE_AB.json"
        )

    import jax

    from vizier_tpu.surrogates import SurrogateConfig

    ucb_pe = args.designer == "ucb_pe"
    report = {
        "backend": jax.default_backend(),
        "designer": args.designer,
        # Which path produced what: both arms are stamped explicitly, and
        # the process-wide env default rides along for provenance.
        "surrogates_env_config": SurrogateConfig.from_env().as_dict(),
        "note": (
            (
                "Sparse UCB-PE (SGPR collapsed-bound train + pending-pick "
                "conditioning through the Nyström-augmented inducing "
                "posterior; compute-IR kind gp_ucb_pe_sparse) vs the exact "
                "UCB-PE path (O(n³) ARD + O(n³) per-pick re-conditioning). "
                "Latency is the full designer suggest (train + greedy "
                "batch) at the north-star scale, same run/backend; parity "
                "is two-sided rank-sum on final regrets over full BO "
                "loops; VIZIER_SPARSE_UCB_PE=0 is checked bit-identical "
                "to the exact path."
            )
            if ucb_pe
            else (
                "Sparse SGPR collapsed-bound surrogate (k-center inducing "
                "selection, same multi-restart L-BFGS ARD program) vs the "
                "exact O(n³) GP. Latency is the device-side suggest step "
                "(train + acquisition sweep) at the north-star scale; "
                "parity is two-sided rank-sum on final regrets over full "
                "BO loops; VIZIER_SPARSE=0 is checked bit-identical to "
                "the seed path."
            )
        ),
    }
    if not args.skip_latency:
        report["latency"] = (
            measure_latency_ucb_pe(args) if ucb_pe else measure_latency(args)
        )
    if not args.skip_parity:
        report["parity"] = (
            measure_parity_ucb_pe(args) if ucb_pe else measure_parity(args)
        )
    report["off_switch"] = (
        check_off_bit_identity_ucb_pe() if ucb_pe else check_off_bit_identity()
    )
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
