#!/usr/bin/env python
"""Compute-tier A/B: N frontends sharing ONE Pythia compute server vs N
self-contained replicas, same-run, same workload.

The disaggregated tier exists to raise ONE number: batch-flush occupancy.
A self-contained replica's batch executor only ever sees its own studies
— at one study per replica every flush is a singleton (occupancy ≈ 1).
Routing the whole fleet's suggests to one shared
:class:`~vizier_tpu.service.pythia_service.PythiaServicer` lets
same-bucket computations from DIFFERENT frontends fuse into one vmapped
flush (occupancy ≈ N; the reference's ``DistributedPythiaVizierServer``
topology, arXiv:2408.11527 §4).

Both arms run the SAME workload: N GP studies with identical search-space
shapes (one padding bucket; identical per-study acquisition budgets via
the ``gp_ucb_pe.max_acquisition_evaluations`` study-metadata key, which
rides the StudySpec across the gRPC hop), each study owned by its own
frontend and driven by its own client thread through the full service
surface (``VizierClient`` → ``SuggestTrials`` → Pythia). Only the Pythia
topology differs:

- **shared_tier** — 8 in-process ``DefaultVizierServer`` frontends, each
  wrapped with :class:`~vizier_tpu.distributed.compute_tier.
  RemotePythiaStub`, dispatching to one REAL
  ``distributed.pythia_server_main`` subprocess (its ``--frontends``
  routed read-back resolving each study to the frontend that owns it);
- **self_contained** — 8 in-process stacks, each with its own local
  Pythia and its own batch executor (the subprocess-fleet shape).

Three more gates ride the same run:

- **kill** — a fresh compute server is SIGKILLed mid-run; every
  in-flight-and-after suggest must complete via the frontends' local
  fallback (50/50, zero client-visible errors);
- **bit-identity** — ``VIZIER_COMPUTE_TIER`` unset vs ``=0``:
  ``maybe_wrap_pythia`` must return the local Pythia UNCHANGED (identity)
  and the full suggest trajectories must be bit-identical;
- **fan-in** — the compute server's observability dump is merged with the
  frontends' span dumps (``observability.fleet``): the remote-hop spans
  must carry all N ``frontend=`` attributions.

Evidence lands in ``COMPUTE_TIER_AB.json``. Acceptance: shared-tier mean
batch-flush occupancy >= 4x the self-contained arm at 8 frontends,
suggest p50/p99 reported for both arms, kill completes 50/50, off-switch
bit-identical.

Usage:  python tools/compute_tier_ab.py [--frontends 8] [--rounds 2]
            [--out COMPUTE_TIER_AB.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu import pyvizier as vz  # noqa: E402
from vizier_tpu.distributed import compute_tier, routing  # noqa: E402
from vizier_tpu.reliability import ReliabilityConfig  # noqa: E402
from vizier_tpu.service import proto_converters as pc  # noqa: E402
from vizier_tpu.service import vizier_client  # noqa: E402
from vizier_tpu.service.protos import vizier_service_pb2  # noqa: E402
from vizier_tpu.service.vizier_server import DefaultVizierServer  # noqa: E402
from vizier_tpu.serving.config import ServingConfig  # noqa: E402

_REPO = pathlib.Path(__file__).resolve().parent.parent


def _sphere(parameters: dict) -> float:
    return -sum((float(v) - 0.3) ** 2 for v in parameters.values())


def _study_config(dim: int, acq_evals: int, algorithm: str = "") -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm=algorithm) if algorithm else vz.StudyConfig()
    for d in range(dim):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    if acq_evals:
        # The remote-client path to the designer's sweep budget: the key
        # rides the StudySpec through the Pythia surface, so the shared
        # compute server applies the SAME budget as the in-process arm
        # (service.policy_factory validates it at policy construction).
        config.metadata.ns("gp_ucb_pe")["max_acquisition_evaluations"] = str(
            acq_evals
        )
    return config


def _reliability() -> ReliabilityConfig:
    return ReliabilityConfig(
        retry_max_attempts=8,
        retry_base_delay_secs=0.1,
        retry_max_delay_secs=0.5,
    )


def _owned_study_names(frontend_ids) -> dict:
    """rid -> a study name the fleet's rendezvous router assigns to rid.

    The compute server reads trials back through a ``RoutedVizierStub``
    over the SAME router, so each study's read-back must land on the
    frontend that actually holds it."""
    router = routing.StudyRouter(list(frontend_ids))
    names = {}
    for rid in frontend_ids:
        for salt in range(10_000):
            name = f"owners/ab/studies/{rid}-s{salt}"
            if router.replica_for(name) == rid:
                names[rid] = name
                break
        else:  # pragma: no cover - rendezvous covers 8 ids long before 10k
            raise SystemExit(f"No study name routed to {rid} in 10k salts")
    return names


def _create_and_seed(servicer, study_name: str, config, start_trials: int, seed: int):
    """Creates the study and seeds ``start_trials`` completed trials."""
    import numpy as np

    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/ab", study=pc.study_to_proto(config, study_name)
        )
    )
    rng = np.random.default_rng(seed)
    dim = len(config.search_space.parameters)
    for _ in range(start_trials):
        params = {f"x{d}": float(rng.uniform()) for d in range(dim)}
        t = vz.Trial(parameters=params)
        t.complete(vz.Measurement(metrics={"obj": _sphere(params)}))
        servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(
                parent=study_name, trial=pc.trial_to_proto(t)
            )
        )


def _spawn_compute_server(
    *, frontends: str, obs_dump_dir: str, max_wait_ms: float, batch_size: int
):
    """One REAL pythia_server_main subprocess; returns (proc, endpoint)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "VIZIER_BATCH_MAX_WAIT_MS": str(max_wait_ms),
        "VIZIER_BATCH_MAX_SIZE": str(batch_size),
    }
    cmd = [
        sys.executable,
        "-m",
        "vizier_tpu.distributed.pythia_server_main",
        "--server-id",
        "compute-ab",
        "--port",
        "0",
    ]
    if frontends:
        cmd += ["--frontends", frontends]
    if obs_dump_dir:
        cmd += ["--obs-dump-dir", obs_dump_dir]
    proc = subprocess.Popen(
        cmd, cwd=str(_REPO), env=env, stdout=subprocess.PIPE, text=True
    )
    line = proc.stdout.readline().strip()
    if not line.startswith("READY "):
        proc.kill()
        raise SystemExit(f"compute server failed to start: {line!r}")
    return proc, line.split(" ", 1)[1]


def _stop_server(srv) -> None:
    """Stops a DefaultVizierServer AND its serving runtime's background
    planes (batch-executor threads would otherwise outlive the arm)."""
    srv.stop(0)
    srv.pythia_servicer.shutdown()


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    rank = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _drive_clients(stacks, *, warmup_rounds: int, rounds: int) -> list:
    """One client thread per (frontend, study): warmup (unrecorded, pays
    XLA compiles), then lockstep measured rounds — every frontend issues
    its suggest in the same window, the arrival pattern the tier exists to
    fuse — completing each suggestion so the next round trains on fresh
    data. Returns sorted per-suggest latencies (seconds)."""
    latencies: list = []
    lat_lock = threading.Lock()
    # Lockstep across BOTH warmup and measured rounds so every round's
    # suggests arrive together in both arms (identical workload shape).
    round_barrier = threading.Barrier(len(stacks))
    errors: list = []

    def client(servicer, study_name):
        c = vizier_client.VizierClient(
            servicer, study_name, "w", reliability=_reliability()
        )
        for r in range(warmup_rounds + rounds):
            round_barrier.wait()
            t0 = time.perf_counter()
            (trial,) = c.get_suggestions(1)
            dt = time.perf_counter() - t0
            if r >= warmup_rounds:
                with lat_lock:
                    latencies.append(dt)
            params = dict(trial.parameters.as_dict())
            c.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": _sphere(params)})
            )

    def wrapped(servicer, study_name):
        try:
            client(servicer, study_name)
        except Exception as e:  # noqa: BLE001 - surfaced after join
            errors.append(f"{study_name}: {e!r}")
            # Unblock peers parked on the barrier.
            round_barrier.abort()

    threads = [
        threading.Thread(target=wrapped, args=(servicer, name))
        for servicer, name in stacks
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise SystemExit(f"client threads failed: {errors}")
    latencies.sort()
    return latencies


def _latency_summary(latencies, label_count) -> dict:
    return {
        "suggest_p50_ms": round(_percentile(latencies, 50) * 1e3, 1),
        "suggest_p99_ms": round(_percentile(latencies, 99) * 1e3, 1),
        "suggestions": len(latencies),
        "frontends": label_count,
    }


def _counter_total(metrics_snapshot: dict, name: str) -> float:
    family = metrics_snapshot.get(name) or {}
    return float(sum((family.get("series") or {}).values()))


def _occupancy_of(metrics_snapshot: dict) -> float:
    """Mean batch-flush occupancy from the ``vizier_batch_occupancy``
    histogram — suggests fused per flush, counting SOLO flushes as 1 (the
    executor runs a lone slot sequentially, so the ``batched_suggests``
    counter alone would undercount the self-contained arm to zero). Same
    formula as ``observability.fleet.compute_tier_section``."""
    family = metrics_snapshot.get("vizier_batch_occupancy") or {}
    total = count = 0.0
    for series in (family.get("series") or {}).values():
        total += float(series.get("sum", 0.0))
        count += float(series.get("count", 0.0))
    return total / count if count else 0.0


def run_shared_arm(args, dump_dir: str) -> dict:
    ids = [f"fe{i}" for i in range(args.frontends)]
    names = _owned_study_names(ids)
    config = _study_config(args.dim, args.acq_evals)

    # Frontends: local Pythia is the FALLBACK only — batching off so the
    # 8 idle executors don't shadow the tier's occupancy evidence.
    servers = {rid: DefaultVizierServer(
        serving_config=ServingConfig(batching=False)
    ) for rid in ids}
    proc = None
    try:
        frontends_spec = ",".join(
            f"{rid}={servers[rid].endpoint}" for rid in ids
        )
        proc, endpoint = _spawn_compute_server(
            frontends=frontends_spec,
            obs_dump_dir=dump_dir,
            max_wait_ms=args.max_wait_ms,
            batch_size=args.frontends,
        )
        stubs = {}
        for rid in ids:
            stub = compute_tier.RemotePythiaStub(
                endpoint,
                local=servers[rid].pythia_servicer,
                replica_id=rid,
                config=compute_tier.ComputeTierConfig(
                    enabled=True, endpoint=endpoint
                ),
            )
            servers[rid].servicer.set_pythia(stub)
            stubs[rid] = stub
        for i, rid in enumerate(ids):
            _create_and_seed(
                servers[rid].servicer,
                names[rid],
                config,
                args.start_trials,
                seed=i + 1,
            )
        latencies = _drive_clients(
            [(servers[rid].servicer, names[rid]) for rid in ids],
            warmup_rounds=args.warmup_rounds,
            rounds=args.rounds,
        )
        stub_stats = {rid: stubs[rid].stats() for rid in ids}
        fallbacks = sum(s["fallback_serves"] for s in stub_stats.values())
        remote_calls = sum(s["remote_calls"] for s in stub_stats.values())
        if fallbacks:
            raise SystemExit(
                f"shared arm leaked {fallbacks} local-fallback serves — the "
                "tier went down mid-measurement; occupancy evidence invalid"
            )

        # Graceful SIGTERM so the server writes its observability dump —
        # the occupancy evidence lives in the CHILD's metrics registry.
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        proc = None
    finally:
        if proc is not None:
            proc.kill()
            proc.wait()
        for srv in servers.values():
            _stop_server(srv)

    metrics = json.loads(
        (pathlib.Path(dump_dir) / "compute-ab-metrics.json").read_text()
    )
    flushes = _counter_total(metrics, "vizier_serving_batch_flushes")
    batched = _counter_total(metrics, "vizier_serving_batched_suggests")
    occupancy = _occupancy_of(metrics)

    # Merge the frontends' spans with the compute server's dump: the hop
    # spans must attribute every frontend (the fleet fan-in view).
    from vizier_tpu.observability import fleet as fleet_lib
    from vizier_tpu.observability import tracing as tracing_lib

    fleet_lib.dump_process(dump_dir, "frontends", tracer=tracing_lib.get_tracer())
    fan_in = fleet_lib.fleet_report(dump_dir)["compute_tier"]

    return {
        **_latency_summary(latencies, args.frontends),
        "mean_batch_occupancy": round(occupancy, 2),
        "batch_flushes": int(flushes),
        "batched_suggests": int(batched),
        "remote_calls": remote_calls,
        "fallback_serves": fallbacks,
        "fan_in": fan_in["fan_in"],
        "fan_in_frontends": fan_in["frontends"],
        "compute_server_occupancy_histogram": fan_in["batch_occupancy"],
    }


def run_self_contained_arm(args) -> dict:
    ids = [f"fe{i}" for i in range(args.frontends)]
    names = _owned_study_names(ids)
    config = _study_config(args.dim, args.acq_evals)
    # The SAME batching knobs the shared tier ran with — each replica just
    # has a private executor, so its flushes only ever see its own study.
    serving_config = ServingConfig(
        batch_max_wait_ms=args.max_wait_ms, batch_max_size=args.frontends
    )
    servers = {
        rid: DefaultVizierServer(serving_config=serving_config) for rid in ids
    }
    try:
        for i, rid in enumerate(ids):
            _create_and_seed(
                servers[rid].servicer,
                names[rid],
                config,
                args.start_trials,
                seed=i + 1,
            )
        latencies = _drive_clients(
            [(servers[rid].servicer, names[rid]) for rid in ids],
            warmup_rounds=args.warmup_rounds,
            rounds=args.rounds,
        )
        flushes = batched = 0
        total = count = 0.0
        for srv in servers.values():
            snap = srv.pythia_servicer.serving_stats()
            flushes += snap.get("batch_flushes", 0)
            batched += snap.get("batched_suggests", 0)
            metrics = srv.pythia_servicer.serving_runtime.metrics.snapshot()
            family = metrics.get("vizier_batch_occupancy") or {}
            for series in (family.get("series") or {}).values():
                total += float(series.get("sum", 0.0))
                count += float(series.get("count", 0.0))
    finally:
        for srv in servers.values():
            _stop_server(srv)
    occupancy = total / count if count else 0.0
    return {
        **_latency_summary(latencies, args.frontends),
        "mean_batch_occupancy": round(occupancy, 2),
        "batch_flushes": int(flushes),
        "batched_suggests": int(batched),
    }


def run_kill_phase(args) -> dict:
    """SIGKILL the compute server mid-run: every suggest still completes
    via the frontends' local fallback (RANDOM_SEARCH — the kill gate
    measures the degradation path, not designer compute)."""
    ids = ["ka", "kb"]
    per_frontend = args.kill_suggests // len(ids)
    config = _study_config(args.dim, 0, algorithm="RANDOM_SEARCH")
    servers = {rid: DefaultVizierServer() for rid in ids}
    proc = None
    completed = {rid: 0 for rid in ids}
    errors: list = []
    try:
        # No --frontends: RANDOM_SEARCH needs no trial read-back, and the
        # kill phase wants a server it can lose without a routed stub
        # half-connected to dead frontends.
        proc, endpoint = _spawn_compute_server(
            frontends="",
            obs_dump_dir="",
            max_wait_ms=5.0,
            batch_size=8,
        )
        stubs = {}
        for rid in ids:
            stub = compute_tier.RemotePythiaStub(
                endpoint,
                local=servers[rid].pythia_servicer,
                replica_id=rid,
                config=compute_tier.ComputeTierConfig(
                    enabled=True, endpoint=endpoint, health_interval_s=0.5
                ),
            )
            servers[rid].servicer.set_pythia(stub)
            stubs[rid] = stub
        for i, rid in enumerate(ids):
            name = f"owners/ab/studies/kill-{rid}"
            servers[rid].servicer.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/ab", study=pc.study_to_proto(config, name)
                )
            )
        kill_at = args.kill_suggests * 2 // 5  # ~40% in, mid-run by design
        progress = threading.Lock()
        killed = threading.Event()

        def client(rid):
            name = f"owners/ab/studies/kill-{rid}"
            c = vizier_client.VizierClient(
                servers[rid].servicer, name, "w", reliability=_reliability()
            )
            for _ in range(per_frontend):
                (trial,) = c.get_suggestions(1)
                c.complete_trial(
                    trial.id, vz.Measurement(metrics={"obj": 0.5})
                )
                with progress:
                    completed[rid] += 1
                    total = sum(completed.values())
                if total >= kill_at and not killed.is_set():
                    killed.set()
                    proc.kill()  # SIGKILL: no drain, no dump, no goodbye

        def wrapped(rid):
            try:
                client(rid)
            except Exception as e:  # noqa: BLE001 - surfaced after join
                errors.append(f"{rid}: {e!r}")

        threads = [threading.Thread(target=wrapped, args=(rid,)) for rid in ids]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        proc.wait()
        proc = None
        stub_stats = {rid: stubs[rid].stats() for rid in ids}
    finally:
        if proc is not None:
            proc.kill()
            proc.wait()
        for srv in servers.values():
            _stop_server(srv)
    total = sum(completed.values())
    remote_calls = sum(s["remote_calls"] for s in stub_stats.values())
    fallback_serves = sum(s["fallback_serves"] for s in stub_stats.values())
    ok = (
        not errors
        and total == args.kill_suggests
        and remote_calls > 0
        and fallback_serves > 0
    )
    return {
        "completed": f"{total}/{args.kill_suggests}",
        "client_errors": errors,
        "remote_calls_before_kill": remote_calls,
        "fallback_serves_after_kill": fallback_serves,
        "ok": bool(ok),
    }


def run_bit_identity(args) -> dict:
    """``VIZIER_COMPUTE_TIER`` unset vs ``=0``: ``maybe_wrap_pythia`` must
    be an identity (no stub layer at all) and the GP suggest trajectories
    through the full service must match bit for bit."""

    def run(env_value):
        saved = os.environ.pop("VIZIER_COMPUTE_TIER", None)
        if env_value is not None:
            os.environ["VIZIER_COMPUTE_TIER"] = env_value
        try:
            srv = DefaultVizierServer(
                serving_config=ServingConfig(
                    batch_max_wait_ms=args.max_wait_ms,
                    batch_max_size=args.frontends,
                )
            )
            try:
                wrapped = compute_tier.maybe_wrap_pythia(
                    srv.pythia_servicer, replica_id="r0"
                )
                identity = wrapped is srv.pythia_servicer
                srv.servicer.set_pythia(wrapped)
                name = "owners/ab/studies/offswitch"
                _create_and_seed(
                    srv.servicer,
                    name,
                    _study_config(args.dim, args.acq_evals),
                    args.start_trials,
                    seed=7,
                )
                c = vizier_client.VizierClient(
                    srv.servicer, name, "w", reliability=_reliability()
                )
                trajectory = []
                for _ in range(args.rounds):
                    (trial,) = c.get_suggestions(1)
                    params = dict(trial.parameters.as_dict())
                    trajectory.append(sorted(params.items()))
                    c.complete_trial(
                        trial.id, vz.Measurement(metrics={"obj": _sphere(params)})
                    )
                return identity, trajectory
            finally:
                _stop_server(srv)
        finally:
            if env_value is not None:
                del os.environ["VIZIER_COMPUTE_TIER"]
            if saved is not None:
                os.environ["VIZIER_COMPUTE_TIER"] = saved

    identity_unset, traj_unset = run(None)
    identity_zero, traj_zero = run("0")
    return {
        "wrap_is_identity": bool(identity_unset and identity_zero),
        "trajectories_match": bool(traj_unset == traj_zero),
        "rounds": args.rounds,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--frontends", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=2)
    parser.add_argument("--warmup-rounds", type=int, default=1)
    # 9 completed seed trials land in the pad_trials=16 bucket; warmup plus
    # measured rounds grow each study to <= 16, so every arm stays on one
    # compiled program per bucket (no mid-measurement recompile).
    parser.add_argument("--start-trials", type=int, default=9)
    parser.add_argument("--dim", type=int, default=4)
    parser.add_argument(
        "--acq-evals",
        type=int,
        default=300,
        help="per-study acquisition sweep budget, applied via the "
        "gp_ucb_pe/max_acquisition_evaluations study-metadata key so BOTH "
        "arms (and the remote compute server) share one designer cost",
    )
    parser.add_argument(
        "--max-wait-ms",
        type=float,
        default=300.0,
        help="batch-executor flush window in every arm; generous so the "
        "8 frontends' concurrent suggests actually meet in one flush",
    )
    parser.add_argument("--kill-suggests", type=int, default=50)
    parser.add_argument("--out", default="COMPUTE_TIER_AB.json")
    args = parser.parse_args()

    from vizier_tpu.converters import padding as padding_lib

    schedule = padding_lib.DEFAULT_PADDING
    end_trials = args.start_trials + args.warmup_rounds + args.rounds
    if schedule.pad_trials(args.start_trials) != schedule.pad_trials(end_trials):
        raise SystemExit(
            f"start_trials={args.start_trials} grows to {end_trials} across "
            "a padding-bucket boundary; shrink --rounds or move "
            "--start-trials."
        )

    # Fast client polling: the A/B measures tier topology, not the
    # client's long-poll cadence.
    vizier_client.environment_variables.polling_delay_secs = 0.005

    config = dict(
        frontends=args.frontends,
        rounds=args.rounds,
        warmup_rounds=args.warmup_rounds,
        start_trials=args.start_trials,
        dim=args.dim,
        designer="VizierGPUCBPEBandit",
        acq_evals=args.acq_evals,
        max_wait_ms=args.max_wait_ms,
        kill_suggests=args.kill_suggests,
        backend=os.environ.get("JAX_PLATFORMS", ""),
    )

    print("[compute_tier_ab] running arm: shared_tier", flush=True)
    with tempfile.TemporaryDirectory(prefix="compute_tier_ab_") as dump_dir:
        shared = run_shared_arm(args, dump_dir)
    print(f"[compute_tier_ab] shared_tier: {json.dumps(shared)}", flush=True)

    print("[compute_tier_ab] running arm: self_contained", flush=True)
    self_contained = run_self_contained_arm(args)
    print(
        f"[compute_tier_ab] self_contained: {json.dumps(self_contained)}",
        flush=True,
    )

    print("[compute_tier_ab] running kill phase", flush=True)
    kill = run_kill_phase(args)
    print(f"[compute_tier_ab] kill: {json.dumps(kill)}", flush=True)

    print("[compute_tier_ab] checking VIZIER_COMPUTE_TIER=0 bit-identity",
          flush=True)
    bit_identity = run_bit_identity(args)
    print(f"[compute_tier_ab] bit_identity: {json.dumps(bit_identity)}",
          flush=True)

    ratio = shared["mean_batch_occupancy"] / max(
        self_contained["mean_batch_occupancy"], 1e-9
    )
    report = {
        "config": config,
        "shared_tier": shared,
        "self_contained": self_contained,
        "kill": kill,
        "bit_identity": bit_identity,
        "verdict": {
            "occupancy_ratio": round(ratio, 2),
            "meets_4x_at_8_frontends": bool(
                ratio >= 4.0 and args.frontends >= 8
            ),
            "kill_completed": kill["completed"],
            "kill_via_local_fallback": kill["ok"],
            "compute_tier_off_bit_identical": bool(
                bit_identity["wrap_is_identity"]
                and bit_identity["trajectories_match"]
            ),
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["verdict"], indent=2))
    ok = (
        report["verdict"]["meets_4x_at_8_frontends"]
        and report["verdict"]["kill_via_local_fallback"]
        and report["verdict"]["compute_tier_off_bit_identical"]
    )
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
