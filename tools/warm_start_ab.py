"""A/B: warm-started vs cold-started ARD for steady-state serving.

Usage: python tools/warm_start_ab.py [--out WARM_START_AB.json]
       [--trials 1000] [--dim 20] [--evals 75000] [--repeats 5]
       [--parity-trials 48] [--parity-seeds 1 2 3 4 5]

Two measurements, one JSON report:

1. **Device-side steady-state suggest latency** at the north-star config
   (1000 trials x 20-D): per repeat, one fresh completed trial replaces a
   row (what a steady-state serving step sees), then the measured step is
   ARD train + one full acquisition sweep.
   - cold arm: ``ard_restarts`` full L-BFGS restarts from random inits —
     the reference's per-request behavior;
   - warm arm: ONE restart seeded with the previous repeat's trained
     unconstrained optimum (the serving runtime's steady state,
     ``ServingConfig.warm_ard_restarts=1``). The L-BFGS ftol early exit is
     what converts the good seed into wall-clock savings.

2. **Regret parity**: full BO loops on shifted 20-D Sphere instances,
   warm (1 warm restart) vs cold (full budget), >= 5 seeds, two-sided
   rank-sum on final regrets. Parity is green when p > 0.05.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import numpy as np


def _progress(msg: str) -> None:
    print(f"[warm_start_ab] {msg}", file=sys.stderr, flush=True)


def measure_latency(args) -> dict:
    import jax

    from vizier_tpu import types
    from vizier_tpu.designers.gp import acquisitions
    from vizier_tpu.designers.gp_bandit import _maximize_acquisition, _train_gp
    from vizier_tpu.models import gp as gp_lib
    from vizier_tpu.models import kernels
    from vizier_tpu.models import output_warpers
    from vizier_tpu.optimizers import eagle as eagle_lib
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib
    from vizier_tpu.optimizers import vectorized as vectorized_lib

    num_trials, dim = args.trials, args.dim
    n_pad = 1 << (num_trials - 1).bit_length()
    rng = np.random.default_rng(0)
    x = rng.uniform(size=(num_trials, dim)).astype(np.float32)
    y = -np.sum((x - 0.5) ** 2, axis=1) + 0.1 * rng.normal(size=num_trials)
    warper = output_warpers.create_default_warper()

    def make_data(step: int) -> gp_lib.GPData:
        """One fresh observation per steady-state step (row swap keeps the
        padded shapes — and therefore the jit cache — identical)."""
        xs, ys = x.copy(), y.copy()
        if step > 0:
            row = (step * 37) % num_trials
            r = np.random.default_rng(1000 + step)
            xs[row] = r.uniform(size=dim).astype(np.float32)
            ys[row] = -np.sum((xs[row] - 0.5) ** 2) + 0.1 * r.normal()
        warped = output_warpers.create_default_warper()(ys)
        features = types.ContinuousAndCategorical(
            continuous=types.PaddedArray.from_array(xs, (n_pad, dim)),
            categorical=types.PaddedArray.from_array(
                np.zeros((num_trials, 0), np.int32), (n_pad, 0), fill_value=0
            ),
        )
        labels = types.PaddedArray.from_array(
            warped[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
        )
        return gp_lib.GPData.from_model_data(types.ModelData(features, labels))

    model = gp_lib.VizierGaussianProcess(num_continuous=dim, num_categorical=0)
    ard = lbfgs_lib.LbfgsOptimizer(maxiter=50)
    strategy = eagle_lib.VectorizedEagleStrategy(
        num_continuous=dim, category_sizes=()
    )
    vec_opt = vectorized_lib.VectorizedOptimizer(
        strategy, max_evaluations=args.evals
    )
    coll = model.param_collection()
    cold_restarts = lbfgs_lib.DEFAULT_RANDOM_RESTARTS

    def sweep(states, data, key):
        predictive = gp_lib.EnsemblePredictive(states)
        best_label = jax.numpy.max(
            jax.numpy.where(data.row_mask, data.labels, -jax.numpy.inf)
        )
        scoring = acquisitions.ScoringFunction(
            predictive=predictive,
            acquisition=acquisitions.UCB(1.8),
            best_label=best_label,
            trust_region=acquisitions.TrustRegion.from_data(data),
        )
        return _maximize_acquisition(
            vec_opt, scoring, key, args.batch,
            kernels.MixedFeatures(data.continuous[:10], data.categorical[:10]),
        )

    datas = [make_data(i) for i in range(args.repeats + 1)]

    def run_arm(warm: bool):
        times = []
        prev_params = None
        for step, data in enumerate(datas):
            key = jax.random.PRNGKey(step)
            k_train, k_acq = jax.random.split(key)
            t0 = time.perf_counter()
            if warm and prev_params is not None:
                states = _train_gp(model, ard, data, k_train, 1, 1, prev_params)
            else:
                states = _train_gp(model, ard, data, k_train, cold_restarts, 1)
            result = sweep(states, data, k_acq)
            jax.block_until_ready(result)
            elapsed = (time.perf_counter() - t0) * 1000.0
            if warm:
                prev_params = coll.unconstrain(
                    jax.tree_util.tree_map(lambda a: a[0], states.params)
                )
                jax.block_until_ready(prev_params)
                if step == 0:
                    # Pre-compile the 1-restart warm program so the first
                    # TIMED step measures compute, not XLA compilation.
                    jax.block_until_ready(
                        _train_gp(model, ard, data, k_train, 1, 1, prev_params)
                    )
            # step 0 is the compile/bootstrap run for BOTH arms (and the
            # warm arm's mandatory first cold train): excluded.
            if step > 0:
                times.append(elapsed)
                _progress(
                    f"{'warm' if warm else 'cold'} step {step}: {elapsed:.0f} ms"
                )
        return times

    _progress(f"latency: cold arm at {num_trials}x{dim}d, {args.evals} evals")
    cold_times = run_arm(warm=False)
    _progress("latency: warm arm")
    warm_times = run_arm(warm=True)
    cold_p50 = float(np.percentile(cold_times, 50))
    warm_p50 = float(np.percentile(warm_times, 50))
    return {
        "config": {
            "num_trials": num_trials,
            "dim": dim,
            "max_evaluations": args.evals,
            "batch": args.batch,
            "cold_restarts": cold_restarts,
            "warm_restarts": 1,
            "repeats": args.repeats,
        },
        "cold_suggest_p50_ms": round(cold_p50, 1),
        "warm_suggest_p50_ms": round(warm_p50, 1),
        "cold_suggest_ms": [round(t, 1) for t in cold_times],
        "warm_suggest_ms": [round(t, 1) for t in warm_times],
        "speedup": round(cold_p50 / warm_p50, 3),
    }


def rank_sum_p(a, b) -> float:
    """Two-sided Mann-Whitney p (normal approximation), H0: same dist."""
    from scipy import stats

    a, b = np.asarray(a, float), np.asarray(b, float)
    ranks = stats.rankdata(np.concatenate([a, b]))
    n, m = len(a), len(b)
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    mu, sigma = n * m / 2.0, np.sqrt(n * m * (n + m + 1) / 12.0)
    return float(2.0 * (1.0 - stats.norm.cdf(abs(u - mu) / max(sigma, 1e-9))))


def measure_parity(args) -> dict:
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import experimenter_factory
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    def run_arm(seed: int, warm: bool) -> float:
        exp = experimenter_factory.shifted_bbob_instance(
            "Sphere", seed, dim=args.dim
        )
        designer = VizierGPUCBPEBandit(
            exp.problem_statement(),
            rng_seed=seed,
            num_seed_trials=5,
            max_acquisition_evaluations=args.parity_evals,
            use_warm_start_ard=warm,
            warm_ard_restarts=1 if warm else None,
        )
        best, tid = np.inf, 0
        while tid < args.parity_trials:
            batch = [
                s.to_trial(tid + i + 1)
                for i, s in enumerate(designer.suggest(args.parity_batch))
            ]
            tid += len(batch)
            exp.evaluate(batch)
            designer.update(core_lib.CompletedTrials(batch))
            for t in batch:
                best = min(best, t.final_measurement.metrics["bbob_eval"].value)
        return best

    warm_finals, cold_finals = [], []
    for seed in args.parity_seeds:
        t0 = time.perf_counter()
        warm_finals.append(run_arm(seed, warm=True))
        cold_finals.append(run_arm(seed, warm=False))
        _progress(
            f"parity seed {seed}: warm={warm_finals[-1]:.4f} "
            f"cold={cold_finals[-1]:.4f} ({time.perf_counter() - t0:.0f}s)"
        )
    p = rank_sum_p(warm_finals, cold_finals)
    return {
        "config": {
            "fn": "Sphere(shifted)",
            "dim": args.dim,
            "trials": args.parity_trials,
            "batch": args.parity_batch,
            "max_evaluations": args.parity_evals,
            "seeds": list(args.parity_seeds),
        },
        "warm_final_regrets": [round(v, 4) for v in warm_finals],
        "cold_final_regrets": [round(v, 4) for v in cold_finals],
        "rank_sum_p": round(p, 4),
        "parity_green": p > 0.05,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="WARM_START_AB.json")
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--dim", type=int, default=20)
    ap.add_argument("--evals", type=int, default=75_000)
    ap.add_argument("--batch", type=int, default=25)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--parity-trials", type=int, default=45)
    ap.add_argument("--parity-batch", type=int, default=5)
    ap.add_argument("--parity-evals", type=int, default=2_000)
    ap.add_argument("--parity-seeds", type=int, nargs="+", default=[1, 2, 3, 4, 5])
    ap.add_argument("--skip-latency", action="store_true")
    ap.add_argument("--skip-parity", action="store_true")
    args = ap.parse_args()

    import jax

    report = {
        "backend": jax.default_backend(),
        "note": (
            "Warm-started steady-state ARD (serving designer cache, "
            "warm_ard_restarts=1) vs the reference's cold per-request "
            "train. Latency is the device-side suggest step (ARD train + "
            "acquisition sweep) at the north-star scale; parity is "
            "two-sided rank-sum on final regrets over full BO loops."
        ),
    }
    if not args.skip_latency:
        report["latency"] = measure_latency(args)
    if not args.skip_parity:
        report["parity"] = measure_parity(args)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
