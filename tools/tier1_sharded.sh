#!/bin/bash
# Sharded tier-1 recipe: the full suite as per-directory groups, each run
# to completion under the SAME flags as the single-process tier-1 line.
#
# Why: the 870 s tier-1 wall no longer fits the whole suite in one pytest
# process on a 1-core container — the unmodified seed also times out there
# (rc=124, ~81% of dots emitted; see ROADMAP.md "Tier-1 timing"). Sharding
# by directory keeps every group inside the wall with the identical
# selection (-m 'not slow') and plugin set, so a red test cannot hide
# behind the timeout.
#
# Usage:  bash tools/tier1_sharded.sh            # all groups
#         TIER1_SHARD_TIMEOUT=600 bash tools/tier1_sharded.sh
#
# Exit status: nonzero if ANY group fails (including a group timeout).
set -u
cd "$(dirname "$0")/.."

TIMEOUT="${TIER1_SHARD_TIMEOUT:-870}"
FLAGS=(-q -m 'not slow' --continue-on-collection-errors
       -p no:cacheprovider -p no:xdist -p no:randomly)

# One group per line; directories grouped so each fits the wall with slack
# (measured on the 1-core container; heaviest groups get their own shard).
GROUPS_LIST=(
  "tests/analysis"
  "tests/parallel tests/compute"
  "tests/loadgen"
  "tests/serving"
  "tests/observability"
  "tests/service tests/reliability tests/distributed tests/surrogates tests/pythia tests/pyvizier --ignore=tests/distributed/test_compute_tier.py"
  "tests/distributed/test_compute_tier.py"
  "tests/designers tests/algorithms tests/converters tests/models"
  "tests/benchmarks tests/pyglove tests/test_aux.py tests/test_conformance_and_surrogates.py tests/test_imports.py tests/test_round1_extras.py"
)

overall_rc=0
total_passed=0
summary=()
for group in "${GROUPS_LIST[@]}"; do
  echo "== tier1 shard: ${group} =="
  log="$(mktemp /tmp/tier1_shard.XXXXXX.log)"
  # shellcheck disable=SC2086  # the group is a space-separated path list
  timeout -k 10 "${TIMEOUT}" env JAX_PLATFORMS=cpu \
    python -m pytest ${group} "${FLAGS[@]}" 2>&1 | tee "${log}"
  rc=${PIPESTATUS[0]}
  passed=$(grep -aoE '[0-9]+ passed' "${log}" | tail -1 | grep -oE '[0-9]+' || echo 0)
  total_passed=$((total_passed + passed))
  if [ "${rc}" -ne 0 ]; then
    overall_rc=1
    summary+=("FAIL rc=${rc} (${passed} passed)  ${group}")
  else
    summary+=("ok   (${passed} passed)  ${group}")
  fi
  rm -f "${log}"
done

echo
echo "== tier1 sharded summary =="
for line in "${summary[@]}"; do echo "  ${line}"; done
echo "TOTAL_PASSED=${total_passed}"
exit "${overall_rc}"
