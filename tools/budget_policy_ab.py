"""A/B of the acquisition budget policies on shifted 20-D BBOB.

Usage: python tools/budget_policy_ab.py [--trials 150] [--seeds 1 2 3 4 5]

Compares first_pick_full (the shipped default: full budget on the
exploitation pick, one further budget split across the exploration picks)
against per_pick (reference semantics, a full budget on EVERY pick) and
per_batch (one split budget) on the same pinned shifted instances as
parity_suite.py / the CI gate (experimenter_factory.shifted_bbob_instance).
Prints one JSON line per (function, policy, seed) plus a summary. Measured
round 4: first_pick_full matches-or-beats per_pick regret at ~1/12th the
acquisition compute; per_batch degrades 20-D exploitation measurably.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=150)
    ap.add_argument("--batch", type=int, default=10)
    ap.add_argument("--evals", type=int, default=25_000)
    ap.add_argument("--seeds", type=int, nargs="+", default=[1, 2, 3, 4, 5])
    args = ap.parse_args()

    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import experimenter_factory
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    results: dict = {}
    # Two 20-D BBOB families plus a low-D classic (Branin in the BBOB
    # frame, bbob.EXTRA_FUNCTIONS) so the DEFAULT-policy evidence does not
    # rest on one dimensionality regime (r4 verdict weak #2).
    configs = (("Sphere", 20), ("Rastrigin", 20), ("Branin", 2))
    # Optimum VALUE of each objective (shift moves the argmin, not the
    # minimum): Sphere/Rastrigin are 0 at the optimum; Branin ≈ 0.397887
    # (synthetic/bbob.py:381). Subtracted so "final_regret" is a true
    # regret, comparable across functions.
    optima = {"Sphere": 0.0, "Rastrigin": 0.0, "Branin": 0.3978873577}
    for fn_name, dim in configs:
        for policy in ("first_pick_full", "per_batch", "per_pick"):
            finals = []
            for seed in args.seeds:
                exp = experimenter_factory.shifted_bbob_instance(
                    fn_name, seed, dim=dim
                )
                problem = exp.problem_statement()
                designer = VizierGPUCBPEBandit(
                    problem,
                    rng_seed=seed,
                    max_acquisition_evaluations=args.evals,
                    num_seed_trials=5,
                    acquisition_budget_policy=policy,
                )
                best, tid = np.inf, 0
                t0 = time.perf_counter()
                while tid < args.trials:
                    batch = [
                        s.to_trial(tid + i + 1)
                        for i, s in enumerate(designer.suggest(args.batch))
                    ]
                    tid += len(batch)
                    exp.evaluate(batch)
                    designer.update(core_lib.CompletedTrials(batch))
                    for t in batch:
                        best = min(
                            best,
                            t.final_measurement.metrics["bbob_eval"].value,
                        )
                elapsed = time.perf_counter() - t0
                best -= optima[fn_name]
                finals.append(best)
                print(
                    json.dumps(
                        {
                            "fn": fn_name,
                            "dim": dim,
                            "policy": policy,
                            "seed": seed,
                            "final_regret": round(best, 4),
                            "wall_s": round(elapsed, 1),
                        }
                    ),
                    flush=True,
                )
            results[(f"{fn_name}{dim}d", policy)] = finals
    print("== summary (median final regret, lower better) ==", flush=True)
    summary = {}
    for (cfg_name, policy), finals in results.items():
        summary[f"{cfg_name}:{policy}"] = float(np.median(finals))
    print(json.dumps(summary, indent=1))
    artifact = {
        "seeds": args.seeds,
        "trials": args.trials,
        "batch": args.batch,
        "evals": args.evals,
        "per_run": {
            f"{cfg}:{pol}": [round(v, 4) for v in finals]
            for (cfg, pol), finals in results.items()
        },
        "median_final_regret": summary,
    }
    out = os.path.join(_REPO_ROOT, "budget_ab_r5.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out}", flush=True)


if __name__ == "__main__":
    main()
