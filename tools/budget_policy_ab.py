"""A/B of the acquisition budget policies on shifted 20-D BBOB.

Usage: python tools/budget_policy_ab.py [--trials 150] [--seeds 1 2]

Compares first_pick_full (the shipped default: full budget on the
exploitation pick, one further budget split across the exploration picks)
against per_pick (reference semantics, a full budget on EVERY pick) and
per_batch (one split budget) on the same pinned shifted instances as
parity_suite.py / the CI gate (experimenter_factory.shifted_bbob_instance).
Prints one JSON line per (function, policy, seed) plus a summary. Measured
round 4: first_pick_full matches-or-beats per_pick regret at ~1/12th the
acquisition compute; per_batch degrades 20-D exploitation measurably.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=150)
    ap.add_argument("--batch", type=int, default=10)
    ap.add_argument("--evals", type=int, default=25_000)
    ap.add_argument("--seeds", type=int, nargs="+", default=[1, 2])
    args = ap.parse_args()

    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import experimenter_factory
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    results: dict = {}
    for fn_name in ("Sphere", "Rastrigin"):
        for policy in ("first_pick_full", "per_batch", "per_pick"):
            finals = []
            for seed in args.seeds:
                exp = experimenter_factory.shifted_bbob_instance(fn_name, seed)
                problem = exp.problem_statement()
                designer = VizierGPUCBPEBandit(
                    problem,
                    rng_seed=seed,
                    max_acquisition_evaluations=args.evals,
                    num_seed_trials=5,
                    acquisition_budget_policy=policy,
                )
                best, tid = np.inf, 0
                t0 = time.perf_counter()
                while tid < args.trials:
                    batch = [
                        s.to_trial(tid + i + 1)
                        for i, s in enumerate(designer.suggest(args.batch))
                    ]
                    tid += len(batch)
                    exp.evaluate(batch)
                    designer.update(core_lib.CompletedTrials(batch))
                    for t in batch:
                        best = min(
                            best,
                            t.final_measurement.metrics["bbob_eval"].value,
                        )
                elapsed = time.perf_counter() - t0
                finals.append(best)
                print(
                    json.dumps(
                        {
                            "fn": fn_name,
                            "policy": policy,
                            "seed": seed,
                            "final_regret": round(best, 4),
                            "wall_s": round(elapsed, 1),
                        }
                    ),
                    flush=True,
                )
            results[(fn_name, policy)] = finals
    print("== summary (median final regret, lower better) ==", flush=True)
    summary = {}
    for (fn_name, policy), finals in results.items():
        summary[f"{fn_name}:{policy}"] = float(np.median(finals))
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()
