"""Decomposes the DEFAULT designer's e2e suggest() cost at full scale.

Usage: JAX_PLATFORMS=cpu python tools/profile_e2e.py [--trials 1000] [--evals 75000]

Prints a per-stage wall-clock table for one steady-state suggest(25):
encode/warp (host), ARD train (device), suggest-batch (device), decode.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import jax
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--evals", type=int, default=75_000)
    ap.add_argument("--batch", type=int, default=25)
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()

    from vizier_tpu import pyvizier as vz
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    dim = 20
    rng = np.random.default_rng(0)
    x = rng.uniform(size=(args.trials, dim))
    y = -np.sum((x - 0.5) ** 2, axis=1) + 0.1 * rng.normal(size=args.trials)

    problem = vz.ProblemStatement()
    for d in range(dim):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    designer = VizierGPUCBPEBandit(
        problem, max_acquisition_evaluations=args.evals
    )
    trials = []
    for i in range(args.trials):
        t = vz.Trial(
            id=i + 1, parameters={f"x{d}": float(x[i, d]) for d in range(dim)}
        )
        t.complete(vz.Measurement(metrics={"obj": float(y[i])}))
        trials.append(t)

    t0 = time.perf_counter()
    designer.update(core_lib.CompletedTrials(trials))
    print(f"update(all {args.trials}): {time.perf_counter()-t0:.3f}s")

    # Instrument the stages by monkey-timing the designer internals.
    stage: dict = {}

    orig_train = designer._train_states_me

    def timed_train():
        t0 = time.perf_counter()
        # Sub-time the host-side encode inside by instrumenting the converter.
        conv = designer._converter
        orig_enc = conv.metrics.encode
        orig_feat = designer._padded_features

        def enc(trials):
            s = time.perf_counter()
            out = orig_enc(trials)
            stage["metrics.encode"] = stage.get("metrics.encode", 0) + (
                time.perf_counter() - s
            )
            return out

        def feat(trials, extra_rows=0):
            s = time.perf_counter()
            out = orig_feat(trials, extra_rows)
            stage["padded_features"] = stage.get("padded_features", 0) + (
                time.perf_counter() - s
            )
            return out

        object.__setattr__(conv.metrics, "encode", enc)
        designer._padded_features = feat
        try:
            out = orig_train()
            jax.block_until_ready(out[0].params if hasattr(out[0], "params") else out[0])
        finally:
            object.__setattr__(conv.metrics, "encode", orig_enc)
            designer._padded_features = orig_feat
        stage["train_states_me(total)"] = stage.get(
            "train_states_me(total)", 0
        ) + (time.perf_counter() - t0)
        return out

    designer._train_states_me = timed_train

    from vizier_tpu.designers import gp_ucb_pe as mod

    orig_suggest_batch = mod._suggest_batch

    def timed_suggest_batch(*a, **kw):
        t0 = time.perf_counter()
        out = orig_suggest_batch(*a, **kw)
        jax.block_until_ready(out[0].scores)
        stage["suggest_batch(jit)"] = stage.get("suggest_batch(jit)", 0) + (
            time.perf_counter() - t0
        )
        return out

    mod._suggest_batch = timed_suggest_batch

    orig_all_points = designer._all_points_data

    def timed_all_points(count):
        t0 = time.perf_counter()
        out = orig_all_points(count)
        stage["all_points_data"] = stage.get("all_points_data", 0) + (
            time.perf_counter() - t0
        )
        return out

    designer._all_points_data = timed_all_points

    orig_decode = designer._decode_ucb_pe

    def timed_decode(*a, **kw):
        t0 = time.perf_counter()
        out = orig_decode(*a, **kw)
        stage["decode"] = stage.get("decode", 0) + (time.perf_counter() - t0)
        return out

    designer._decode_ucb_pe = timed_decode

    print("compile pass (not counted):", flush=True)
    t0 = time.perf_counter()
    designer.suggest(args.batch)
    print(f"  compile suggest: {time.perf_counter()-t0:.1f}s", flush=True)

    next_id = args.trials + 1
    totals = []
    for r in range(args.repeats):
        stage.clear()
        fresh = vz.Trial(
            id=next_id,
            parameters={f"x{d}": float(v) for d, v in enumerate(rng.uniform(size=dim))},
        )
        fresh.complete(vz.Measurement(metrics={"obj": float(-r)}))
        next_id += 1
        t0 = time.perf_counter()
        designer.update(core_lib.CompletedTrials([fresh]))
        designer.suggest(args.batch)
        total = time.perf_counter() - t0
        totals.append(total)
        print(f"repeat {r}: total {total*1000:.0f} ms", flush=True)
        for k, v in sorted(stage.items(), key=lambda kv: -kv[1]):
            print(f"  {k:28s} {v*1000:9.1f} ms ({100*v/total:5.1f}%)")
        # metrics.encode / padded_features are nested inside
        # train_states_me(total); only top-level intervals count here.
        top_level = sum(
            v
            for k, v in stage.items()
            if k not in ("metrics.encode", "padded_features")
        )
        other = total - top_level
        print(f"  {'(other/untimed)':28s} {other*1000:9.1f} ms ({100*other/total:5.1f}%)")
    print(f"p50 total: {np.percentile(totals, 50)*1000:.0f} ms")


if __name__ == "__main__":
    main()
