"""Noise-robustness sweep: the DEFAULT designer under the BBOB-noisy zoo.

Usage: python tools/noise_robustness.py [--trials 60] [--seeds 1 2 3]

The r4 review noted noise-robustness experiments (a stated use of the
wrapper zoo) could not be reproduced with a Gaussian-only wrapper. This
tool runs ``VizierGPUCBPEBandit`` on shifted 4-D Sphere under every noise
model in ``wrappers.NOISE_TYPES`` and reports the final TRUE simple
regret (the ``_before_noise`` metric of the observed-noisy incumbent:
what the tuner actually delivered, judged on clean ground truth). Writes
``noise_robustness_r5.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from __graft_entry__ import _honor_platform_env

_honor_platform_env()

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=60)
    ap.add_argument("--batch", type=int, default=5)
    ap.add_argument("--evals", type=int, default=4000)
    ap.add_argument("--dim", type=int, default=4)
    ap.add_argument("--seeds", type=int, nargs="+", default=[1, 2, 3])
    args = ap.parse_args()

    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.benchmarks.experimenters import (
        experimenter_factory,
        wrappers,
    )
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    results: dict = {}
    for noise_type in wrappers.NOISE_TYPES:
        finals = []
        for seed in args.seeds:
            clean = experimenter_factory.shifted_bbob_instance(
                "Sphere", seed, dim=args.dim
            )
            exp = wrappers.NoisyExperimenter.from_type(
                clean, noise_type, seed=seed
            )
            designer = VizierGPUCBPEBandit(
                exp.problem_statement(),
                rng_seed=seed,
                max_acquisition_evaluations=args.evals,
                num_seed_trials=5,
            )
            best_noisy, best_true, tid = np.inf, np.inf, 0
            while tid < args.trials:
                batch = [
                    s.to_trial(tid + i + 1)
                    for i, s in enumerate(designer.suggest(args.batch))
                ]
                tid += len(batch)
                exp.evaluate(batch)
                designer.update(core_lib.CompletedTrials(batch))
                for t in batch:
                    m = t.final_measurement.metrics
                    noisy = m["bbob_eval"].value
                    if noisy < best_noisy:
                        best_noisy = noisy
                        # True regret of the incumbent the tuner believes in.
                        best_true = m["bbob_eval_before_noise"].value
            finals.append(best_true)
            print(
                json.dumps(
                    {
                        "noise": noise_type,
                        "seed": seed,
                        "true_regret": round(best_true, 4),
                    }
                ),
                flush=True,
            )
        results[noise_type] = {
            "per_seed_true_regret": [round(v, 4) for v in finals],
            "median": round(float(np.median(finals)), 4),
        }
    artifact = {
        "config": (
            f"shifted Sphere {args.dim}-D, {args.trials} trials x batch "
            f"{args.batch}, DEFAULT designer, seeds {args.seeds}"
        ),
        "metric": "true simple regret of the noisy-incumbent (before_noise)",
        "results": results,
    }
    out = os.path.join(_REPO_ROOT, "noise_robustness_r5.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out}", flush=True)


if __name__ == "__main__":
    main()
