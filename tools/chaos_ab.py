#!/usr/bin/env python
"""Chaos A/B: study completion under injected faults, reliability on vs off.

Runs the same seeded fault schedule (probabilistic designer failures plus
transport faults between client and service) against two arms:

- **reliability_on** — retries + deadline propagation + circuit breaker +
  quasi-random fallback (the vizier_tpu.reliability defaults, with the
  breaker window compressed to match test-speed suggest rates);
- **reliability_off** — ``ReliabilityConfig.disabled()``, the seed's
  fail-hard behavior.

Evidence lands in ``CHAOS_AB.json``: completed trials, fallback rate,
retry/breaker/deadline counters, and per-site injection counts. The
expected shape: the ON arm completes every trial with a bounded fallback
rate (≈ the injected designer-fault rate); the OFF arm dies at the first
injected fault that reaches the client.

Usage:  python tools/chaos_ab.py [--trials 50] [--seed 11] [--fault-prob 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.designers import random as random_designer
from vizier_tpu.observability import MetricsRegistry, ObservabilityConfig
from vizier_tpu.reliability import ReliabilityConfig, is_fallback_suggestion
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu.testing import chaos

STUDY = "owners/chaos/studies/ab"


def _study_config() -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.search_space.root.add_float_param("y", -1.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


class _ChaosPolicyFactory:
    def __init__(self, monkey: chaos.ChaosMonkey):
        self._monkey = monkey

    def __call__(self, problem, algorithm, supporter, study_name):
        return designer_policy.DesignerPolicy(
            supporter,
            chaos.chaos_designer_factory(
                lambda p, **kw: random_designer.RandomDesigner(
                    p.search_space, seed=0
                ),
                self._monkey,
            ),
        )


def run_arm(
    *, trials: int, seed: int, fault_prob: float, reliability: ReliabilityConfig
) -> dict:
    monkey = chaos.ChaosMonkey(seed=seed, failure_prob=fault_prob)
    servicer = vizier_service.VizierServicer(reliability_config=reliability)
    pythia = pythia_service.PythiaServicer(
        servicer, _ChaosPolicyFactory(monkey), reliability_config=reliability
    )
    servicer.set_pythia(pythia)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/chaos",
            study=pc.study_to_proto(_study_config(), STUDY),
        )
    )
    client = vizier_client.VizierClient(
        chaos.ChaosServiceStub(servicer, monkey),
        STUDY,
        "chaos-worker",
        reliability=reliability,
    )

    # Per-suggest latency distribution via the observability histogram —
    # under injected faults the tail (retries, breaker cooldowns, fallback
    # detours) is the story a bare mean would bury.
    suggest_hist = MetricsRegistry().histogram(
        "chaos_suggest_latency_seconds", help="chaos_ab per-suggest wall time"
    )
    completed = fallback_trials = 0
    error = None
    start = time.perf_counter()
    try:
        for i in range(trials):
            t0 = time.perf_counter()
            (trial,) = client.get_suggestions(1)
            suggest_hist.observe(time.perf_counter() - t0)
            if is_fallback_suggestion(trial.metadata):
                fallback_trials += 1
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
            )
            completed += 1
    except Exception as e:  # the OFF arm is expected to land here
        error = f"{type(e).__name__}: {e}"
    elapsed = time.perf_counter() - start

    def _ms(q: float):
        value = suggest_hist.percentile(q)
        return round(value * 1000.0, 2) if value is not None else None

    stats = pythia.serving_stats()
    return {
        "completed_trials": completed,
        "target_trials": trials,
        "failed": error is not None,
        "error": error,
        "fallback_trials": fallback_trials,
        "fallback_rate": fallback_trials / max(1, completed),
        "elapsed_secs": round(elapsed, 3),
        "suggest_latency_ms": {"p50": _ms(50), "p95": _ms(95), "p99": _ms(99)},
        "serving_stats": {k: v for k, v in sorted(stats.items()) if v},
        "injected": monkey.counts(),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--trials", type=int, default=50)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--fault-prob", type=float, default=0.1)
    parser.add_argument(
        "--out",
        default=str(pathlib.Path(__file__).resolve().parent.parent / "CHAOS_AB.json"),
    )
    args = parser.parse_args()

    # Fast client backoffs: the A/B measures completion/fallback behavior,
    # not wall-clock sleeps.
    vizier_client.environment_variables.polling_delay_secs = 0.005

    arms = {
        "reliability_on": ReliabilityConfig(
            retry_base_delay_secs=0.01,
            retry_max_delay_secs=0.1,
            # The breaker's sliding window assumes production suggest rates
            # (designer runs are seconds apart); at test speed 50 suggests
            # land inside one 60 s window, so the window is compressed to
            # keep "N failures within a window" meaning the same thing.
            breaker_window_secs=0.5,
            breaker_cooldown_secs=0.2,
        ),
        "reliability_off": ReliabilityConfig.disabled(),
    }
    report = {
        "config": {
            "trials": args.trials,
            "seed": args.seed,
            "designer_fault_prob": args.fault_prob,
            "transport_fault_prob": args.fault_prob,
            "algorithm": "RANDOM_SEARCH (chaos-wrapped designer)",
            "observability": ObservabilityConfig.from_env().as_dict(),
        },
        "arms": {},
    }
    for name, reliability in arms.items():
        print(f"[chaos_ab] running arm: {name}")
        report["arms"][name] = run_arm(
            trials=args.trials,
            seed=args.seed,
            fault_prob=args.fault_prob,
            reliability=reliability,
        )

    on, off = report["arms"]["reliability_on"], report["arms"]["reliability_off"]
    report["verdict"] = {
        "on_completed_all": on["completed_trials"] == args.trials,
        "on_fallback_rate": round(on["fallback_rate"], 4),
        "off_failed": off["failed"],
        "off_completed": off["completed_trials"],
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["verdict"], indent=2))
    print(f"[chaos_ab] wrote {args.out}")


if __name__ == "__main__":
    main()
