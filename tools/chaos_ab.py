#!/usr/bin/env python
"""Chaos A/B: study completion under injected faults, reliability on vs off.

Runs the same seeded fault schedule (probabilistic designer failures plus
transport faults between client and service) against two arms:

- **reliability_on** — retries + deadline propagation + circuit breaker +
  quasi-random fallback (the vizier_tpu.reliability defaults, with the
  breaker window compressed to match test-speed suggest rates);
- **reliability_off** — ``ReliabilityConfig.disabled()``, the seed's
  fail-hard behavior.

Evidence lands in ``CHAOS_AB.json``: completed trials, fallback rate,
retry/breaker/deadline counters, and per-site injection counts. The
expected shape: the ON arm completes every trial with a bounded fallback
rate (≈ the injected designer-fault rate); the OFF arm dies at the first
injected fault that reaches the client.

Usage:  python tools/chaos_ab.py [--trials 50] [--seed 11] [--fault-prob 0.1]
        [--distributed N] [--kill-at K] [--no-shared-fs]
        [--replica-mode subprocess] [--partition]
        [--instrument-locks] [--mesh-devices N]

``--replica-mode subprocess`` (with ``--distributed``) adds the
**subprocess_partition** arm: an N-replica fleet of REAL ``replica_main``
processes managed by the lease-based ``SubprocessReplicaManager`` —
standby logs stream between processes over the ``ReplicationService``
gRPC surface, heartbeat leases detect death, and failover replays from
standby logs collected over the wire. The schedule SIGKILLs the owner
mid-run and (with ``--partition``) later partitions the next owner away
from the driver via ``testing/netchaos.py`` (heartbeats and client RPCs
drop; the replica itself keeps running), heals the partition, and drives
one stale append directly at the zombie. The verdict asserts all trials
completed, zero lost studies (every driven trial accounted through the
failed-over tier, the zombie's stale trial NOT among them), >= 1 standby
recovery, and >= 1 fenced stale-append rejection observed via heartbeat.
The same invocation also runs the **replication_off_identity** check:
the in-process kill-the-owner arm under ``VIZIER_DISTRIBUTED_REPLICATION
=0`` must produce a bit-identical suggestion trajectory to the
replication-on arm (the off switch IS the PR 12 legacy path).

``--no-shared-fs`` (with ``--distributed``) adds the **replicated_failover**
arm: same kill-the-owner schedule, but the dead replica's WAL directory is
``rm -rf``'d at the moment of the kill — the run can only complete via the
rendezvous successors' replication standby logs
(``distributed/replication.py``), proving failover needs no shared
filesystem. The verdict asserts all trials completed AND >= 1 study was
recovered from source ``standby``.

``--mesh-devices N`` adds a mesh-executor chaos arm: chaos-wrapped GP
designers across multiple shape buckets drive a mesh-sharded
``BatchExecutor`` (``parallel.mesh``, N simulated devices, per-placement
dispatch workers) under the same seeded fault schedule. A device-program
strike poisons ONE placement's flush; the arm asserts the strike degrades
only that flush's slots (sequential fallback / isolated designer errors)
while other placements keep serving — and, with ``--instrument-locks``,
that the per-placement worker threads' runtime lock order is a subset of
the static graph.

``--distributed N`` adds a third arm: the same seeded fault schedule
against an N-replica sharded tier (``vizier_tpu.distributed``) with
snapshot+WAL persistence, and at trial ``--kill-at`` (default: halfway)
the replica that owns the study is KILLED. The run must still complete
every trial: the routed stub surfaces the dead replica, the manager fails
its studies over to the rendezvous successors by WAL replay, and the
client's retry machinery lands on the successor — with the breaker /
fallback counters still visible in the shared-Pythia serving stats.

``--instrument-locks`` runs every arm under
``analysis.debug_locks.instrument()`` and cross-checks the runtime
acquisition order against the static lock-order graph (now including the
router/WAL locks) when the soak finishes; an observed edge the static
pass missed fails the run. This is the chaos-soak ↔ static-analysis
cross-check the long `slow`-marked soak in
``tests/distributed/test_chaos_soak.py`` runs in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")


def _peek_int_flag(name: str, default: int) -> int:
    """Reads an int flag from argv BEFORE the jax-importing modules below
    (the mesh arm must set --xla_force_host_platform_device_count before
    jax's backend initializes)."""
    for i, arg in enumerate(sys.argv):
        if arg == name and i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
        if arg.startswith(name + "="):
            return int(arg.split("=", 1)[1])
    return default


_MESH_DEVICES = _peek_int_flag("--mesh-devices", 0)
if _MESH_DEVICES:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags
            + f" --xla_force_host_platform_device_count={_MESH_DEVICES}"
        ).strip()

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.designers import random as random_designer
from vizier_tpu.observability import MetricsRegistry, ObservabilityConfig
from vizier_tpu.reliability import ReliabilityConfig, is_fallback_suggestion
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu.testing import chaos

STUDY = "owners/chaos/studies/ab"


def _study_config() -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.search_space.root.add_float_param("y", -1.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


class _ChaosPolicyFactory:
    def __init__(self, monkey: chaos.ChaosMonkey):
        self._monkey = monkey

    def __call__(self, problem, algorithm, supporter, study_name):
        return designer_policy.DesignerPolicy(
            supporter,
            chaos.chaos_designer_factory(
                lambda p, **kw: random_designer.RandomDesigner(
                    p.search_space, seed=0
                ),
                self._monkey,
            ),
        )


def run_arm(
    *, trials: int, seed: int, fault_prob: float, reliability: ReliabilityConfig
) -> dict:
    monkey = chaos.ChaosMonkey(seed=seed, failure_prob=fault_prob)
    servicer = vizier_service.VizierServicer(reliability_config=reliability)
    pythia = pythia_service.PythiaServicer(
        servicer, _ChaosPolicyFactory(monkey), reliability_config=reliability
    )
    servicer.set_pythia(pythia)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/chaos",
            study=pc.study_to_proto(_study_config(), STUDY),
        )
    )
    client = vizier_client.VizierClient(
        chaos.ChaosServiceStub(servicer, monkey),
        STUDY,
        "chaos-worker",
        reliability=reliability,
    )

    # Per-suggest latency distribution via the observability histogram —
    # under injected faults the tail (retries, breaker cooldowns, fallback
    # detours) is the story a bare mean would bury.
    suggest_hist = MetricsRegistry().histogram(
        "chaos_suggest_latency_seconds", help="chaos_ab per-suggest wall time"
    )
    completed = fallback_trials = 0
    error = None
    start = time.perf_counter()
    try:
        for i in range(trials):
            t0 = time.perf_counter()
            (trial,) = client.get_suggestions(1)
            suggest_hist.observe(time.perf_counter() - t0)
            if is_fallback_suggestion(trial.metadata):
                fallback_trials += 1
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
            )
            completed += 1
    except Exception as e:  # the OFF arm is expected to land here
        error = f"{type(e).__name__}: {e}"
    elapsed = time.perf_counter() - start

    def _ms(q: float):
        value = suggest_hist.percentile(q)
        return round(value * 1000.0, 2) if value is not None else None

    stats = pythia.serving_stats()
    return {
        "completed_trials": completed,
        "target_trials": trials,
        "failed": error is not None,
        "error": error,
        "fallback_trials": fallback_trials,
        "fallback_rate": fallback_trials / max(1, completed),
        "elapsed_secs": round(elapsed, 3),
        "suggest_latency_ms": {"p50": _ms(50), "p95": _ms(95), "p99": _ms(99)},
        "serving_stats": {k: v for k, v in sorted(stats.items()) if v},
        "injected": monkey.counts(),
    }


def run_distributed_arm(
    *,
    trials: int,
    seed: int,
    fault_prob: float,
    reliability: ReliabilityConfig,
    num_replicas: int,
    kill_at: int,
    delete_wal_dir: bool = False,
) -> dict:
    """Kill-one-replica failover under the same seeded fault schedule.

    With ``delete_wal_dir`` the dead replica's entire WAL directory is
    ``rm -rf``'d at the moment of the kill — the shared-nothing proof:
    the run must still complete every trial, with recovery sourced from
    the rendezvous successors' replication standby logs instead of the
    corpse's (now nonexistent) disk.
    """
    import shutil
    import tempfile

    from vizier_tpu.distributed import ReplicaManager

    monkey = chaos.ChaosMonkey(seed=seed, failure_prob=fault_prob)
    wal_root = tempfile.mkdtemp(prefix="vizier-chaos-wal-")
    manager = ReplicaManager(
        num_replicas,
        wal_root=wal_root,
        policy_factory=_ChaosPolicyFactory(monkey),
        reliability_config=reliability,
    )
    study_name = "owners/chaos/studies/dist-ab"
    manager.stub.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/chaos",
            study=pc.study_to_proto(_study_config(), study_name),
        )
    )
    # Transport faults injected BETWEEN the client and the router: they
    # exercise client retries without implicating any replica (the manager
    # verifies liveness before failing over).
    client = vizier_client.VizierClient(
        chaos.ChaosServiceStub(manager.stub, monkey),
        study_name,
        "chaos-worker",
        reliability=reliability,
    )
    owner_before = manager.router.replica_for(study_name)

    suggest_hist = MetricsRegistry().histogram(
        "chaos_suggest_latency_seconds", help="chaos_ab per-suggest wall time"
    )
    completed = fallback_trials = 0
    error = None
    killed = False
    trajectory = []  # per-trial suggested parameters (bit-identity checks)
    start = time.perf_counter()
    try:
        for i in range(trials):
            if i == kill_at:
                if delete_wal_dir:
                    # Drain the streamer, then vaporize the owner's disk
                    # BEFORE the kill: nothing local remains to fail over
                    # from — the standby logs must carry the recovery.
                    manager.flush_replication(owner_before)
                    shutil.rmtree(
                        os.path.join(wal_root, owner_before),
                        ignore_errors=True,
                    )
                manager.kill_replica(owner_before)
                killed = True
            t0 = time.perf_counter()
            (trial,) = client.get_suggestions(1)
            suggest_hist.observe(time.perf_counter() - t0)
            trajectory.append(
                tuple(
                    sorted(
                        (name, round(float(value), 12))
                        for name, value in trial.parameters.as_dict().items()
                    )
                )
            )
            if is_fallback_suggestion(trial.metadata):
                fallback_trials += 1
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
            )
            completed += 1
    except Exception as e:  # a failed failover lands here
        error = f"{type(e).__name__}: {e}"
    elapsed = time.perf_counter() - start

    def _ms(q: float):
        value = suggest_hist.percentile(q)
        return round(value * 1000.0, 2) if value is not None else None

    stats = manager.serving_stats()
    owner_after = manager.router.replica_for(study_name)
    manager.shutdown()
    import hashlib

    return {
        "trajectory_sha256": hashlib.sha256(
            repr(trajectory).encode("utf-8")
        ).hexdigest(),
        "_trajectory": trajectory,  # popped before JSON (identity checks)
        "completed_trials": completed,
        "target_trials": trials,
        "failed": error is not None,
        "error": error,
        "replicas": num_replicas,
        "wal_root": wal_root,
        "dead_wal_dir_deleted": bool(delete_wal_dir and killed),
        "killed_replica": owner_before if killed else None,
        "killed_at_trial": kill_at if killed else None,
        "owner_after_failover": owner_after,
        "failovers": stats["failovers"],
        "restored_studies": stats["restored_studies"],
        "recovery_sources": stats.get("recovery_sources", {}),
        "replication": stats.get("replication", {}),
        "router": stats["router"],
        "fallback_trials": fallback_trials,
        "fallback_rate": fallback_trials / max(1, completed),
        "elapsed_secs": round(elapsed, 3),
        "suggest_latency_ms": {"p50": _ms(50), "p95": _ms(95), "p99": _ms(99)},
        "serving_stats": {
            k: v
            for k, v in sorted(stats.items())
            if isinstance(v, int) and v
        },
        "injected": monkey.counts(),
    }


def run_replication_off_identity(
    *,
    trials: int,
    seed: int,
    fault_prob: float,
    reliability: ReliabilityConfig,
    num_replicas: int,
    kill_at: int,
) -> dict:
    """``VIZIER_DISTRIBUTED_REPLICATION=0`` must BE the legacy path.

    Runs the in-process kill-the-owner arm twice — replication on (the
    default) and off (the PR 12 local-disk failover) — on the same seeded
    schedule and asserts the suggestion trajectories are bit-identical:
    the replication plane is pure redundancy, invisible to what clients
    are served, and the off switch reproduces the legacy path exactly.
    """
    import unittest.mock

    arms = {}
    trajectories = {}
    for name, value in (("replication_on", "1"), ("replication_off", "0")):
        with unittest.mock.patch.dict(
            os.environ, {"VIZIER_DISTRIBUTED_REPLICATION": value}
        ):
            result = run_distributed_arm(
                trials=trials,
                seed=seed,
                fault_prob=fault_prob,
                reliability=reliability,
                num_replicas=num_replicas,
                kill_at=kill_at,
            )
        trajectories[name] = result.pop("_trajectory")
        arms[name] = {
            "completed_trials": result["completed_trials"],
            "failed": result["failed"],
            "recovery_sources": result["recovery_sources"],
            "replication_armed": bool(result["replication"]),
            "trajectory_sha256": result["trajectory_sha256"],
        }
    return {
        "arms": arms,
        "bit_identical": trajectories["replication_on"]
        == trajectories["replication_off"],
    }


def run_subprocess_partition_arm(
    *,
    trials: int,
    seed: int,
    num_replicas: int,
    kill_at: int,
    partition: bool,
    lease_timeout_s: float = 1.0,
    heartbeat_interval_s: float = 0.1,
) -> dict:
    """Kill-the-owner + partition-then-heal against REAL replica processes.

    The schedule: at ``kill_at`` the owning ``replica_main`` process is
    SIGKILLed (lease expiry / the routed stub's dead-process check detects
    it; failover replays from standby logs collected over gRPC); with
    ``partition`` armed, at ``kill_at + (trials - kill_at) // 3`` the NEXT
    owner is partitioned away from the driver (netchaos severs heartbeats
    and client RPCs; the process keeps running), the lease expires, the
    manager fences the zombie's epoch everywhere reachable and fails its
    studies over; the window heals two-thirds in, and one stale append is
    driven directly at the zombie — its delivery must be REJECTED by the
    fenced standby stores (counted via heartbeat) and must NOT surface in
    the routed tier's final listing (no split-brain write wins).
    """
    import tempfile

    from vizier_tpu.distributed import subprocess_fleet
    from vizier_tpu.service import grpc_stubs
    from vizier_tpu.service.protos import (
        replication_service_pb2 as rpb,
        study_pb2,
    )
    from vizier_tpu.testing import netchaos as netchaos_lib

    wal_root = tempfile.mkdtemp(prefix="vizier-chaos-subproc-")
    net = netchaos_lib.NetChaos(seed=seed)
    fleet = subprocess_fleet.SubprocessReplicaManager(
        num_replicas,
        wal_root=wal_root,
        netchaos=net,
        lease_timeout_s=lease_timeout_s,
        heartbeat_interval_s=heartbeat_interval_s,
    )
    study_name = "owners/chaos/studies/subproc-ab"
    partition_at = kill_at + max(1, (trials - kill_at) // 3)
    heal_at = kill_at + max(2, 2 * (trials - kill_at) // 3)
    # Client-side reliability must ride out a full lease expiry plus the
    # failover replay before its attempts run dry.
    reliability = ReliabilityConfig(
        retry_max_attempts=16,
        retry_base_delay_secs=0.1,
        retry_max_delay_secs=0.5,
        breaker_window_secs=0.5,
        breaker_cooldown_secs=0.2,
    )
    owners: list = []
    partitioned_replica = None
    stale_trial_id = 10_000 + trials
    error = None
    completed = 0
    fenced_rejections = 0
    suggest_hist = MetricsRegistry().histogram(
        "chaos_suggest_latency_seconds", help="chaos_ab per-suggest wall time"
    )
    start = time.perf_counter()
    try:
        fleet.stub.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(
                parent="owners/chaos",
                study=pc.study_to_proto(_study_config(), study_name),
            )
        )
        client = vizier_client.VizierClient(
            fleet.stub, study_name, "chaos-worker", reliability=reliability
        )
        owners.append(fleet.owner_of(study_name))
        for i in range(trials):
            if i == kill_at:
                fleet.kill_replica(owners[-1])
            if partition and i == partition_at:
                owner_now = fleet.owner_of(study_name)
                # Pin the partition to an acked-replication boundary (the
                # client is sequential, so this is exact): replication is
                # asynchronous, and the partition must test FENCING, not
                # whether an arbitrary in-flight batch won a race.
                fleet._control.call_once(
                    owner_now,
                    "FlushStream",
                    rpb.FlushStreamRequest(timeout_secs=5.0),
                )
                fleet.partition_replica(owner_now)
                partitioned_replica = owner_now
            if partition and i == heal_at and partitioned_replica is not None:
                fleet.heal_partition(partitioned_replica)
                # The zombie still serves its (stale) study copy: a
                # client with stale routing writes one trial directly at
                # it. The append lands in the zombie's local WAL, its
                # streamer delivers — and every fenced standby store
                # rejects the dead generation.
                zombie_stub = grpc_stubs.create_vizier_stub(
                    fleet.endpoint_of(partitioned_replica)
                )
                zombie_stub.CreateTrial(
                    vizier_service_pb2.CreateTrialRequest(
                        parent=study_name,
                        trial=study_pb2.Trial(
                            name=f"{study_name}/trials/{stale_trial_id}"
                        ),
                    )
                )
            t0 = time.perf_counter()
            (trial,) = client.get_suggestions(1)
            suggest_hist.observe(time.perf_counter() - t0)
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
            )
            completed += 1
            current = fleet.owner_of(study_name)
            if current != owners[-1]:
                owners.append(current)
        # The fenced rejection is observed via heartbeat from whichever
        # live replica the zombie's delivery reached; give the zombie's
        # streamer a bounded window to drain and be fenced.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            fleet.check_health()
            fenced_rejections = fleet.serving_stats()["replication"][
                "fenced_rejections"
            ]
            if not partition or fenced_rejections >= 1:
                break
            time.sleep(0.25)
        listed = client.list_trials()
        listed_ids = sorted(t.id for t in listed)
    except Exception as e:
        error = f"{type(e).__name__}: {e}"
        listed, listed_ids = [], []
    elapsed = time.perf_counter() - start
    stats = fleet.serving_stats()
    fleet.shutdown()

    def _ms(q: float):
        value = suggest_hist.percentile(q)
        return round(value * 1000.0, 2) if value is not None else None

    return {
        "completed_trials": completed,
        "target_trials": trials,
        "failed": error is not None,
        "error": error,
        "replicas": num_replicas,
        "replica_mode": "subprocess",
        "wal_root": wal_root,
        "owner_chain": owners,
        "killed_replica": owners[0] if owners else None,
        "killed_at_trial": kill_at,
        "partitioned_replica": partitioned_replica,
        "partitioned_at_trial": partition_at if partition else None,
        "healed_at_trial": heal_at if partition else None,
        "lease_timeout_s": lease_timeout_s,
        "heartbeat_interval_s": heartbeat_interval_s,
        "failovers": stats["failovers"],
        "restored_studies": stats["restored_studies"],
        "recovery_sources": stats["recovery_sources"],
        "fenced_rejections": fenced_rejections,
        "stale_append_rejected": bool(partition)
        and stale_trial_id not in listed_ids,
        "listed_trials": len(listed),
        "zero_lost": len(listed) == completed
        and stale_trial_id not in listed_ids,
        "router": stats["router"],
        "leases": stats["leases"],
        "netchaos": net.counts(),
        "elapsed_secs": round(elapsed, 3),
        "suggest_latency_ms": {"p50": _ms(50), "p95": _ms(95), "p99": _ms(99)},
    }


def run_mesh_executor_arm(
    *,
    devices: int,
    seed: int,
    fault_prob: float,
    rounds: int = 4,
    buckets: int = 2,
    studies_per_bucket: int = 2,
) -> dict:
    """Chaos soak on the mesh-sharded batch executor itself.

    Chaos-wrapped UCB-PE designers across ``buckets`` distinct shape
    buckets (sticky-assigned to different placements) run concurrent
    suggest rounds through a mesh executor while the seeded monkey strikes
    the batch hooks. A ``device_program`` strike poisons one placement's
    flush — the executor must degrade only that flush (per-slot sequential
    fallback; a re-struck fallback surfaces as that slot's own designer
    error) while other placements' flushes keep completing. After the
    soak, a fault-free designer must still be served (no dead workers, no
    poisoned queues).
    """
    import threading

    import numpy as np

    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.designers import gp_ucb_pe
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib
    from vizier_tpu.testing import failing
    from vizier_tpu.parallel.batch_executor import BatchExecutor
    from vizier_tpu.parallel.mesh import MeshConfig
    from vizier_tpu.serving.stats import ServingStats

    def problem(dim=2):
        p = vz.ProblemStatement()
        for d in range(dim):
            p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(
                name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return p

    def designer(bucket_index: int, study_seed: int):
        d = gp_ucb_pe.VizierGPUCBPEBandit(
            problem(),
            rng_seed=study_seed,
            # Distinct acquisition budgets -> distinct jit statics ->
            # distinct buckets (mirrors tools/batching_ab.py --devices).
            max_acquisition_evaluations=200 + 8 * bucket_index,
            ard_restarts=2,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=10),
            warm_start_min_trials=0,
        )
        rng = np.random.default_rng(study_seed)
        trials = []
        for i in range(5):
            t = vz.Trial(
                parameters={
                    "x0": float(rng.uniform()),
                    "x1": float(rng.uniform()),
                },
                id=i + 1,
            )
            t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        return d

    monkey = chaos.ChaosMonkey(seed=seed, failure_prob=fault_prob)
    stats = ServingStats()
    executor = BatchExecutor(
        max_batch_size=8,
        max_wait_ms=30.0,
        stats=stats,
        metrics=stats.registry,
        mesh=MeshConfig(enabled=True, num_devices=devices),
    )
    pool = [
        chaos.ChaosDesigner(designer(b, b * 100 + c + 1), monkey)
        for b in range(buckets)
        for c in range(studies_per_bucket)
    ]

    completed = injected = 0
    count_lock = threading.Lock()

    def client(d):
        nonlocal completed, injected
        for _ in range(rounds):
            try:
                out = executor.suggest(d, 1)
                assert out, "empty suggestion batch"
                with count_lock:
                    completed += 1
            except failing.FailedSuggestError:
                with count_lock:
                    injected += 1

    threads = [threading.Thread(target=client, args=(d,)) for d in pool]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    # Post-soak liveness: a fault-free designer must still be served by the
    # same (possibly previously poisoned) placements.
    clean = executor.suggest(designer(0, 999), 1)
    placement_flushes = executor.placement_flush_counts()
    executor.close()

    snap = stats.snapshot()
    attempts = len(pool) * rounds
    return {
        "devices": devices,
        "buckets": buckets,
        "studies_per_bucket": studies_per_bucket,
        "rounds": rounds,
        "attempts": attempts,
        "completed": completed,
        "isolated_designer_errors": injected,
        "all_accounted": completed + injected == attempts,
        "post_soak_liveness": bool(clean),
        "batch_fallbacks": snap.get("batch_fallbacks", 0),
        "batch_slot_errors": snap.get("batch_slot_errors", 0),
        "mesh_flushes": snap.get("mesh_flushes", 0),
        "placement_flushes": placement_flushes,
        "elapsed_secs": round(elapsed, 3),
        "injected": monkey.counts(),
    }


class _SlowSuggestDesigner:
    """Wraps a designer so every ``slow_every``-th suggest stalls — the
    induced latency regression the SLO soak must catch as a p99 breach.
    ``tick`` is a shared per-study counter held by the factory: policies
    are rebuilt per request, so the cadence must outlive the instance."""

    def __init__(self, designer, tick, slow_every: int, delay_secs: float):
        self._designer = designer
        self._tick = tick
        self._slow_every = max(1, slow_every)
        self._delay_secs = delay_secs

    def __getattr__(self, name):
        return getattr(self._designer, name)

    def suggest(self, count=None):
        if self._tick() % self._slow_every == 0:
            time.sleep(self._delay_secs)
        return self._designer.suggest(count)


class _SlowChaosPolicyFactory:
    def __init__(self, monkey: chaos.ChaosMonkey, slow_every: int, delay_secs: float):
        import threading

        self._monkey = monkey
        self._slow_every = slow_every
        self._delay_secs = delay_secs
        self._lock = threading.Lock()
        self._counts: dict = {}

    def _tick(self, study_name: str) -> int:
        with self._lock:
            self._counts[study_name] = self._counts.get(study_name, 0) + 1
            return self._counts[study_name]

    def __call__(self, problem, algorithm, supporter, study_name):
        return designer_policy.DesignerPolicy(
            supporter,
            chaos.chaos_designer_factory(
                lambda p, **kw: _SlowSuggestDesigner(
                    random_designer.RandomDesigner(p.search_space, seed=0),
                    tick=lambda: self._tick(study_name),
                    slow_every=self._slow_every,
                    delay_secs=self._delay_secs,
                ),
                self._monkey,
            ),
        )


def run_slo_soak_arm(
    *,
    trials: int,
    seed: int,
    fault_prob: float,
    reliability: ReliabilityConfig,
    num_replicas: int,
    kill_at: int,
    out_dir: str,
    p99_threshold_ms: float = 25.0,
    slow_every: int = 5,
    delay_secs: float = 0.12,
) -> dict:
    """SLOs armed + flight recorder on, over a 2-study / N-replica tier.

    Induces a latency breach (every ``slow_every``-th suggest stalls
    ``delay_secs`` — far past the ``p99_threshold_ms`` objective), kills
    the first study's owning replica mid-run, then checks the whole
    observability plane end to end: the breach produced a black-box dump
    whose exemplar trace_ids resolve to complete traces in the merged
    per-replica span dumps, and the fleet merge stitches cross-source
    traces plus the failover timeline from the recorder events.
    """
    import tempfile

    from vizier_tpu.distributed import ReplicaManager
    from vizier_tpu.observability import fleet as fleet_lib
    from vizier_tpu.observability import flight_recorder as recorder_lib
    from vizier_tpu.observability import tracing as tracing_lib

    import unittest.mock

    os.makedirs(out_dir, exist_ok=True)
    env_overrides = {
        "VIZIER_SLO": "1",
        # Short fast window + a long one; manual evaluation cadence keeps
        # the soak deterministic on loaded CI machines.
        "VIZIER_SLO_WINDOWS": "10,120",
        "VIZIER_SLO_EVAL_INTERVAL_S": "0",
        "VIZIER_SLO_SUGGEST_P99_MS": str(p99_threshold_ms),
        "VIZIER_SLO_DUMP_DIR": out_dir,
        "VIZIER_FLIGHT_RECORDER": "1",
    }
    # patch.dict restores the environment on exit (no hand-rolled
    # save/restore — environ reads stay literal for the env_registry pass).
    env_patch = unittest.mock.patch.dict(os.environ, env_overrides)
    env_patch.start()
    # Fresh global tracer + recorder so the soak's artifacts are self-
    # contained (and the recorder re-derives as ENABLED from the env).
    prev_tracer = tracing_lib.set_tracer(tracing_lib.Tracer(max_spans=65536))
    prev_recorder = recorder_lib.set_recorder(None)
    manager = None
    try:
        monkey = chaos.ChaosMonkey(seed=seed, failure_prob=fault_prob)
        wal_root = tempfile.mkdtemp(prefix="vizier-slo-wal-")
        manager = ReplicaManager(
            num_replicas,
            wal_root=wal_root,
            policy_factory=_SlowChaosPolicyFactory(monkey, slow_every, delay_secs),
            reliability_config=reliability,
        )
        runtime = manager.pythia.serving_runtime
        assert runtime.slo_engine is not None, "SLO engine failed to arm"

        # Two studies owned by two DIFFERENT replicas, so the merged span
        # dump covers >= 2 replica sources.
        studies = []
        owners = set()
        i = 0
        while len(studies) < 2 and i < 1000:
            name = f"owners/chaos/studies/slo-{i}"
            i += 1
            owner = manager.router.replica_for(name)
            if owner not in owners:
                owners.add(owner)
                studies.append((name, owner))
        clients = {}
        for study_name, _owner in studies:
            manager.stub.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/chaos",
                    study=pc.study_to_proto(_study_config(), study_name),
                )
            )
            clients[study_name] = vizier_client.VizierClient(
                chaos.ChaosServiceStub(manager.stub, monkey),
                study_name,
                "chaos-worker",
                reliability=reliability,
            )

        killed_replica = studies[0][1]
        completed = 0
        start = time.perf_counter()
        for t in range(trials):
            if t == kill_at:
                manager.kill_replica(killed_replica)
            study_name, _ = studies[t % len(studies)]
            client = clients[study_name]
            (trial,) = client.get_suggestions(1)
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.01 * t})
            )
            completed += 1
            if (t + 1) % 10 == 0:
                runtime.slo_engine.evaluate()
        elapsed = time.perf_counter() - start
        slo_report = runtime.slo_report()

        # Fleet dump: per-replica span files split from the shared ring,
        # plus the registry snapshot and recorder events.
        manager.dump_observability(out_dir)
        fleet_report = fleet_lib.fleet_report(out_dir)
        merged = fleet_lib.merge_spans(fleet_lib.load_fleet_dir(out_dir)["spans"])
        by_trace = {}
        for span in merged:
            by_trace.setdefault(span.get("trace_id"), []).append(span)

        # The black box must point at real, complete traces: every
        # exemplar trace_id resolves in the merged span dump with a root
        # span and a service-side span.
        dumps = list(runtime.slo_engine.dumps)
        exemplar_trace_ids = []
        exemplars_resolve = False
        if dumps:
            with open(dumps[0]) as f:
                blackbox = json.load(f)
            exemplar_trace_ids = sorted(blackbox.get("exemplar_traces", {}))
            def _complete(trace_id):
                spans = by_trace.get(trace_id, [])
                names = {s.get("name") for s in spans}
                has_root = any(s.get("parent_id") is None for s in spans)
                return (
                    len(spans) >= 3
                    and has_root
                    and "service.suggest_trials" in names
                )
            exemplars_resolve = bool(exemplar_trace_ids) and all(
                _complete(tid) for tid in exemplar_trace_ids
            )

        timeline = fleet_report["failover_timeline"]
        breached = set(slo_report["breaching"])
        span_sources = set(fleet_report["sources"])
        replica_sources = {s for s in span_sources if s.startswith("replica-")}
        return {
            "trials": trials,
            "completed_trials": completed,
            "elapsed_secs": round(elapsed, 3),
            "studies": [
                {"study": name, "owner": owner} for name, owner in studies
            ],
            "killed_replica": killed_replica,
            "killed_at_trial": kill_at,
            "p99_threshold_ms": p99_threshold_ms,
            "induced_delay_ms": delay_secs * 1e3,
            "slo": slo_report,
            "slo_breached": sorted(breached),
            "p99_breached": any(b.startswith("suggest_p99") for b in breached),
            "blackbox_dumps": dumps,
            "exemplar_trace_ids": exemplar_trace_ids,
            "exemplars_resolve_to_complete_traces": exemplars_resolve,
            "fleet": fleet_report,
            "fleet_replica_sources": sorted(replica_sources),
            "cross_replica_traces": fleet_report["cross_replica_traces"],
            "failover_timeline_kinds": sorted(
                {e["kind"] for e in timeline}
            ),
            "serving_stats": {
                k: v
                for k, v in sorted(manager.serving_stats().items())
                if isinstance(v, int) and v
            },
            "injected": monkey.counts(),
            "out_dir": out_dir,
        }
    finally:
        if manager is not None:
            manager.shutdown()
        tracing_lib.set_tracer(prev_tracer)
        recorder_lib.set_recorder(prev_recorder)
        env_patch.stop()


def write_observability_e2e(arm: dict, out_path: str) -> dict:
    """OBSERVABILITY_E2E.json v2: the SLO-armed soak as evidence."""
    fleet = arm["fleet"]
    evidence = {
        "version": 2,
        "what": (
            "PR 11 acceptance: SLO-armed chaos soak over a 2-replica tier "
            "with an induced p99 breach -> black-box dump whose exemplar "
            "trace_ids resolve to complete traces in the merged per-replica "
            "span dumps; fleet merge stitches cross-replica traces and the "
            "failover timeline from flight-recorder events"
        ),
        "slo": {
            "config": arm["slo"]["config"],
            "breached": arm["slo_breached"],
            "p99_breached": arm["p99_breached"],
            "statuses": arm["slo"]["statuses"],
            "blackbox_dump": arm["blackbox_dumps"][:1],
            "exemplar_trace_ids": arm["exemplar_trace_ids"],
            "exemplars_resolve_to_complete_traces": arm[
                "exemplars_resolve_to_complete_traces"
            ],
        },
        "fleet": {
            "sources": fleet["sources"],
            "spans": fleet["spans"],
            "traces": fleet["traces"],
            "cross_replica_traces": fleet["cross_replica_traces"],
            "cross_replica_examples": fleet["cross_replica_examples"][:3],
            "failover_timeline": fleet["failover_timeline"],
        },
        "soak": {
            "trials": arm["trials"],
            "completed_trials": arm["completed_trials"],
            "killed_replica": arm["killed_replica"],
            "killed_at_trial": arm["killed_at_trial"],
            "p99_threshold_ms": arm["p99_threshold_ms"],
            "induced_delay_ms": arm["induced_delay_ms"],
            "serving_stats": arm["serving_stats"],
            "injected": arm["injected"],
        },
    }
    pathlib.Path(out_path).write_text(json.dumps(evidence, indent=2) + "\n")
    return evidence


def _cross_check_locks(observatory, out: dict) -> bool:
    """Diffs the soak's observed lock order against the static graph."""
    from vizier_tpu.analysis import debug_locks, suite

    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    static = suite.run_suite(repo_root, passes=["lock_order"]).lock_result
    check = debug_locks.check_against_static(observatory, static, repo_root)
    out["lock_check"] = {
        "acquisitions": observatory.acquisitions,
        "confirmed_edges": sorted(set(check.confirmed)),
        "missing_from_static_graph": [
            {"src": src, "dst": dst, "thread": edge.thread}
            for src, dst, edge in check.missing_static
        ],
        "unmapped_sites": [s.short() for s in check.unmapped_sites],
    }
    return not check.missing_static


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--trials", type=int, default=50)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--fault-prob", type=float, default=0.1)
    parser.add_argument(
        "--distributed",
        type=int,
        default=0,
        metavar="N",
        help="add the N-replica kill-one-replica failover arm (0 = skip)",
    )
    parser.add_argument(
        "--kill-at",
        type=int,
        default=-1,
        help="trial index at which the owning replica dies (-1 = halfway)",
    )
    parser.add_argument(
        "--no-shared-fs",
        action="store_true",
        help="with --distributed: add the replicated_failover arm — the "
        "dead replica's WAL directory is DELETED at the kill, so the "
        "run can only complete via the successors' replication standby "
        "logs (the shared-nothing durability proof)",
    )
    parser.add_argument(
        "--replica-mode",
        choices=("inprocess", "subprocess"),
        default="inprocess",
        help="with --distributed: 'subprocess' adds the "
        "subprocess_partition arm (real replica_main processes, "
        "lease-based failure detection, cross-process standby "
        "replication) plus the replication-off bit-identity check",
    )
    parser.add_argument(
        "--partition",
        action="store_true",
        help="with --replica-mode subprocess: add a partition-then-heal "
        "window (netchaos) on the post-failover owner, and assert the "
        "healed zombie's stale append is fenced out",
    )
    parser.add_argument(
        "--mesh-devices",
        type=int,
        default=0,
        metavar="N",
        help="add the mesh-executor chaos arm on N simulated devices "
        "(0 = skip); composes with --instrument-locks so the per-placement "
        "dispatch workers enter the runtime lock-order cross-check",
    )
    parser.add_argument(
        "--instrument-locks",
        action="store_true",
        help="record runtime lock order during the soak and fail on edges "
        "the static lock_order graph does not predict",
    )
    parser.add_argument(
        "--slo-soak",
        action="store_true",
        help="add the SLO-armed observability arm: 2-replica tier, induced "
        "p99 breach -> black-box dump + fleet-merged cross-replica traces; "
        "regenerates OBSERVABILITY_E2E.json (v2)",
    )
    parser.add_argument(
        "--slo-replicas",
        type=int,
        default=2,
        help="replica count for the --slo-soak arm",
    )
    parser.add_argument(
        "--obs-dump-dir",
        default="",
        help="dump directory for the --slo-soak arm's span/metric/recorder "
        "+ black-box files (default: a fresh temp dir)",
    )
    parser.add_argument(
        "--obs-e2e-out",
        default=str(
            pathlib.Path(__file__).resolve().parent.parent
            / "OBSERVABILITY_E2E.json"
        ),
        help="where --slo-soak writes the v2 evidence JSON",
    )
    parser.add_argument(
        "--out",
        default=str(pathlib.Path(__file__).resolve().parent.parent / "CHAOS_AB.json"),
    )
    args = parser.parse_args()

    # Fast client backoffs: the A/B measures completion/fallback behavior,
    # not wall-clock sleeps.
    vizier_client.environment_variables.polling_delay_secs = 0.005

    arms = {
        "reliability_on": ReliabilityConfig(
            retry_base_delay_secs=0.01,
            retry_max_delay_secs=0.1,
            # The breaker's sliding window assumes production suggest rates
            # (designer runs are seconds apart); at test speed 50 suggests
            # land inside one 60 s window, so the window is compressed to
            # keep "N failures within a window" meaning the same thing.
            breaker_window_secs=0.5,
            breaker_cooldown_secs=0.2,
        ),
        "reliability_off": ReliabilityConfig.disabled(),
    }
    report = {
        "config": {
            "trials": args.trials,
            "seed": args.seed,
            "designer_fault_prob": args.fault_prob,
            "transport_fault_prob": args.fault_prob,
            "algorithm": "RANDOM_SEARCH (chaos-wrapped designer)",
            "observability": ObservabilityConfig.from_env().as_dict(),
            "instrument_locks": bool(args.instrument_locks),
            "mesh_devices": args.mesh_devices,
        },
        "arms": {},
    }
    if args.instrument_locks:
        from vizier_tpu.analysis import debug_locks

        instrumentation = debug_locks.instrument()
    else:
        import contextlib

        instrumentation = contextlib.nullcontext(None)

    kill_at = args.kill_at if args.kill_at >= 0 else args.trials // 2
    with instrumentation as observatory:
        for name, reliability in arms.items():
            print(f"[chaos_ab] running arm: {name}")
            report["arms"][name] = run_arm(
                trials=args.trials,
                seed=args.seed,
                fault_prob=args.fault_prob,
                reliability=reliability,
            )
        if args.distributed:
            print(
                f"[chaos_ab] running arm: distributed_failover "
                f"({args.distributed} replicas, kill at trial {kill_at})"
            )
            report["arms"]["distributed_failover"] = run_distributed_arm(
                trials=args.trials,
                seed=args.seed,
                fault_prob=args.fault_prob,
                reliability=arms["reliability_on"],
                num_replicas=args.distributed,
                kill_at=kill_at,
            )
            report["arms"]["distributed_failover"].pop("_trajectory", None)
            if args.no_shared_fs:
                print(
                    "[chaos_ab] running arm: replicated_failover "
                    f"({args.distributed} replicas, dead WAL dir DELETED "
                    f"at trial {kill_at})"
                )
                report["arms"]["replicated_failover"] = run_distributed_arm(
                    trials=args.trials,
                    seed=args.seed,
                    fault_prob=args.fault_prob,
                    reliability=arms["reliability_on"],
                    num_replicas=args.distributed,
                    kill_at=kill_at,
                    delete_wal_dir=True,
                )
                report["arms"]["replicated_failover"].pop("_trajectory", None)
            if args.replica_mode == "subprocess":
                print(
                    "[chaos_ab] running check: replication_off_identity "
                    f"({args.distributed} replicas, in-process, "
                    "VIZIER_DISTRIBUTED_REPLICATION=0 vs 1)"
                )
                report["replication_off_identity"] = (
                    run_replication_off_identity(
                        trials=args.trials,
                        seed=args.seed,
                        fault_prob=args.fault_prob,
                        reliability=arms["reliability_on"],
                        num_replicas=args.distributed,
                        kill_at=kill_at,
                    )
                )
                print(
                    f"[chaos_ab] running arm: subprocess_partition "
                    f"({args.distributed} replica_main processes, kill at "
                    f"trial {kill_at}, partition={args.partition})"
                )
                report["arms"]["subprocess_partition"] = (
                    run_subprocess_partition_arm(
                        trials=args.trials,
                        seed=args.seed,
                        num_replicas=args.distributed,
                        kill_at=kill_at,
                        partition=args.partition,
                    )
                )
        if args.mesh_devices:
            print(
                f"[chaos_ab] running arm: mesh_executor "
                f"({args.mesh_devices} devices)"
            )
            report["arms"]["mesh_executor"] = run_mesh_executor_arm(
                devices=args.mesh_devices,
                seed=args.seed,
                fault_prob=args.fault_prob,
            )
        if args.slo_soak:
            import tempfile

            out_dir = args.obs_dump_dir or tempfile.mkdtemp(
                prefix="vizier-obs-dump-"
            )
            print(
                f"[chaos_ab] running arm: slo_soak "
                f"({args.slo_replicas} replicas, dumps -> {out_dir})"
            )
            report["arms"]["slo_soak"] = run_slo_soak_arm(
                trials=args.trials,
                seed=args.seed,
                fault_prob=args.fault_prob,
                reliability=arms["reliability_on"],
                num_replicas=args.slo_replicas,
                kill_at=kill_at,
                out_dir=out_dir,
            )

    on, off = report["arms"]["reliability_on"], report["arms"]["reliability_off"]
    report["verdict"] = {
        "on_completed_all": on["completed_trials"] == args.trials,
        "on_fallback_rate": round(on["fallback_rate"], 4),
        "off_failed": off["failed"],
        "off_completed": off["completed_trials"],
    }
    ok = True
    if args.distributed:
        dist = report["arms"]["distributed_failover"]
        report["verdict"].update(
            {
                "distributed_completed_all": dist["completed_trials"]
                == args.trials,
                "distributed_failovers": dist["failovers"],
                "distributed_killed_replica": dist["killed_replica"],
            }
        )
        ok = ok and dist["completed_trials"] == args.trials and dist["failovers"] >= 1
        if args.no_shared_fs:
            repl = report["arms"]["replicated_failover"]
            standby_recoveries = int(
                repl["recovery_sources"].get("standby", 0)
            )
            report["verdict"].update(
                {
                    "replicated_completed_all": repl["completed_trials"]
                    == args.trials,
                    "replicated_wal_dir_deleted": repl[
                        "dead_wal_dir_deleted"
                    ],
                    "replicated_standby_recoveries": standby_recoveries,
                }
            )
            ok = ok and (
                repl["completed_trials"] == args.trials
                and repl["dead_wal_dir_deleted"]
                and standby_recoveries >= 1
            )
        if args.replica_mode == "subprocess":
            identity = report["replication_off_identity"]
            sub = report["arms"]["subprocess_partition"]
            subprocess_standby = int(
                sub["recovery_sources"].get("standby", 0)
            )
            report["verdict"].update(
                {
                    "subprocess_completed_all": sub["completed_trials"]
                    == args.trials,
                    "subprocess_zero_lost": sub["zero_lost"],
                    "subprocess_standby_recoveries": subprocess_standby,
                    "subprocess_fenced_rejections": sub[
                        "fenced_rejections"
                    ],
                    "subprocess_stale_append_rejected": sub[
                        "stale_append_rejected"
                    ],
                    "replication_off_bit_identical": identity[
                        "bit_identical"
                    ],
                }
            )
            ok = ok and (
                sub["completed_trials"] == args.trials
                and sub["zero_lost"]
                and subprocess_standby >= 1
                and identity["bit_identical"]
            )
            if args.partition:
                ok = ok and (
                    sub["fenced_rejections"] >= 1
                    and sub["stale_append_rejected"]
                )
    if args.mesh_devices:
        mesh_arm = report["arms"]["mesh_executor"]
        report["verdict"].update(
            {
                "mesh_all_accounted": mesh_arm["all_accounted"],
                "mesh_post_soak_liveness": mesh_arm["post_soak_liveness"],
                "mesh_isolated_errors": mesh_arm["isolated_designer_errors"],
            }
        )
        ok = ok and mesh_arm["all_accounted"] and mesh_arm["post_soak_liveness"]
    if args.slo_soak:
        slo_arm = report["arms"]["slo_soak"]
        report["verdict"].update(
            {
                "slo_completed_all": slo_arm["completed_trials"]
                == args.trials,
                "slo_p99_breached": slo_arm["p99_breached"],
                "slo_blackbox_dumped": bool(slo_arm["blackbox_dumps"]),
                "slo_exemplars_resolve": slo_arm[
                    "exemplars_resolve_to_complete_traces"
                ],
                "fleet_replica_sources": len(
                    slo_arm["fleet_replica_sources"]
                ),
                "fleet_cross_replica_traces": slo_arm["cross_replica_traces"],
                "fleet_failover_in_timeline": "replica_failover"
                in slo_arm["failover_timeline_kinds"],
            }
        )
        ok = ok and (
            slo_arm["completed_trials"] == args.trials
            and slo_arm["p99_breached"]
            and bool(slo_arm["blackbox_dumps"])
            and slo_arm["exemplars_resolve_to_complete_traces"]
            and len(slo_arm["fleet_replica_sources"]) >= 2
            and slo_arm["cross_replica_traces"] >= 1
            and "replica_failover" in slo_arm["failover_timeline_kinds"]
        )
        write_observability_e2e(slo_arm, args.obs_e2e_out)
        print(f"[chaos_ab] wrote {args.obs_e2e_out}")
    if args.instrument_locks:
        locks_ok = _cross_check_locks(observatory, report)
        report["verdict"]["lock_order_confirmed"] = locks_ok
        ok = ok and locks_ok
    pathlib.Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["verdict"], indent=2))
    print(f"[chaos_ab] wrote {args.out}")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
