"""Core JAX types: padded arrays and model input/output containers.

TPU-first equivalents of the reference's
``/root/reference/vizier/_src/jax/types.py:40,165,176,189``. ``PaddedArray``
is the recompile-avoidance mechanism: trial counts and feature dims are
padded to quantized shapes (see ``converters.padding``) with per-axis boolean
validity masks, so XLA sees a small set of static shapes while the *actual*
counts stay traced values. Every downstream kernel must thread the masks —
fill values leak into Cholesky factors and acquisitions otherwise.

All containers are registered pytrees (``flax.struct``) so they pass through
``jit``/``vmap``/``shard_map`` and can carry ``NamedSharding`` annotations:
the canonical mesh axes are ``('trials', 'features', 'ensemble')``.
"""

from __future__ import annotations

from typing import Any, Generic, Optional, Tuple, TypeVar, Union

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
ArrayLike = Union[jax.Array, np.ndarray]

_T = TypeVar("_T")


@flax.struct.dataclass
class PaddedArray:
    """A fixed-shape array whose trailing rows/cols are padding.

    ``padded_array`` has the quantized (static) shape. ``is_missing`` holds
    one boolean mask per axis (shape ``[padded_array.shape[i]]``), True where
    that index is padding. ``fill_value`` is what padding positions hold.

    The *unpadded* extent of each axis is a traced value
    (``true_shape``), so growing from 7 to 8 trials inside one padding
    bucket does not retrace.
    """

    padded_array: Array
    is_missing: Tuple[Array, ...]
    fill_value: Any = flax.struct.field(pytree_node=False, default=0.0)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_array(
        cls,
        array: ArrayLike,
        target_shape: Optional[Tuple[int, ...]] = None,
        *,
        fill_value: Any = 0.0,
    ) -> "PaddedArray":
        """Pads ``array`` up to ``target_shape`` (defaults to its own shape).

        Host (numpy) inputs are padded in numpy so only the stable padded
        shape ever reaches the device: a ``jnp.pad`` here would compile one
        program per *unpadded* length (every new trial count) and ship a
        new-shape buffer across the interconnect each suggest — measured at
        ~0.5 s/array through a tunneled TPU vs ~0.1 ms for the warm
        fixed-shape path.
        """
        on_host = not isinstance(array, jax.Array)
        xp = np if on_host else jnp
        array = xp.asarray(array)
        if on_host and not jax.config.jax_enable_x64:
            # Mirror jax's x64-disabled canonicalization: a float64/int64
            # host buffer would otherwise key a second jit-cache entry per
            # dtype downstream (the exact retrace this host path avoids).
            canonical = {
                np.dtype(np.float64): np.float32,
                np.dtype(np.int64): np.int32,
                np.dtype(np.uint64): np.uint32,
                np.dtype(np.complex128): np.complex64,
            }.get(array.dtype)
            if canonical is not None:
                array = array.astype(canonical)
        if target_shape is None:
            target_shape = array.shape
        if len(target_shape) != array.ndim:
            raise ValueError(f"target_shape {target_shape} rank != array rank {array.ndim}.")
        for axis, (have, want) in enumerate(zip(array.shape, target_shape)):
            if have > want:
                raise ValueError(
                    f"Axis {axis}: array dim {have} exceeds target {want}; cannot pad down."
                )
        pad_width = [(0, want - have) for have, want in zip(array.shape, target_shape)]
        padded = xp.pad(array, pad_width, constant_values=fill_value)
        masks = tuple(
            xp.arange(want) >= have for have, want in zip(array.shape, target_shape)
        )
        return cls(padded_array=padded, is_missing=masks, fill_value=fill_value)

    @classmethod
    def as_padded(cls, array: ArrayLike, *, fill_value: Any = 0.0) -> "PaddedArray":
        """Wraps an array with no padding (all entries valid)."""
        return cls.from_array(array, fill_value=fill_value)

    # -- shape accessors ---------------------------------------------------

    @property
    def shape(self) -> Tuple[int, ...]:
        """The padded (static) shape."""
        return self.padded_array.shape

    @property
    def dtype(self):
        return self.padded_array.dtype

    @property
    def ndim(self) -> int:
        return self.padded_array.ndim

    def true_shape(self) -> Tuple[Array, ...]:
        """Unpadded extent per axis, as traced int32 scalars."""
        return tuple(jnp.sum(~m).astype(jnp.int32) for m in self.is_missing)

    def num_valid(self, axis: int = 0) -> Array:
        return jnp.sum(~self.is_missing[axis]).astype(jnp.int32)

    def valid_mask(self, axis: int = 0) -> Array:
        """True where the index along ``axis`` is real data."""
        return ~self.is_missing[axis]

    def joint_valid_mask(self) -> Array:
        """Full-rank boolean mask, True where every axis index is valid."""
        mask = None
        for axis, m in enumerate(self.is_missing):
            shape = [1] * self.ndim
            shape[axis] = self.shape[axis]
            part = (~m).reshape(shape)
            mask = part if mask is None else mask & part
        assert mask is not None
        return jnp.broadcast_to(mask, self.shape)

    # -- transforms --------------------------------------------------------

    def replace_fill_value(self, fill_value: Any) -> "PaddedArray":
        """Rewrites padding positions to a new fill value."""
        new = jnp.where(self.joint_valid_mask(), self.padded_array, fill_value)
        return PaddedArray(padded_array=new, is_missing=self.is_missing, fill_value=fill_value)

    def unpad(self) -> np.ndarray:
        """Strips padding; host-side only (shape depends on mask values)."""
        counts = [int(np.sum(~np.asarray(m))) for m in self.is_missing]
        out = np.asarray(self.padded_array)
        return out[tuple(slice(0, c) for c in counts)]

    def pad_to(self, target_shape: Tuple[int, ...]) -> "PaddedArray":
        """Re-pads to a larger static shape (host-side convenience)."""
        return PaddedArray.from_array(
            jnp.asarray(self.unpad()), target_shape, fill_value=self.fill_value
        )

    def __repr__(self) -> str:
        return (
            f"PaddedArray(shape={self.shape}, dtype={self.dtype}, "
            f"fill_value={self.fill_value!r})"
        )


@flax.struct.dataclass
class ContinuousAndCategorical(Generic[_T]):
    """A pair of containers, one for continuous and one for categorical data."""

    continuous: _T
    categorical: _T

    def map(self, fn) -> "ContinuousAndCategorical":
        return ContinuousAndCategorical(fn(self.continuous), fn(self.categorical))


# The GP feature container: continuous features are float [N, Dc] scaled to
# [0,1]; categorical features are integer category indices [N, Ds].
ModelInput = ContinuousAndCategorical[PaddedArray]


@flax.struct.dataclass
class ModelData:
    """Features + labels: the training set handed to stochastic-process models."""

    features: ModelInput
    labels: PaddedArray  # [N, num_metrics] float, NaN for infeasible.


def padded_zeros(
    continuous_shape: Tuple[int, int],
    categorical_shape: Tuple[int, int],
    *,
    dtype=jnp.float32,
) -> ModelInput:
    """An all-padding ModelInput (useful as a neutral element)."""
    cont = PaddedArray(
        padded_array=jnp.zeros(continuous_shape, dtype=dtype),
        is_missing=(
            jnp.ones(continuous_shape[0], dtype=bool),
            jnp.ones(continuous_shape[1], dtype=bool),
        ),
        fill_value=0.0,
    )
    cat = PaddedArray(
        padded_array=jnp.zeros(categorical_shape, dtype=jnp.int32),
        is_missing=(
            jnp.ones(categorical_shape[0], dtype=bool),
            jnp.ones(categorical_shape[1], dtype=bool),
        ),
        fill_value=0,
    )
    return ContinuousAndCategorical(continuous=cont, categorical=cat)
