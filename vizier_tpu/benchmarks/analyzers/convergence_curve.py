"""Convergence curves and designer comparators.

Parity with
``/root/reference/vizier/_src/benchmarks/analyzers/convergence_curve.py:35,714,837``:
best-so-far curves extracted from trials, interpolation/alignment across
repeats, and comparators (log-efficiency score, win rate) used by the
statistical convergence tests that gate every algorithm change.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class ConvergenceCurve:
    """ys[b, t]: best objective seen by batch b after t+1 trials."""

    xs: np.ndarray  # [T] trial counts (1-based)
    ys: np.ndarray  # [B, T]
    trend: "ConvergenceCurve.YTrend" = None  # type: ignore[assignment]

    class YTrend(enum.Enum):
        UNKNOWN = "UNKNOWN"
        INCREASING = "INCREASING"
        DECREASING = "DECREASING"

    def __post_init__(self):
        self.xs = np.asarray(self.xs)
        self.ys = np.atleast_2d(np.asarray(self.ys))
        if self.trend is None:
            self.trend = ConvergenceCurve.YTrend.UNKNOWN
        if self.ys.shape[-1] != len(self.xs):
            raise ValueError(f"ys {self.ys.shape} does not match xs {self.xs.shape}.")

    @property
    def num_batches(self) -> int:
        return self.ys.shape[0]

    @classmethod
    def align_xs(
        cls,
        curves: Sequence["ConvergenceCurve"],
        *,
        keep_curves_separate: bool = False,
    ) -> "ConvergenceCurve" | List["ConvergenceCurve"]:
        """Puts curves onto a common x grid (interpolating where needed).

        Default combines all batches into one stacked curve (reference
        ``_align_xs_combine_ys``); ``keep_curves_separate`` returns one
        aligned curve per input (``_align_xs_keep_ys``) — needed when the
        inputs are different algorithms that must not be pooled.
        """
        if not curves:
            raise ValueError("No curves to align.")
        trend = curves[0].trend
        if any(c.trend != trend for c in curves):
            raise ValueError("Cannot align curves with mismatched trends.")
        max_x = max(float(c.xs[-1]) for c in curves)
        xs = np.arange(1, int(max_x) + 1)
        if keep_curves_separate:
            return [
                cls(
                    xs=xs,
                    ys=np.stack([np.interp(xs, c.xs, row) for row in c.ys]),
                    trend=trend,
                )
                for c in curves
            ]
        ys = []
        for c in curves:
            for row in c.ys:
                ys.append(np.interp(xs, c.xs, row))
        return cls(xs=xs, ys=np.stack(ys), trend=trend)

    def interpolate_at(self, xs: np.ndarray) -> "ConvergenceCurve":
        """This curve resampled at arbitrary x positions."""
        xs = np.asarray(xs, dtype=np.float64)
        ys = np.stack([np.interp(xs, self.xs, row) for row in self.ys])
        return ConvergenceCurve(xs=xs, ys=ys, trend=self.trend)

    def extrapolate_ys(self, num_extra_steps: int) -> "ConvergenceCurve":
        """Extends each batch flat at its best-so-far value.

        Reference ``extrapolate_ys`` (``convergence_curve.py:198``): a
        best-so-far curve is a running extremum, so the honest extrapolation
        holds the incumbent — comparators can then align curves from runs of
        different lengths without fabricating progress.
        """
        if num_extra_steps <= 0:
            return self
        step = float(self.xs[-1] - self.xs[-2]) if len(self.xs) > 1 else 1.0
        extra_xs = self.xs[-1] + step * np.arange(1, num_extra_steps + 1)
        extra_ys = np.repeat(self.ys[:, -1:], num_extra_steps, axis=1)
        return ConvergenceCurve(
            xs=np.concatenate([self.xs, extra_xs]),
            ys=np.concatenate([self.ys, extra_ys], axis=1),
            trend=self.trend,
        )

    def percentile_curve(self, percentile: float = 50.0) -> np.ndarray:
        return np.percentile(self.ys, percentile, axis=0)


class ConvergenceCurveConverter:
    """Trials → best-so-far ConvergenceCurve for one objective metric."""

    def __init__(
        self,
        metric_information: base_study_config.MetricInformation,
        *,
        flip_signs_for_min: bool = False,
    ):
        self._metric = metric_information
        self._flip = flip_signs_for_min

    def convert(self, trials: Sequence[trial_.Trial]) -> ConvergenceCurve:
        goal = self._metric.goal
        values = []
        for t in trials:
            usable = (
                t.final_measurement
                and not t.infeasible  # same invariant as MetricsEncoder
                and self._metric.name in t.final_measurement.metrics
            )
            if usable:
                values.append(t.final_measurement.metrics[self._metric.name].value)
            else:
                values.append(np.nan)
        values = np.asarray(values, dtype=np.float64)
        if goal.is_maximize:
            with np.errstate(invalid="ignore"):
                ys = np.fmax.accumulate(np.where(np.isnan(values), -np.inf, values))
            trend = ConvergenceCurve.YTrend.INCREASING
        else:
            with np.errstate(invalid="ignore"):
                ys = np.fmin.accumulate(np.where(np.isnan(values), np.inf, values))
            trend = ConvergenceCurve.YTrend.DECREASING
        if self._flip and goal.is_minimize:
            ys = -ys
            trend = ConvergenceCurve.YTrend.INCREASING
        return ConvergenceCurve(
            xs=np.arange(1, len(values) + 1), ys=ys[None, :], trend=trend
        )


@dataclasses.dataclass
class LogEfficiencyConvergenceCurveComparator:
    """Sample-efficiency score of ``compared`` vs ``baseline``.

    Score ≈ log(baseline trials needed / compared trials needed) to reach the
    same objective quantile: positive = compared is more sample-efficient.
    Curves must share trend (both INCREASING after any flips).
    """

    baseline_curve: ConvergenceCurve

    def score(self, compared: ConvergenceCurve) -> float:
        base = self.baseline_curve
        if base.trend != compared.trend:
            raise ValueError(f"Trend mismatch: {base.trend} vs {compared.trend}.")
        base_med, comp_med = _signed_median_curves(base, compared, align=False)
        # Objective threshold: final median of the baseline.
        target = base_med[-1]
        base_t = _first_index_reaching(base_med, target)
        comp_t = _first_index_reaching(comp_med, target)
        if comp_t is None:
            # Compared never reaches it; score by how far it got in log-ratio
            # of trials at its best value.
            reached = comp_med[-1]
            base_at = _first_index_reaching(base_med, reached)
            if base_at is None:
                return 0.0
            return float(np.log((base_at + 1) / len(comp_med)))
        return float(np.log((base_t + 1) / (comp_t + 1)))


def _first_index_reaching(values: np.ndarray, target: float) -> Optional[int]:
    hits = np.nonzero(values >= target - 1e-12)[0]
    return int(hits[0]) if len(hits) else None


def _signed_median_curves(
    base: ConvergenceCurve, compared: ConvergenceCurve, *, align: bool
):
    """Median curves of both, sign-flipped so bigger is always better.

    ``align=True`` truncates both to the shorter length.
    """
    sign = 1.0 if base.trend == ConvergenceCurve.YTrend.INCREASING else -1.0
    base_med = sign * base.percentile_curve(50.0)
    comp_med = sign * compared.percentile_curve(50.0)
    if align:
        n = min(len(base_med), len(comp_med))
        return base_med[:n], comp_med[:n]
    return base_med, comp_med


@dataclasses.dataclass
class WinRateComparator:
    """Fraction of (baseline, compared) batch pairs where compared wins."""

    baseline_curve: ConvergenceCurve

    def score(self, compared: ConvergenceCurve) -> float:
        base = self.baseline_curve
        sign = 1.0 if base.trend == ConvergenceCurve.YTrend.INCREASING else -1.0
        wins, total = 0, 0
        for b in base.ys:
            for c in compared.ys:
                total += 1
                if sign * c[-1] > sign * b[-1]:
                    wins += 1
        return wins / max(total, 1)


@dataclasses.dataclass
class SimpleRegretComparator:
    """Simple regret vs a known optimum at a fixed trial budget."""

    optimum: float
    goal: base_study_config.ObjectiveMetricGoal

    def regret(self, curve: ConvergenceCurve, at_trial: Optional[int] = None) -> float:
        idx = -1 if at_trial is None else min(at_trial - 1, curve.ys.shape[1] - 1)
        best = np.median(curve.ys[:, idx])
        if self.goal.is_maximize:
            return float(self.optimum - best)
        return float(best - self.optimum)


class HypervolumeCurveConverter:
    """Trials → cumulative-hypervolume curve (multi-objective progress).

    Parity with the reference ``HypervolumeCurveConverter``
    (``convergence_curve.py:714``), computed by the XLA random-direction
    hypervolume op.
    """

    def __init__(
        self,
        metric_informations: Sequence[base_study_config.MetricInformation],
        *,
        reference_point: Optional[np.ndarray] = None,
        num_vectors: int = 2000,
        seed: int = 0,
    ):
        self._metrics = list(metric_informations)
        self._reference = reference_point
        self._num_vectors = num_vectors
        self._seed = seed

    def convert(self, trials: Sequence[trial_.Trial]) -> ConvergenceCurve:
        import jax

        from vizier_tpu.ops import pareto as pareto_ops

        if not trials:
            return ConvergenceCurve(
                xs=np.zeros((0,)),
                ys=np.zeros((1, 0)),
                trend=ConvergenceCurve.YTrend.INCREASING,
            )
        rows = []
        for t in trials:
            row = []
            for info in self._metrics:
                usable = (
                    t.final_measurement
                    and not t.infeasible  # same invariant as MetricsEncoder
                    and info.name in t.final_measurement.metrics
                )
                if usable:
                    v = t.final_measurement.metrics[info.name].value
                    row.append(-v if info.goal.is_minimize else v)
                else:
                    row.append(-np.inf)
            rows.append(row)
        points = np.asarray(rows, dtype=np.float32)
        if self._reference is None:
            finite = points[np.all(np.isfinite(points), axis=1)]
            ref = (
                finite.min(axis=0) - 1e-6
                if len(finite)
                else np.zeros(points.shape[1], np.float32)
            )
        else:
            ref = np.asarray(self._reference, np.float32)
        shifted = np.maximum(np.nan_to_num(points - ref[None, :], neginf=0.0), 0.0)
        cum = pareto_ops.cum_hypervolume_origin(
            shifted, jax.random.PRNGKey(self._seed), num_vectors=self._num_vectors
        )
        ys = np.asarray(cum, dtype=np.float64)
        return ConvergenceCurve(
            xs=np.arange(1, len(trials) + 1),
            ys=ys[None, :],
            trend=ConvergenceCurve.YTrend.INCREASING,
        )


@dataclasses.dataclass
class PercentageBetterComparator:
    """Fraction of x-positions where compared's median beats baseline's."""

    baseline_curve: ConvergenceCurve

    def score(self, compared: ConvergenceCurve) -> float:
        base_med, comp_med = _signed_median_curves(
            self.baseline_curve, compared, align=True
        )
        return float(np.mean(comp_med > base_med))


@dataclasses.dataclass
class OptimalityGapComparator:
    """Relative final-gap score of compared vs baseline.

    Reference comparator family (``convergence_curve.py:913`` context):
    both curves' final median distances to the optimum are compared as
    log(baseline_gap / compared_gap) — positive means compared ends closer
    to the optimum; 0 means parity.
    """

    baseline_curve: ConvergenceCurve
    optimum: float

    def score(self, compared: ConvergenceCurve) -> float:
        base_gap = abs(self.optimum - np.median(self.baseline_curve.ys[:, -1]))
        comp_gap = abs(self.optimum - np.median(compared.ys[:, -1]))
        return float(np.log(max(base_gap, 1e-12) / max(comp_gap, 1e-12)))


class MultiMetricCurveConverter:
    """Metric-config-driven curve converter with safety warping.

    Parity with the reference ``MultiMetricCurveConverter``
    (``convergence_curve.py:464``): single-objective configs route to
    ``ConvergenceCurveConverter``, multi-objective to
    ``HypervolumeCurveConverter``, and unsafe trials are warped infeasible
    (``multimetric.SafetyChecker``) before conversion either way.
    """

    def __init__(self, metrics_config, converter):
        self.metrics_config = metrics_config
        self.converter = converter

    @classmethod
    def from_metrics_config(
        cls, metrics_config: base_study_config.MetricsConfig, **kwargs
    ) -> "MultiMetricCurveConverter":
        objectives = list(
            metrics_config.of_type(base_study_config.MetricType.OBJECTIVE)
        )
        if metrics_config.is_single_objective:
            converter = ConvergenceCurveConverter(objectives[0], **kwargs)
        else:
            converter = HypervolumeCurveConverter(objectives, **kwargs)
        return cls(metrics_config, converter)

    def convert(self, trials: Sequence[trial_.Trial]) -> ConvergenceCurve:
        if not trials:
            raise ValueError("No trials provided.")
        if not any(m.is_safety_metric for m in self.metrics_config):
            return self.converter.convert(list(trials))
        import copy as _copy

        from vizier_tpu.pyvizier import multimetric

        checker = multimetric.SafetyChecker(self.metrics_config)
        # Deep-copy only what warping may mutate (the unsafe trials).
        warped = [
            t if checker.is_safe(t) else checker.warp_unsafe_trials([_copy.deepcopy(t)])[0]
        for t in trials]
        return self.converter.convert(warped)


class RestartingCurveConverter:
    """Incremental curve building with periodic converter rebuilds.

    Parity with the reference ``RestartingCurveConverter``
    (``convergence_curve.py:516``), adapted to this project's *stateless*
    converters: every ``convert(new_batch)`` runs the current converter
    over the FULL accumulated history and returns the tail slice for the
    new batch (so callers can stream batches and concatenate curves), and
    the converter instance is rebuilt via ``converter_factory`` whenever
    the total trial count crosses a power of ``restart_rate`` — refreshing
    anything the converter snapshots at construction (e.g. an inferred
    hypervolume reference point).
    """

    def __init__(self, converter_factory, *, restart_min_trials: int = 10,
                 restart_rate: float = 2.0):
        if restart_min_trials < 0:
            raise ValueError("restart_min_trials must be >= 0.")
        if restart_rate <= 1.0:
            raise ValueError("restart_rate must be > 1.")
        self._factory = converter_factory
        self._restart_min_trials = restart_min_trials
        self._restart_rate = restart_rate
        self._all_trials: List[trial_.Trial] = []
        self._converter = None

    def convert(self, trials: Sequence[trial_.Trial]) -> ConvergenceCurve:
        if self._converter is None:
            self._converter = self._factory()
        self._all_trials.extend(trials)
        full = self._converter.convert(list(self._all_trials))
        curve = ConvergenceCurve(
            xs=full.xs[-len(trials):] if len(trials) else full.xs[:0],
            ys=full.ys[:, full.ys.shape[1] - len(trials):],
            trend=full.trend,
        )
        if len(self._all_trials) >= self._restart_min_trials:
            log_prev = np.log(1 + len(self._all_trials) - len(trials)) / np.log(
                self._restart_rate
            )
            log_now = np.log(1 + len(self._all_trials)) / np.log(self._restart_rate)
            if int(log_now) > int(log_prev):
                self._converter = None  # rebuild on next convert
        return curve


def build_convergence_curve(
    baseline_curve: Sequence[float], compared_curve: Sequence[float]
) -> List[float]:
    """Relative convergence: for each baseline value, the first compared
    index reaching it (inf if never). Both curves must be non-decreasing
    (maximization best-so-far). Reference ``convergence_curve.py:1108``.
    """
    import bisect

    compared = list(compared_curve)
    out: List[float] = []
    for value in baseline_curve:
        j = bisect.bisect_left(compared, value)
        out.append(float(j) if j != len(compared) else float("inf"))
    return out
