"""One-sided t-test score for comparing algorithms' simple regrets.

Parity with
``/root/reference/vizier/_src/benchmarks/analyzers/simple_regret_score.py:27``:
the p-value that the baseline's mean final objective is better than the
candidate's. Low score = high confidence the candidate beats the baseline.
Single-candidate inputs use a one-sample t-test against the candidate's
value; otherwise Welch's unequal-variance two-sample test.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from scipy import stats

from vizier_tpu.pyvizier import base_study_config


def t_test_mean_score(
    baseline_mean_values: Sequence[float],
    candidate_mean_values: Sequence[float],
    objective_goal: base_study_config.ObjectiveMetricGoal,
) -> float:
    """p-value of the one-sided test that candidate's mean beats baseline's."""
    baseline = np.asarray(baseline_mean_values, dtype=float)
    candidate = np.asarray(candidate_mean_values, dtype=float)
    if objective_goal == base_study_config.ObjectiveMetricGoal.MAXIMIZE:
        alternative = "less"  # confidence that baseline mean < candidate mean
    else:
        alternative = "greater"
    if candidate.size == 1:
        result = stats.ttest_1samp(
            a=baseline, popmean=float(candidate[0]), alternative=alternative
        )
    else:
        result = stats.ttest_ind(
            baseline, candidate, equal_var=False, alternative=alternative
        )
    return float(result.pvalue)
