"""Benchmark-state → records/DataFrame summaries.

Parity with ``/root/reference/vizier/_src/benchmarks/analyzers/state_analyzer.py:87``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.runners import benchmark_state
from vizier_tpu.pyvizier import trial as trial_


class BenchmarkStateAnalyzer:
    """Summarizes finished benchmark states into plain records."""

    @staticmethod
    def to_records(
        states: Sequence[benchmark_state.BenchmarkState],
        *,
        algorithm_names: Optional[Sequence[str]] = None,
    ) -> List[Dict]:
        records = []
        for i, state in enumerate(states):
            problem = state.experimenter.problem_statement()
            metric = next(
                m for m in problem.metric_information if not m.is_safety_metric
            )
            trials = state.algorithm.supporter.GetTrials(
                status_matches=trial_.TrialStatus.COMPLETED
            )
            curve = cc.ConvergenceCurveConverter(
                metric, flip_signs_for_min=True
            ).convert(trials)
            records.append(
                {
                    "algorithm": (
                        algorithm_names[i] if algorithm_names else f"algo_{i}"
                    ),
                    "num_trials": len(trials),
                    "best_objective": float(curve.ys[0, -1]) if len(trials) else np.nan,
                    "curve_xs": curve.xs,
                    "curve_ys": curve.ys[0],
                }
            )
        return records

    @staticmethod
    def to_dataframe(states, *, algorithm_names=None):
        import pandas as pd

        return pd.DataFrame(
            BenchmarkStateAnalyzer.to_records(states, algorithm_names=algorithm_names)
        )


@dataclasses.dataclass
class PlotElement:
    """One named curve of a benchmark run (reference ``PlotElement``)."""

    curve: cc.ConvergenceCurve
    yscale: str = "linear"  # 'linear' | 'symlog'


@dataclasses.dataclass
class BenchmarkRecord:
    """One (algorithm, experimenter) result bundle (reference ``:76``).

    ``plot_elements`` maps element names (e.g. 'objective', 'hypervolume')
    to curves; comparison scores are added by ``BenchmarkRecordAnalyzer``.
    """

    algorithm: str
    experimenter_metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    plot_elements: Dict[str, PlotElement] = dataclasses.field(default_factory=dict)
    scores: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def experimenter_key(self) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(self.experimenter_metadata.items()))


class BenchmarkRecordAnalyzer:
    """Cross-record comparison + pandas summaries (reference ``:195``)."""

    @staticmethod
    def add_comparison_metrics(
        records: Sequence[BenchmarkRecord],
        baseline_algo: str,
        *,
        element: str = "objective",
    ) -> List[BenchmarkRecord]:
        """Scores every record against the baseline algorithm's curve on the
        same experimenter: log-efficiency, win-rate, percentage-better."""
        baselines = {
            r.experimenter_key: r
            for r in records
            if r.algorithm == baseline_algo and element in r.plot_elements
        }
        for r in records:
            if element not in r.plot_elements:
                continue
            base = baselines.get(r.experimenter_key)
            if base is None:
                continue
            base_curve = base.plot_elements[element].curve
            curve = r.plot_elements[element].curve
            # Align lengths: extrapolate the shorter run at its incumbent.
            gap = len(base_curve.xs) - len(curve.xs)
            if gap > 0:
                curve = curve.extrapolate_ys(gap)
            elif gap < 0:
                base_curve = base_curve.extrapolate_ys(-gap)
            r.scores[f"log_efficiency_vs_{baseline_algo}"] = (
                cc.LogEfficiencyConvergenceCurveComparator(base_curve).score(curve)
            )
            r.scores[f"win_rate_vs_{baseline_algo}"] = cc.WinRateComparator(
                base_curve
            ).score(curve)
            r.scores[f"pct_better_vs_{baseline_algo}"] = (
                cc.PercentageBetterComparator(base_curve).score(curve)
            )
        return list(records)

    @staticmethod
    def summarize(records: Sequence[BenchmarkRecord]) -> List[Dict]:
        """Flat records (one row per (algorithm, experimenter)) for pandas."""
        rows = []
        for r in records:
            row: Dict = {
                "algorithm": r.algorithm,
                "experimenter": r.experimenter_key,
            }
            for name, element in r.plot_elements.items():
                curve = element.curve
                if curve.ys.size:
                    row[f"{name}_final_median"] = float(
                        np.median(curve.ys[:, -1])
                    )
                    row[f"{name}_num_trials"] = int(curve.xs[-1])
            row.update(r.scores)
            rows.append(row)
        return rows

    @staticmethod
    def summarize_dataframe(records: Sequence[BenchmarkRecord]):
        import pandas as pd

        return pd.DataFrame(BenchmarkRecordAnalyzer.summarize(records))
