"""Benchmark-state → records/DataFrame summaries.

Parity with ``/root/reference/vizier/_src/benchmarks/analyzers/state_analyzer.py:87``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.runners import benchmark_state
from vizier_tpu.pyvizier import trial as trial_


class BenchmarkStateAnalyzer:
    """Summarizes finished benchmark states into plain records."""

    @staticmethod
    def to_records(
        states: Sequence[benchmark_state.BenchmarkState],
        *,
        algorithm_names: Optional[Sequence[str]] = None,
    ) -> List[Dict]:
        records = []
        for i, state in enumerate(states):
            problem = state.experimenter.problem_statement()
            metric = next(
                m for m in problem.metric_information if not m.is_safety_metric
            )
            trials = state.algorithm.supporter.GetTrials(
                status_matches=trial_.TrialStatus.COMPLETED
            )
            curve = cc.ConvergenceCurveConverter(
                metric, flip_signs_for_min=True
            ).convert(trials)
            records.append(
                {
                    "algorithm": (
                        algorithm_names[i] if algorithm_names else f"algo_{i}"
                    ),
                    "num_trials": len(trials),
                    "best_objective": float(curve.ys[0, -1]) if len(trials) else np.nan,
                    "curve_xs": curve.xs,
                    "curve_ys": curve.ys[0],
                }
            )
        return records

    @staticmethod
    def to_dataframe(states, *, algorithm_names=None):
        import pandas as pd

        return pd.DataFrame(
            BenchmarkStateAnalyzer.to_records(states, algorithm_names=algorithm_names)
        )
