"""Convergence-curve plotting.

Parity with the reference's benchmark plotting utilities
(``analyzers/plot_utils.py``): median curves with interquartile bands per
algorithm, on a caller-supplied or fresh matplotlib axis.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.analyzers import convergence_curve as cc


def plot_median_convergence(
    curves_by_algorithm: Dict[str, cc.ConvergenceCurve],
    *,
    ax=None,
    title: str = "",
    ylabel: str = "best objective",
    percentiles: Sequence[float] = (25.0, 75.0),
    log_x: bool = False,
):
    """Plots each algorithm's median curve with a percentile band."""
    import matplotlib.pyplot as plt

    if len(percentiles) != 2:
        raise ValueError(f"percentiles must be a (low, high) pair, got {percentiles}.")
    if ax is None:
        _, ax = plt.subplots(figsize=(7, 4.5))
    for name, curve in curves_by_algorithm.items():
        median = curve.percentile_curve(50.0)
        (line,) = ax.plot(curve.xs, median, label=name)
        if curve.num_batches > 1:
            lo = curve.percentile_curve(percentiles[0])
            hi = curve.percentile_curve(percentiles[1])
            ax.fill_between(curve.xs, lo, hi, alpha=0.2, color=line.get_color())
    if log_x:
        ax.set_xscale("log")
    ax.set_xlabel("trials")
    ax.set_ylabel(ylabel)
    if title:
        ax.set_title(title)
    ax.legend()
    return ax


def plot_states(
    states,
    *,
    algorithm_names: Optional[Sequence[str]] = None,
    ax=None,
    title: str = "",
):
    """Plots benchmark states directly (states → curves → plot)."""
    from vizier_tpu.benchmarks.analyzers.state_analyzer import BenchmarkStateAnalyzer

    records = BenchmarkStateAnalyzer.to_records(
        states, algorithm_names=algorithm_names
    )
    # Repeats of the same algorithm stack into one multi-batch curve (so the
    # percentile band reflects run-to-run variation).
    grouped: Dict[str, list] = {}
    for r in records:
        grouped.setdefault(r["algorithm"], []).append(r)
    curves = {}
    for name, group in grouped.items():
        aligned = cc.ConvergenceCurve.align_xs(
            [
                cc.ConvergenceCurve(
                    xs=r["curve_xs"],
                    ys=np.asarray(r["curve_ys"])[None, :],
                    trend=cc.ConvergenceCurve.YTrend.INCREASING,
                )
                for r in group
            ]
        )
        curves[name] = aligned
    return plot_median_convergence(curves, ax=ax, title=title)
