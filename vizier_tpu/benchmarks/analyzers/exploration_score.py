"""Exploration scores: how thoroughly an algorithm covers the search space.

Parity with
``/root/reference/vizier/_src/benchmarks/analyzers/exploration_score_utils.py:29,99``:
marginal entropy of suggested parameter values (categorical/discrete/integer
by exact counts, continuous by cube-root-rule histogram bins), averaged over
all parameters of all studies in a benchmark-results mapping.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np

from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import study as study_
from vizier_tpu.pyvizier import trial as trial_

# algorithm -> experimenter/spec -> seed -> study
BenchmarkResults = Dict[str, Dict[str, Dict[int, study_.ProblemAndTrials]]]


def _entropy(counts: np.ndarray) -> float:
    p = counts[counts > 0].astype(float)
    if p.size == 0:
        return 0.0
    p = p / p.sum()
    return float(-np.sum(p * np.log(p)))


def compute_parameter_entropy(
    parameter_config: pc.ParameterConfig,
    parameter_values: Iterable[Optional[trial_.ParameterValue]],
) -> float:
    """Entropy (nats) of one parameter's suggested values.

    Comparing two runs is only meaningful at equal sample sizes — the
    histogram/bin estimator's bias depends on n.
    """
    values = [pv.value for pv in parameter_values if pv is not None]
    if not values:
        return 0.0
    ptype = parameter_config.type
    if ptype in (pc.ParameterType.CATEGORICAL, pc.ParameterType.DISCRETE):
        feasible = set(parameter_config.feasible_values)
        bad = [v for v in values if v not in feasible]
        if bad:
            raise ValueError(
                f"Out-of-bound values {bad[:5]} for {parameter_config.name}; "
                f"feasible: {sorted(feasible)}"
            )
        _, counts = np.unique(np.asarray(values, dtype=object), return_counts=True)
        return _entropy(counts)
    lo, hi = parameter_config.bounds
    arr = np.asarray(values, dtype=float)
    if np.any(arr < lo) or np.any(arr > hi):
        raise ValueError(
            f"Out-of-bound values for {parameter_config.name}: bounds [{lo}, {hi}]"
        )
    if ptype == pc.ParameterType.INTEGER:
        _, counts = np.unique(arr, return_counts=True)
        return _entropy(counts)
    # Continuous: fixed-width bins, count ~ c * n^(1/3) (cube-root rules),
    # c chosen so n=100 gives ~30 bins; never more bins than samples.
    n = len(arr)
    c = 30.0 / (100.0 ** (1.0 / 3.0))
    num_bins = min(int(c * n ** (1.0 / 3.0)), n)
    num_bins = max(num_bins, 1)
    counts, _ = np.histogram(arr, bins=np.linspace(lo, hi, num=num_bins + 1))
    return _entropy(counts)


def compute_average_marginal_parameter_entropy(results: BenchmarkResults) -> float:
    """Mean marginal entropy over every parameter of every study in results."""
    entropies = []
    for spec_results in results.values():
        for seed_results in spec_results.values():
            for study in seed_results.values():
                for config in study.problem.search_space.parameters:
                    values = [t.parameters.get(config.name) for t in study.trials]
                    entropies.append(compute_parameter_entropy(config, values))
    if not entropies:
        return 0.0
    return float(np.mean(entropies))
