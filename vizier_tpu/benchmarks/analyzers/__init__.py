"""Benchmark analyzers: convergence curves, comparators, scores, records."""

from vizier_tpu.benchmarks.analyzers.exploration_score import (
    compute_average_marginal_parameter_entropy,
    compute_parameter_entropy,
)
from vizier_tpu.benchmarks.analyzers.simple_regret_score import t_test_mean_score
