"""Benchmark state: an experimenter + an algorithm playing a study.

Parity with
``/root/reference/vizier/_src/benchmarks/runners/benchmark_state.py:42-154``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.benchmarks.experimenters import base as experimenter_base
from vizier_tpu.pythia import local_policy_supporters
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import study_config as sc
from vizier_tpu.pyvizier import trial as trial_


class PolicySuggester:
    """A policy bound to an in-RAM supporter (the benchmark 'algorithm')."""

    def __init__(
        self,
        policy: policy_lib.Policy,
        supporter: local_policy_supporters.InRamPolicySupporter,
    ):
        self._policy = policy
        self._supporter = supporter

    @classmethod
    def from_designer_factory(
        cls,
        problem: base_study_config.ProblemStatement,
        designer_factory: core_lib.DesignerFactory,
        *,
        seed: Optional[int] = None,
        use_in_ram_policy: bool = True,
    ) -> "PolicySuggester":
        config = sc.StudyConfig.from_problem(problem)
        supporter = local_policy_supporters.InRamPolicySupporter(config)
        factory = (
            (lambda p: designer_factory(p, seed=seed)) if seed is not None else designer_factory
        )
        if use_in_ram_policy:
            policy = designer_policy.InRamDesignerPolicy(supporter, factory, problem=problem)
        else:
            policy = designer_policy.DesignerPolicy(supporter, factory)
        return cls(policy, supporter)

    @property
    def supporter(self) -> local_policy_supporters.InRamPolicySupporter:
        return self._supporter

    @property
    def policy(self) -> policy_lib.Policy:
        return self._policy

    def suggest(self, batch_size: int) -> List[trial_.Trial]:
        return self._supporter.SuggestTrials(self._policy, batch_size)


@dataclasses.dataclass
class BenchmarkState:
    experimenter: experimenter_base.Experimenter
    algorithm: PolicySuggester

    @classmethod
    def from_designer_factory(
        cls,
        experimenter: experimenter_base.Experimenter,
        designer_factory: core_lib.DesignerFactory,
        *,
        seed: Optional[int] = None,
    ) -> "BenchmarkState":
        return cls(
            experimenter=experimenter,
            algorithm=PolicySuggester.from_designer_factory(
                experimenter.problem_statement(), designer_factory, seed=seed
            ),
        )
