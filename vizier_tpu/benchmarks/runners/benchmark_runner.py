"""Benchmark runner: composable suggest/evaluate subroutines.

Parity with
``/root/reference/vizier/_src/benchmarks/runners/benchmark_runner.py:63-237``.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import List, Optional, Sequence

from vizier_tpu.benchmarks.runners import benchmark_state
from vizier_tpu.pyvizier import trial as trial_


class BenchmarkSubroutine(abc.ABC):
    @abc.abstractmethod
    def run(self, state: benchmark_state.BenchmarkState) -> None:
        ...


@dataclasses.dataclass
class GenerateSuggestions(BenchmarkSubroutine):
    num_suggestions: int = 1

    def run(self, state: benchmark_state.BenchmarkState) -> None:
        state.algorithm.suggest(self.num_suggestions)


@dataclasses.dataclass
class EvaluateActiveTrials(BenchmarkSubroutine):
    """Evaluates all (or the first ``max_num_trials``) ACTIVE trials."""

    max_num_trials: Optional[int] = None

    def run(self, state: benchmark_state.BenchmarkState) -> None:
        active = state.algorithm.supporter.GetTrials(
            status_matches=trial_.TrialStatus.ACTIVE
        )
        if self.max_num_trials is not None:
            active = active[: self.max_num_trials]
        state.experimenter.evaluate(active)


@dataclasses.dataclass
class GenerateAndEvaluate(BenchmarkSubroutine):
    num_suggestions: int = 1

    def run(self, state: benchmark_state.BenchmarkState) -> None:
        trials = state.algorithm.suggest(self.num_suggestions)
        state.experimenter.evaluate(trials)


@dataclasses.dataclass
class AddPriorTrials(BenchmarkSubroutine):
    """Injects pre-existing (completed) trials into the study."""

    trials: Sequence[trial_.Trial] = ()

    def run(self, state: benchmark_state.BenchmarkState) -> None:
        state.algorithm.supporter.AddTrials(list(self.trials))


@dataclasses.dataclass
class BenchmarkRunner(BenchmarkSubroutine):
    """Runs subroutines in order, ``num_repeats`` times."""

    benchmark_subroutines: Sequence[BenchmarkSubroutine] = ()
    num_repeats: int = 1

    def run(self, state: benchmark_state.BenchmarkState) -> None:
        for _ in range(self.num_repeats):
            for sub in self.benchmark_subroutines:
                sub.run(state)
