"""Benchmark experimenters, runners, and analyzers."""

from vizier_tpu.benchmarks.analyzers.convergence_curve import (
    ConvergenceCurve,
    ConvergenceCurveConverter,
    LogEfficiencyConvergenceCurveComparator,
    SimpleRegretComparator,
    WinRateComparator,
)
from vizier_tpu.benchmarks.experimenters.base import (
    Experimenter,
    NumpyExperimenter,
    bbob_problem,
)
from vizier_tpu.benchmarks.runners.benchmark_runner import (
    AddPriorTrials,
    BenchmarkRunner,
    BenchmarkSubroutine,
    EvaluateActiveTrials,
    GenerateAndEvaluate,
    GenerateSuggestions,
)
from vizier_tpu.benchmarks.runners.benchmark_state import BenchmarkState, PolicySuggester
