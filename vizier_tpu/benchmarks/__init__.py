"""Benchmark experimenters, runners, and analyzers."""

from vizier_tpu.benchmarks.analyzers.convergence_curve import (
    ConvergenceCurve,
    ConvergenceCurveConverter,
    LogEfficiencyConvergenceCurveComparator,
    SimpleRegretComparator,
    WinRateComparator,
)
from vizier_tpu.benchmarks.analyzers.exploration_score import (
    compute_average_marginal_parameter_entropy,
    compute_parameter_entropy,
)
from vizier_tpu.benchmarks.analyzers.simple_regret_score import t_test_mean_score
from vizier_tpu.benchmarks.experimenters.base import (
    Experimenter,
    NumpyExperimenter,
    bbob_problem,
)
from vizier_tpu.benchmarks.experimenters.synthetic.classic import (
    BernoulliMultiArmExperimenter,
    Branin2DExperimenter,
    FixedMultiArmExperimenter,
    HartmannExperimenter,
)
from vizier_tpu.benchmarks.runners.benchmark_runner import (
    AddPriorTrials,
    BenchmarkRunner,
    BenchmarkSubroutine,
    EvaluateActiveTrials,
    GenerateAndEvaluate,
    GenerateSuggestions,
)
from vizier_tpu.benchmarks.runners.benchmark_state import BenchmarkState, PolicySuggester
