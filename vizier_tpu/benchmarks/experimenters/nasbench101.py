"""NASBench-101 experimenter: 7-vertex DAG cell search space.

Parity with
``/root/reference/vizier/_src/benchmarks/experimenters/nasbench101_experimenter.py``:
the search space is the upper-triangular adjacency of a 7-vertex DAG (21
bool params named ``{x}_{y}``) plus one categorical op per interior vertex
(5 spots), and evaluation queries a NASBench-101 API object
(``is_valid``/``query``) — the real ``nasbench`` package when its dataset
is available, or :class:`TabularNASBench101`, a self-contained table
backend keyed by the isomorphism-invariant graph hash.

The graph machinery (pruning unreachable vertices, canonical
neighborhood hashing) is implemented here so the encoding works — and is
testable — without the external package or its 2GB dataset.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

NUM_VERTICES = 7
OP_SPOTS = NUM_VERTICES - 2
MAX_EDGES = 9
INPUT_OP = "input"
OUTPUT_OP = "output"
ALLOWED_OPS = ("conv3x3-bn-relu", "conv1x1-bn-relu", "maxpool3x3")
METRIC_NAMES = (
    "trainable_parameters",
    "training_time",
    "train_accuracy",
    "validation_accuracy",
    "test_accuracy",
)


@dataclasses.dataclass
class ModelSpec:
    """A NASBench-101 cell: DAG adjacency matrix + per-vertex op labels.

    ``matrix``: [V, V] upper-triangular 0/1 (edge x→y iff ``matrix[x, y]``);
    ``ops``: length-V labels, ``ops[0] == "input"``, ``ops[-1] == "output"``.
    """

    matrix: np.ndarray
    ops: List[str]

    def __post_init__(self):
        self.matrix = np.asarray(self.matrix, dtype=int)
        v = self.matrix.shape[0]
        if self.matrix.shape != (v, v) or len(self.ops) != v:
            raise ValueError("matrix must be [V, V] with V op labels.")
        if np.any(np.tril(self.matrix)):
            raise ValueError("matrix must be strictly upper-triangular (a DAG).")
        # Memoized derived values (specs are treated as immutable once
        # built; evaluate loops prune/hash each spec several times).
        self._pruned_cache: Optional[Tuple["ModelSpec"]] = None
        self._hash_cache: Optional[str] = None

    def pruned(self) -> Optional["ModelSpec"]:
        """Removes vertices not on any input→output path.

        Returns None when input and output are disconnected (the cell
        computes nothing — invalid in NASBench-101).
        """
        if self._pruned_cache is not None:
            return self._pruned_cache[0]
        result = self._prune()
        self._pruned_cache = (result,)
        return result

    def _prune(self) -> Optional["ModelSpec"]:
        v = self.matrix.shape[0]
        # Forward reachability from input (vertex 0).
        fwd = {0}
        frontier = [0]
        while frontier:
            x = frontier.pop()
            for y in np.nonzero(self.matrix[x])[0]:
                if y not in fwd:
                    fwd.add(int(y))
                    frontier.append(int(y))
        # Backward reachability from output (vertex V-1).
        bwd = {v - 1}
        frontier = [v - 1]
        while frontier:
            y = frontier.pop()
            for x in np.nonzero(self.matrix[:, y])[0]:
                if x not in bwd:
                    bwd.add(int(x))
                    frontier.append(int(x))
        keep = sorted(fwd & bwd)
        if 0 not in keep or (v - 1) not in keep:
            return None
        idx = np.asarray(keep)
        return ModelSpec(
            matrix=self.matrix[np.ix_(idx, idx)],
            ops=[self.ops[i] for i in keep],
        )

    def graph_hash(self) -> str:
        """Isomorphism-invariant hash of the PRUNED (matrix, ops) graph.

        Iterative neighborhood hashing: every vertex starts from
        (in-degree, out-degree, op) and repeatedly absorbs the sorted
        hashes of its in- and out-neighborhoods; the final digest is the
        hash of the sorted vertex hashes, so any vertex relabeling of the
        same computation graph maps to the same key.
        """
        if self._hash_cache is not None:
            return self._hash_cache
        self._hash_cache = self._compute_hash()
        return self._hash_cache

    def _compute_hash(self) -> str:
        spec = self.pruned()
        if spec is None:
            return "invalid"
        m, ops = spec.matrix, spec.ops
        v = m.shape[0]
        in_deg = m.sum(axis=0)
        out_deg = m.sum(axis=1)
        hashes = [
            hashlib.md5(
                f"{int(in_deg[i])}|{int(out_deg[i])}|{ops[i]}".encode()
            ).hexdigest()
            for i in range(v)
        ]
        for _ in range(v):
            hashes = [
                hashlib.md5(
                    (
                        "".join(sorted(hashes[x] for x in np.nonzero(m[:, i])[0]))
                        + "|"
                        + "".join(sorted(hashes[y] for y in np.nonzero(m[i])[0]))
                        + "|"
                        + hashes[i]
                    ).encode()
                ).hexdigest()
                for i in range(v)
            ]
        return hashlib.md5("".join(sorted(hashes)).encode()).hexdigest()


class TabularNASBench101:
    """Table-backed NASBench-101 API: graph-hash → metrics dict.

    Duck-type compatible with the ``nasbench`` package's API object
    (``is_valid``/``query``) so :class:`NASBench101Experimenter` works
    against either. The table file is a JSON mapping graph hashes (as
    produced by :meth:`ModelSpec.graph_hash`) to metric dicts.
    """

    def __init__(self, table: Dict[str, Dict[str, float]]):
        self._table = table

    @classmethod
    def from_file(cls, path: str) -> "TabularNASBench101":
        if not path or not os.path.exists(path):
            raise FileNotFoundError(
                f"NASBench-101 table not found at {path!r}. Export the "
                "dataset to a hash→metrics JSON; this image bundles no "
                "benchmark data."
            )
        with open(path) as f:
            return cls(json.load(f))

    def is_valid(self, spec: ModelSpec) -> bool:
        pruned = spec.pruned()
        if pruned is None:
            return False
        if pruned.matrix.sum() > MAX_EDGES:
            return False
        if pruned.matrix.shape[0] > NUM_VERTICES:
            return False
        if any(
            op not in ALLOWED_OPS for op in pruned.ops[1:-1]
        ) or pruned.ops[0] != INPUT_OP or pruned.ops[-1] != OUTPUT_OP:
            return False
        # Hash the already-pruned spec: pruning is idempotent, so this
        # equals spec.graph_hash() without re-walking the full graph.
        return pruned.graph_hash() in self._table

    def query(self, spec: ModelSpec) -> Dict[str, float]:
        return dict(self._table[spec.graph_hash()])

    def query_by_hash(self, graph_hash: str) -> Dict[str, float]:
        return dict(self._table[graph_hash])


class NASBench101Experimenter(base.Experimenter):
    """NASBench-101: binary DAG edges + categorical convolution ops.

    Reference ``NASBench101Experimenter`` (``nasbench101_experimenter.py``):
    search space is 21 bools (``{x}_{y}`` for the strict upper triangle of a
    7-vertex adjacency) ∪ 5 categorical op spots; invalid graphs complete
    infeasible, valid ones carry all five tabulated metrics.
    """

    def __init__(self, nasbench):
        self._nasbench = nasbench

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            spec = self._trial_to_model_spec(t)
            if self._nasbench.is_valid(spec):
                results = self._nasbench.query(spec)
                t.complete(
                    trial_.Measurement(
                        metrics={k: results[k] for k in METRIC_NAMES}
                    )
                )
            else:
                t.complete(infeasibility_reason="Not in search space.")

    def _trial_to_model_spec(self, t: trial_.Trial) -> ModelSpec:
        matrix = np.zeros((NUM_VERTICES, NUM_VERTICES), dtype=int)
        for y in range(NUM_VERTICES):
            for x in range(NUM_VERTICES):
                if y > x:
                    matrix[x][y] = int(
                        str(t.parameters.get_value(f"{x}_{y}")) == "True"
                    )
        ops = (
            [INPUT_OP]
            + [
                str(t.parameters.get_value(f"ops_{i}"))
                for i in range(OP_SPOTS)
            ]
            + [OUTPUT_OP]
        )
        return ModelSpec(matrix=matrix, ops=ops)

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        root = problem.search_space.root
        for y in range(NUM_VERTICES):
            for x in range(NUM_VERTICES):
                if y > x:
                    root.add_bool_param(name=f"{x}_{y}")
        for i in range(OP_SPOTS):
            root.add_categorical_param(
                name=f"ops_{i}", feasible_values=list(ALLOWED_OPS)
            )
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="validation_accuracy",
                goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
            )
        )
        return problem


def synthetic_nasbench101(
    num_cells: int = 64, seed: int = 0
) -> Tuple[TabularNASBench101, List[ModelSpec]]:
    """A NASBench-101-STYLE table over randomly sampled valid cells.

    Not real NASBench data (none is bundled): random valid specs are hashed
    and assigned a structured synthetic accuracy, so the full experimenter
    pipeline — encode → prune → hash → query — runs end to end in tests.
    Returns (api, the generating specs).
    """
    rng = np.random.default_rng(seed)
    table: Dict[str, Dict[str, float]] = {}
    specs: List[ModelSpec] = []
    while len(table) < num_cells:
        matrix = np.triu(
            (rng.uniform(size=(NUM_VERTICES, NUM_VERTICES)) < 0.35).astype(int), 1
        )
        # Ensure a backbone path so most samples are valid.
        for i in range(NUM_VERTICES - 1):
            if rng.uniform() < 0.8:
                matrix[i, i + 1] = 1
        ops = (
            [INPUT_OP]
            + [ALLOWED_OPS[i] for i in rng.integers(0, len(ALLOWED_OPS), OP_SPOTS)]
            + [OUTPUT_OP]
        )
        spec = ModelSpec(matrix=matrix, ops=ops)
        pruned = spec.pruned()
        if pruned is None or pruned.matrix.sum() > MAX_EDGES:
            continue
        h = spec.graph_hash()
        if h in table:
            continue
        acc = float(
            0.85
            + 0.05 * np.tanh(pruned.matrix.sum() / 4.0)
            + 0.02 * rng.normal()
        )
        table[h] = {
            "trainable_parameters": float(1e6 * (1 + pruned.matrix.sum())),
            "training_time": float(1000.0 + 100.0 * pruned.matrix.shape[0]),
            "train_accuracy": min(acc + 0.05, 1.0),
            "validation_accuracy": acc,
            "test_accuracy": acc - 0.01,
        }
        specs.append(spec)
    return TabularNASBench101(table), specs
