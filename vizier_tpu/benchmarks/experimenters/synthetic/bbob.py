"""The 24-function BBOB synthetic benchmark suite.

Parity with
``/root/reference/vizier/_src/benchmarks/experimenters/synthetic/bbob.py``:
the standard BBOB functions (Hansen et al., "Real-Parameter Black-Box
Optimization Benchmarking 2009: Noiseless Functions Definitions") with their
standard transforms (T_osz, T_asy, Lambda conditioning, seeded rotations,
boundary penalty). Implemented batched: every function maps ``[N, D] -> [N]``
so whole candidate batches evaluate in one vectorized call.

All functions have optimum value 0 at the origin (use the Shifting wrapper
to relocate optima).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List

import numpy as np

# ---------------------------------------------------------------------------
# Transformations
# ---------------------------------------------------------------------------


def lambda_alpha(alpha: float, dim: int) -> np.ndarray:
    """Diagonal conditioning matrix Λ^α as a [D] vector."""
    if dim == 1:
        return np.ones(1)
    i = np.arange(dim)
    return alpha ** (0.5 * i / (dim - 1))


def t_osz(x: np.ndarray) -> np.ndarray:
    """Oscillation transform, applied elementwise."""
    xhat = np.where(x != 0, np.log(np.abs(np.where(x != 0, x, 1.0))), 0.0)
    c1 = np.where(x > 0, 10.0, 5.5)
    c2 = np.where(x > 0, 7.9, 3.1)
    return np.sign(x) * np.exp(xhat + 0.049 * (np.sin(c1 * xhat) + np.sin(c2 * xhat)))


def t_asy(x: np.ndarray, beta: float) -> np.ndarray:
    """Asymmetry transform over the last axis."""
    dim = x.shape[-1]
    if dim == 1:
        exponents = np.zeros(1)
    else:
        exponents = beta * np.arange(dim) / (dim - 1)
    pos = x > 0
    safe = np.where(pos, x, 1.0)
    return np.where(pos, safe ** (1.0 + exponents * np.sqrt(safe)), x)


def f_pen(x: np.ndarray) -> np.ndarray:
    """Boundary penalty sum(max(0, |x_i| - 5)^2) over the last axis."""
    return np.sum(np.maximum(0.0, np.abs(x) - 5.0) ** 2, axis=-1)


@functools.lru_cache(maxsize=256)
def _rotation(dim: int, seed: int) -> np.ndarray:
    """Seeded random orthogonal matrix (QR of a Gaussian)."""
    rng = np.random.default_rng(seed)
    q, r = np.linalg.qr(rng.standard_normal((dim, dim)))
    return q * np.sign(np.diag(r))


def _r(dim: int, fn_id: int) -> np.ndarray:
    return _rotation(dim, 1000 + fn_id)


def _q(dim: int, fn_id: int) -> np.ndarray:
    return _rotation(dim, 2000 + fn_id)


def _dim(x: np.ndarray) -> int:
    return x.shape[-1]


def _batch(fn: Callable[[np.ndarray], np.ndarray]):
    """Ensures [N, D] input; output [N]."""

    @functools.wraps(fn)
    def wrapped(x: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        return fn(x)

    return wrapped


# ---------------------------------------------------------------------------
# The 24 functions. x: [N, D] -> [N]. Optimum 0 at origin.
# ---------------------------------------------------------------------------


@_batch
def Sphere(x: np.ndarray) -> np.ndarray:
    return np.sum(x**2, axis=-1)


@_batch
def Ellipsoidal(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = t_osz(x)
    cond = 10.0 ** (6.0 * np.arange(d) / max(d - 1, 1))
    return np.sum(cond * z**2, axis=-1)


@_batch
def Rastrigin(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = t_asy(t_osz(x), 0.2) * lambda_alpha(10.0, d)
    return 10.0 * (d - np.sum(np.cos(2 * np.pi * z), axis=-1)) + np.sum(z**2, axis=-1)


@_batch
def BuecheRastrigin(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    t = t_osz(x)
    scales = 10.0 ** (0.5 * np.arange(d) / max(d - 1, 1))
    odd = (np.arange(d) % 2 == 0)  # "odd" indices i=1,3,... in 1-based BBOB
    s = np.where(odd & (t > 0), 10.0 * scales, scales)
    z = s * t
    return (
        10.0 * (d - np.sum(np.cos(2 * np.pi * z), axis=-1))
        + np.sum(z**2, axis=-1)
        + 100.0 * f_pen(x)
    )


@_batch
def LinearSlope(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    # x_opt at the +5 corner; optimum shifted to 0 by the constant term.
    s = 10.0 ** (np.arange(d) / max(d - 1, 1))
    z = np.where(x * 5.0 < 25.0, x, 5.0)
    return np.sum(5.0 * np.abs(s) - s * z, axis=-1)


@_batch
def AttractiveSector(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = (x @ _r(d, 6).T * lambda_alpha(10.0, d)) @ _q(d, 6).T
    s = np.where(z > 0, 100.0, 1.0)
    val = np.sum((s * z) ** 2, axis=-1)
    return t_osz(val.reshape(-1, 1)).reshape(-1) ** 0.9


@_batch
def StepEllipsoidal(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    zhat = (x @ _r(d, 7).T) * lambda_alpha(10.0, d)
    ztilde = np.where(
        np.abs(zhat) > 0.5, np.floor(0.5 + zhat), np.floor(0.5 + 10.0 * zhat) / 10.0
    )
    zr = ztilde @ _q(d, 7).T
    cond = 10.0 ** (2.0 * np.arange(d) / max(d - 1, 1))
    body = np.sum(cond * zr**2, axis=-1)
    first = np.abs(zhat[..., 0]) / 1e4
    return 0.1 * np.maximum(first, body) + f_pen(x)


@_batch
def Rosenbrock(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = np.maximum(1.0, np.sqrt(d) / 8.0) * x + 1.0
    return np.sum(
        100.0 * (z[..., :-1] ** 2 - z[..., 1:]) ** 2 + (z[..., :-1] - 1.0) ** 2, axis=-1
    )


@_batch
def RosenbrockRotated(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    # +1 (not the standard +0.5): keeps the optimum-at-origin convention.
    z = np.maximum(1.0, np.sqrt(d) / 8.0) * (x @ _r(d, 9).T) + 1.0
    return np.sum(
        100.0 * (z[..., :-1] ** 2 - z[..., 1:]) ** 2 + (z[..., :-1] - 1.0) ** 2, axis=-1
    )


@_batch
def EllipsoidalRotated(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = t_osz(x @ _r(d, 10).T)
    cond = 10.0 ** (6.0 * np.arange(d) / max(d - 1, 1))
    return np.sum(cond * z**2, axis=-1)


@_batch
def Discus(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = t_osz(x @ _r(d, 11).T)
    return 1e6 * z[..., 0] ** 2 + np.sum(z[..., 1:] ** 2, axis=-1)


@_batch
def BentCigar(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    r = _r(d, 12)
    z = (t_asy(x @ r.T, 0.5)) @ r.T
    return z[..., 0] ** 2 + 1e6 * np.sum(z[..., 1:] ** 2, axis=-1)


@_batch
def SharpRidge(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = ((x @ _r(d, 13).T) * lambda_alpha(10.0, d)) @ _q(d, 13).T
    return z[..., 0] ** 2 + 100.0 * np.sqrt(np.sum(z[..., 1:] ** 2, axis=-1))


@_batch
def DifferentPowers(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = x @ _r(d, 14).T
    exponents = 2.0 + 4.0 * np.arange(d) / max(d - 1, 1)
    return np.sqrt(np.sum(np.abs(z) ** exponents, axis=-1))


@_batch
def RastriginRotated(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    r, q = _r(d, 15), _q(d, 15)
    z = ((t_asy(t_osz(x @ r.T), 0.2) @ q.T) * lambda_alpha(10.0, d)) @ r.T
    return 10.0 * (d - np.sum(np.cos(2 * np.pi * z), axis=-1)) + np.sum(z**2, axis=-1)


@_batch
def Weierstrass(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    r, q = _r(d, 16), _q(d, 16)
    z = ((t_osz(x @ r.T)) @ q.T * lambda_alpha(0.01, d)) @ r.T
    k = np.arange(12)
    ak = 0.5**k
    bk = 3.0**k
    f0 = np.sum(ak * np.cos(np.pi * bk))
    inner = np.sum(
        ak[None, None, :] * np.cos(2 * np.pi * bk[None, None, :] * (z[..., None] + 0.5)),
        axis=-1,
    )
    return 10.0 * (np.mean(inner, axis=-1) - f0) ** 3 + (10.0 / d) * f_pen(x)


def _schaffers(x: np.ndarray, alpha: float, fn_id: int) -> np.ndarray:
    d = x.shape[-1]
    z = (t_asy(x @ _r(d, fn_id).T, 0.5) @ _q(d, fn_id).T) * lambda_alpha(alpha, d)
    if d == 1:
        s = np.abs(z[..., 0])
    else:
        s = np.sqrt(z[..., :-1] ** 2 + z[..., 1:] ** 2)
    body = np.mean(np.sqrt(s) + np.sqrt(s) * np.sin(50.0 * s**0.2) ** 2, axis=-1) ** 2
    return body + 10.0 * f_pen(x)


@_batch
def SchaffersF7(x: np.ndarray) -> np.ndarray:
    return _schaffers(x, 10.0, 17)


@_batch
def SchaffersF7IllConditioned(x: np.ndarray) -> np.ndarray:
    return _schaffers(x, 1000.0, 18)


@_batch
def GriewankRosenbrock(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    # +1 (not the standard +0.5): keeps the optimum-at-origin convention.
    z = np.maximum(1.0, np.sqrt(d) / 8.0) * (x @ _r(d, 19).T) + 1.0
    if d == 1:
        s = 100.0 * (z[..., :1] ** 2 - z[..., :1]) ** 2 + (z[..., :1] - 1.0) ** 2
    else:
        s = 100.0 * (z[..., :-1] ** 2 - z[..., 1:]) ** 2 + (z[..., :-1] - 1.0) ** 2
    return (10.0 / max(d - 1, 1)) * np.sum(s / 4000.0 - np.cos(s), axis=-1) + 10.0


@_batch
def Schwefel(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    # Optimum at origin in our convention: the canonical 420.96874633 basin
    # center is reached at x = 0 via the +mu shift below.
    mu = 4.2096874633
    z = 100.0 * (lambda_alpha(10.0, d) * x + mu)
    body = -np.sum(z * np.sin(np.sqrt(np.abs(z))), axis=-1) / (100.0 * d)
    return body + 4.189828872724339 + 100.0 * f_pen(z / 100.0)


def _gallagher(x: np.ndarray, num_peaks: int, fn_id: int) -> np.ndarray:
    d = x.shape[-1]
    rng = np.random.default_rng(3000 + fn_id)
    # Peak locations; the global one at the origin with height 10.
    ys = rng.uniform(-4.0, 4.0, size=(num_peaks, d))
    ys[0] = 0.0
    heights = np.concatenate([[10.0], np.linspace(1.1, 9.1, num_peaks - 1)])
    alphas = np.concatenate(
        [[1000.0], 1000.0 ** (2.0 * np.arange(num_peaks - 1) / max(num_peaks - 2, 1))]
    )
    r = _r(d, fn_id)
    xr = x @ r.T
    vals = []
    for i in range(num_peaks):
        c = lambda_alpha(alphas[i], d) / alphas[i] ** 0.25
        diff = xr - ys[i]
        e = np.sum(diff * c * diff, axis=-1)
        vals.append(heights[i] * np.exp(-e / (2.0 * d)))
    best = np.max(np.stack(vals, axis=-1), axis=-1)
    return t_osz((10.0 - best).reshape(-1, 1)).reshape(-1) ** 2 + f_pen(x)


@_batch
def Gallagher101Me(x: np.ndarray) -> np.ndarray:
    return _gallagher(x, 101, 21)


@_batch
def Gallagher21Me(x: np.ndarray) -> np.ndarray:
    return _gallagher(x, 21, 22)


@_batch
def Katsuura(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    z = ((x @ _r(d, 23).T) * lambda_alpha(100.0, d)) @ _q(d, 23).T
    j = 2.0 ** np.arange(1, 33)
    terms = np.abs(j[None, None, :] * z[..., None] - np.round(j[None, None, :] * z[..., None])) / j
    inner = 1.0 + (np.arange(d) + 1.0)[None, :] * np.sum(terms, axis=-1)
    prod = np.prod(inner ** (10.0 / d**1.2), axis=-1)
    return (10.0 / d**2) * prod - 10.0 / d**2 + f_pen(x)


@_batch
def LunacekBiRastrigin(x: np.ndarray) -> np.ndarray:
    d = _dim(x)
    mu0 = 2.5
    s = 1.0 - 1.0 / (2.0 * np.sqrt(d + 20.0) - 8.2)
    mu1 = -np.sqrt((mu0**2 - 1.0) / s)
    # Optimum-at-origin convention: shift the standard xhat = 2 sign(x*) x
    # construction so x = 0 lands on the mu0 basin floor.
    xhat = x + mu0
    z = ((xhat - mu0) @ _r(d, 24).T * lambda_alpha(100.0, d)) @ _q(d, 24).T
    term1 = np.sum((xhat - mu0) ** 2, axis=-1)
    term2 = d + s * np.sum((xhat - mu1) ** 2, axis=-1)
    rastrigin = 10.0 * (d - np.sum(np.cos(2 * np.pi * z), axis=-1))
    return np.minimum(term1, term2) + rastrigin + 1e4 * f_pen(x)


BBOB_FUNCTIONS: Dict[str, Callable[[np.ndarray], np.ndarray]] = {
    "Sphere": Sphere,
    "Ellipsoidal": Ellipsoidal,
    "Rastrigin": Rastrigin,
    "BuecheRastrigin": BuecheRastrigin,
    "LinearSlope": LinearSlope,
    "AttractiveSector": AttractiveSector,
    "StepEllipsoidal": StepEllipsoidal,
    "Rosenbrock": Rosenbrock,
    "RosenbrockRotated": RosenbrockRotated,
    "EllipsoidalRotated": EllipsoidalRotated,
    "Discus": Discus,
    "BentCigar": BentCigar,
    "SharpRidge": SharpRidge,
    "DifferentPowers": DifferentPowers,
    "RastriginRotated": RastriginRotated,
    "Weierstrass": Weierstrass,
    "SchaffersF7": SchaffersF7,
    "SchaffersF7IllConditioned": SchaffersF7IllConditioned,
    "GriewankRosenbrock": GriewankRosenbrock,
    "Schwefel": Schwefel,
    "Gallagher101Me": Gallagher101Me,
    "Gallagher21Me": Gallagher21Me,
    "Katsuura": Katsuura,
    "LunacekBiRastrigin": LunacekBiRastrigin,
}


@_batch
def Branin(x: np.ndarray) -> np.ndarray:
    """The classic 2-D Branin-Hoo function over the standard [-5,10]x[0,15].

    Not part of BBOB, but the canonical GP-BO benchmark (BASELINE.md eval
    configs). Inputs here are in BBOB's [-5, 5] frame and are affinely
    mapped onto Branin's native domain; global minimum value ≈ 0.397887.
    """
    x1 = (x[..., 0] + 5.0) * 1.5 - 5.0  # [-5,5] -> [-5,10]
    x2 = (x[..., 1] + 5.0) * 1.5  # [-5,5] -> [0,15]
    a, b, c = 1.0, 5.1 / (4 * np.pi**2), 5.0 / np.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * np.pi)
    return a * (x2 - b * x1**2 + c * x1 - r) ** 2 + s * (1 - t) * np.cos(x1) + s


# Non-BBOB extras served through the same interface.
EXTRA_FUNCTIONS = {"Branin": Branin}
