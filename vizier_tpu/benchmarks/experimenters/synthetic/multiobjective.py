"""Multi-objective synthetic problems: ZDT and DTLZ families.

Parity in role with the reference's
``synthetic/multiobjective_optproblems.py`` / ``deb.py``: the standard
two-objective ZDT suite (1, 2, 3, 4, 6) and DTLZ1/DTLZ2 with a configurable
number of objectives. All objectives are MINIMIZE.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


def _zdt_g(x: np.ndarray) -> np.ndarray:
    return 1.0 + 9.0 * np.mean(x[..., 1:], axis=-1)


def zdt1(x: np.ndarray) -> np.ndarray:
    f1 = x[..., 0]
    g = _zdt_g(x)
    return np.stack([f1, g * (1.0 - np.sqrt(f1 / g))], axis=-1)


def zdt2(x: np.ndarray) -> np.ndarray:
    f1 = x[..., 0]
    g = _zdt_g(x)
    return np.stack([f1, g * (1.0 - (f1 / g) ** 2)], axis=-1)


def zdt3(x: np.ndarray) -> np.ndarray:
    f1 = x[..., 0]
    g = _zdt_g(x)
    h = 1.0 - np.sqrt(f1 / g) - (f1 / g) * np.sin(10.0 * np.pi * f1)
    return np.stack([f1, g * h], axis=-1)


def zdt4(x: np.ndarray) -> np.ndarray:
    # x0 in [0,1], rest in [-5,5] conventionally; we keep [0,1] and rescale.
    f1 = x[..., 0]
    rest = x[..., 1:] * 10.0 - 5.0
    g = 1.0 + 10.0 * rest.shape[-1] + np.sum(
        rest**2 - 10.0 * np.cos(4.0 * np.pi * rest), axis=-1
    )
    return np.stack([f1, g * (1.0 - np.sqrt(np.maximum(f1, 1e-12) / g))], axis=-1)


def zdt6(x: np.ndarray) -> np.ndarray:
    f1 = 1.0 - np.exp(-4.0 * x[..., 0]) * np.sin(6.0 * np.pi * x[..., 0]) ** 6
    g = 1.0 + 9.0 * np.mean(x[..., 1:], axis=-1) ** 0.25
    return np.stack([f1, g * (1.0 - (f1 / g) ** 2)], axis=-1)


def dtlz1(x: np.ndarray, num_objectives: int = 2) -> np.ndarray:
    m = num_objectives
    xm = x[..., m - 1 :]
    g = 100.0 * (
        xm.shape[-1]
        + np.sum((xm - 0.5) ** 2 - np.cos(20.0 * np.pi * (xm - 0.5)), axis=-1)
    )
    fs = []
    for i in range(m):
        f = 0.5 * (1.0 + g)
        for j in range(m - 1 - i):
            f = f * x[..., j]
        if i > 0:
            f = f * (1.0 - x[..., m - 1 - i])
        fs.append(f)
    return np.stack(fs, axis=-1)


def dtlz2(x: np.ndarray, num_objectives: int = 2) -> np.ndarray:
    m = num_objectives
    xm = x[..., m - 1 :]
    g = np.sum((xm - 0.5) ** 2, axis=-1)
    fs = []
    for i in range(m):
        f = 1.0 + g
        for j in range(m - 1 - i):
            f = f * np.cos(0.5 * np.pi * x[..., j])
        if i > 0:
            f = f * np.sin(0.5 * np.pi * x[..., m - 1 - i])
        fs.append(f)
    return np.stack(fs, axis=-1)


ZDT_FUNCTIONS: Dict[str, Callable[[np.ndarray], np.ndarray]] = {
    "zdt1": zdt1,
    "zdt2": zdt2,
    "zdt3": zdt3,
    "zdt4": zdt4,
    "zdt6": zdt6,
}


class MultiObjectiveExperimenter(base.Experimenter):
    """Wraps ``f: [N, D] -> [N, M]`` over [0, 1]^D, all objectives MINIMIZE."""

    def __init__(
        self,
        impl: Callable[[np.ndarray], np.ndarray],
        *,
        dimension: int,
        num_objectives: int = 2,
        name: str = "mo",
    ):
        self._impl = impl
        self._num_objectives = num_objectives
        problem = base_study_config.ProblemStatement()
        root = problem.search_space.root
        for i in range(dimension):
            root.add_float_param(f"x{i}", 0.0, 1.0)
        for j in range(num_objectives):
            problem.metric_information.append(
                base_study_config.MetricInformation(
                    name=f"{name}_f{j}", goal=base_study_config.ObjectiveMetricGoal.MINIMIZE
                )
            )
        self._problem = problem
        self._param_names = [p.name for p in problem.search_space.parameters]
        self._metric_names = [m.name for m in problem.metric_information]

    @classmethod
    def zdt(cls, which: str, *, dimension: int = 10) -> "MultiObjectiveExperimenter":
        return cls(ZDT_FUNCTIONS[which], dimension=dimension, name=which)

    @classmethod
    def dtlz(
        cls, which: str, *, dimension: int = 7, num_objectives: int = 2
    ) -> "MultiObjectiveExperimenter":
        impls = {"dtlz1": dtlz1, "dtlz2": dtlz2}
        fn = impls[which]
        return cls(
            lambda x: fn(x, num_objectives),
            dimension=dimension,
            num_objectives=num_objectives,
            name=which,
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        xs = np.asarray(
            [
                [float(t.parameters.get_value(n)) for n in self._param_names]
                for t in suggestions
            ]
        )
        values = np.atleast_2d(self._impl(xs))
        for t, row in zip(suggestions, values):
            t.complete(
                trial_.Measurement(
                    metrics={n: float(v) for n, v in zip(self._metric_names, row)}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem
