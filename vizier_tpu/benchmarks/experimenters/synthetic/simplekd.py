"""SimpleKD: a mixed-type testing objective with a known optimum.

Parity in role with
``/root/reference/vizier/_src/benchmarks/experimenters/synthetic/simplekd.py``:
a smooth objective over one categorical, one discrete, one integer, and k
float parameters, with a known optimum, used by convergence tests to check
that designers actually optimize mixed spaces (not just continuous ones).

MAXIMIZE convention; optimum value is 0.0, attained at
``corner='corner'``, ``discrete=2``, ``int=2``, and every float at the
``best_category``-dependent optimum location.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

_CATEGORIES = ("corner", "center", "mixed")
_DISCRETE = (1.0, 2.0, 5.0)
_INT_RANGE = (1, 4)
_FLOAT_RANGE = (-1.0, 1.0)
# Per-category optimum location of the float block.
_FLOAT_OPT = {"corner": -0.8, "center": 0.0, "mixed": 0.4}


class SimpleKDExperimenter(base.Experimenter):
    """-(loss) objective with a known optimum at value 0."""

    def __init__(self, best_category: str = "corner", *, num_float_params: int = 2):
        if best_category not in _CATEGORIES:
            raise ValueError(f"best_category must be one of {_CATEGORIES}.")
        self._best_category = best_category
        self._num_floats = num_float_params
        problem = base_study_config.ProblemStatement()
        root = problem.search_space.root
        root.add_categorical_param("categorical", list(_CATEGORIES))
        root.add_discrete_param("discrete", list(_DISCRETE))
        root.add_int_param("int", *_INT_RANGE)
        for i in range(num_float_params):
            root.add_float_param(f"float_{i}", *_FLOAT_RANGE)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="value", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        self._problem = problem

    @property
    def optimal_value(self) -> float:
        return 0.0

    def optimal_trial(self) -> trial_.Trial:
        params = {"categorical": self._best_category, "discrete": 2.0, "int": 2}
        for i in range(self._num_floats):
            params[f"float_{i}"] = _FLOAT_OPT[self._best_category]
        return trial_.Trial(parameters=params)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            cat = str(t.parameters.get_value("categorical"))
            disc = float(t.parameters.get_value("discrete"))
            ival = int(t.parameters.get_value("int"))
            floats = np.asarray(
                [float(t.parameters.get_value(f"float_{i}")) for i in range(self._num_floats)]
            )
            loss = 0.0
            if cat != self._best_category:
                loss += 1.0
            loss += 0.5 * (np.log(disc) - np.log(2.0)) ** 2
            loss += 0.3 * (ival - 2) ** 2
            loss += float(np.sum((floats - _FLOAT_OPT[cat]) ** 2))
            t.complete(trial_.Measurement(metrics={"value": -loss}))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem
