"""Classic low-dimensional test objectives: Branin, Hartmann, multi-arm.

Parity with the reference's
``benchmarks/experimenters/synthetic/branin.py:51`` (Branin2DExperimenter),
``synthetic/hartmann.py:34`` (HartmannExperimenter + 3D/6D presets) and
``synthetic/multiarm.py:40,61`` (Bernoulli/Fixed multi-arm bandits), built
on this repo's batched ``NumpyExperimenter``.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

MetricInformation = base_study_config.MetricInformation
ObjectiveMetricGoal = base_study_config.ObjectiveMetricGoal
ProblemStatement = base_study_config.ProblemStatement


def branin(x: np.ndarray) -> np.ndarray:
    """Branin-Hoo function, batched ``[..., 2] -> [...]`` (minimize).

    Global minimum 0.397887 at (-pi, 12.275), (pi, 2.275), (9.42478, 2.475).
    """
    x1, x2 = x[..., 0], x[..., 1]
    b = 5.1 / (4.0 * np.pi**2)
    c = 5.0 / np.pi
    t = 1.0 / (8.0 * np.pi)
    return (x2 - b * x1**2 + c * x1 - 6.0) ** 2 + 10.0 * (1.0 - t) * np.cos(x1) + 10.0


class Branin2DExperimenter(base.NumpyExperimenter):
    """2-D Branin minimization over x1 in [-5, 10], x2 in [0, 15]."""

    def __init__(self):
        problem = ProblemStatement()
        problem.search_space.root.add_float_param("x1", -5.0, 10.0)
        problem.search_space.root.add_float_param("x2", 0.0, 15.0)
        problem.metric_information.append(
            MetricInformation(name="value", goal=ObjectiveMetricGoal.MINIMIZE)
        )
        super().__init__(branin, problem)


# Published Hartmann constants (https://www.sfu.ca/~ssurjano/hart3.html, hart6.html).
_HARTMANN_ALPHA = np.array([1.0, 1.2, 3.0, 3.2])
_HARTMANN3_A = np.array(
    [[3, 10, 30], [0.1, 10, 35], [3, 10, 30], [0.1, 10, 35]], dtype=float
)
_HARTMANN3_P = 1e-4 * np.array(
    [[3689, 1170, 2673], [4699, 4387, 7470], [1091, 8732, 5547], [381, 5743, 8828]],
    dtype=float,
)
_HARTMANN6_A = np.array(
    [
        [10, 3, 17, 3.5, 1.7, 8],
        [0.05, 10, 17, 0.1, 8, 14],
        [3, 3.5, 1.7, 10, 17, 8],
        [17, 8, 0.05, 10, 0.1, 14],
    ],
    dtype=float,
)
_HARTMANN6_P = 1e-4 * np.array(
    [
        [1312, 1696, 5569, 124, 8283, 5886],
        [2329, 4135, 8307, 3736, 1004, 9991],
        [2348, 1451, 3522, 2883, 3047, 6650],
        [4047, 8828, 8732, 5743, 1091, 381],
    ],
    dtype=float,
)


class HartmannExperimenter(base.NumpyExperimenter):
    """Hartmann family minimization over the unit hypercube (batched)."""

    def __init__(self, alpha: np.ndarray, a: np.ndarray, p: np.ndarray):
        alpha = np.asarray(alpha, float)
        a = np.asarray(a, float)
        p = np.asarray(p, float)
        dim = a.shape[-1]

        def impl(x: np.ndarray) -> np.ndarray:
            # x: [N, D]; inner exponent over the 4 Hartmann terms.
            sq = np.sum(a[None] * (x[:, None, :] - p[None]) ** 2, axis=-1)  # [N, 4]
            return -np.exp(-sq) @ alpha

        problem = ProblemStatement()
        for i in range(1, dim + 1):
            problem.search_space.root.add_float_param(f"x{i}", 0.0, 1.0)
        problem.metric_information.append(
            MetricInformation(name="value", goal=ObjectiveMetricGoal.MINIMIZE)
        )
        super().__init__(impl, problem)

    @classmethod
    def from_3d(cls) -> "HartmannExperimenter":
        """3-D Hartmann; minimum -3.86278 at (0.114614, 0.555649, 0.852547)."""
        return cls(_HARTMANN_ALPHA, _HARTMANN3_A, _HARTMANN3_P)

    @classmethod
    def from_6d(cls) -> "HartmannExperimenter":
        """6-D Hartmann; minimum -3.32237."""
        return cls(_HARTMANN_ALPHA, _HARTMANN6_A, _HARTMANN6_P)


def _multiarm_problem(arms: Sequence[str]) -> ProblemStatement:
    problem = ProblemStatement()
    problem.search_space.root.add_categorical_param("arm", feasible_values=list(arms))
    problem.metric_information.append(
        MetricInformation(name="reward", goal=ObjectiveMetricGoal.MAXIMIZE)
    )
    return problem


class BernoulliMultiArmExperimenter(base.Experimenter):
    """1-D categorical bandit: each arm pays 1 with its own probability."""

    def __init__(
        self, arms_to_probs: Mapping[str, float], seed: Optional[int] = None
    ):
        self._arms_to_probs = dict(arms_to_probs)
        self._rng = np.random.default_rng(seed)

    def problem_statement(self) -> ProblemStatement:
        return _multiarm_problem(self._arms_to_probs)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            prob = self._arms_to_probs[str(t.parameters.get_value("arm"))]
            reward = float(self._rng.random() < prob)
            t.complete(trial_.Measurement(metrics={"reward": reward}))


class FixedMultiArmExperimenter(base.Experimenter):
    """1-D categorical bandit with deterministic per-arm rewards."""

    def __init__(self, arms_to_rewards: Mapping[str, float]):
        self._arms_to_rewards = dict(arms_to_rewards)

    def problem_statement(self) -> ProblemStatement:
        return _multiarm_problem(self._arms_to_rewards)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            reward = float(self._arms_to_rewards[str(t.parameters.get_value("arm"))])
            t.complete(trial_.Measurement(metrics={"reward": reward}))
