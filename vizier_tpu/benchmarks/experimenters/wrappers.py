"""Experimenter wrappers: noise, shifting, discretizing, sign-flip, etc.

Parity with the reference wrapper experimenters
(``/root/reference/vizier/_src/benchmarks/experimenters/``: noisy_experimenter,
shifting_experimenter, discretizing_experimenter, normalizing_experimenter,
sign_flip_experimenter, infeasible_experimenter, permuting_experimenter).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class _Wrapper(base.Experimenter):
    def __init__(self, exptr: base.Experimenter):
        self._exptr = exptr

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._exptr.problem_statement()


class NoisyExperimenter(_Wrapper):
    """Adds Gaussian noise to every metric after evaluation."""

    def __init__(
        self,
        exptr: base.Experimenter,
        *,
        noise_std: float = 1.0,
        seed: Optional[int] = None,
    ):
        super().__init__(exptr)
        self._std = noise_std
        self._rng = np.random.default_rng(seed)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            noisy = {
                name: trial_.Metric(m.value + self._rng.normal(0.0, self._std))
                for name, m in t.final_measurement.metrics.items()
            }
            t.final_measurement = trial_.Measurement(
                metrics=noisy,
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )


class ShiftingExperimenter(_Wrapper):
    """Shifts the optimum: evaluates f(x - shift) with clipped bounds."""

    def __init__(self, exptr: base.Experimenter, shift: np.ndarray):
        super().__init__(exptr)
        self._shift = np.asarray(shift, dtype=np.float64)
        self._params = [
            p for p in exptr.problem_statement().search_space.parameters
        ]
        if len(self._shift) != len(self._params):
            raise ValueError(
                f"shift has {len(self._shift)} dims for {len(self._params)} parameters."
            )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        shifted = []
        for t in suggestions:
            params = trial_.ParameterDict()
            for p, s in zip(self._params, self._shift):
                lo, hi = p.bounds
                v = float(t.parameters.get_value(p.name)) - s
                params[p.name] = float(np.clip(v, lo, hi))
            shifted.append(trial_.Trial(id=t.id, parameters=params))
        self._exptr.evaluate(shifted)
        for orig, sh in zip(suggestions, shifted):
            orig.final_measurement = sh.final_measurement
            orig.infeasibility_reason = sh.infeasibility_reason
            orig.completion_time = sh.completion_time


class SignFlipExperimenter(_Wrapper):
    """Negates metrics and flips goals (MINIMIZE ⇄ MAXIMIZE)."""

    def __init__(self, exptr: base.Experimenter):
        super().__init__(exptr)
        original = exptr.problem_statement()
        self._problem = base_study_config.ProblemStatement(
            search_space=original.search_space,
            metric_information=base_study_config.MetricsConfig(
                [m.flip_goal() for m in original.metric_information]
            ),
            metadata=original.metadata,
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            t.final_measurement = trial_.Measurement(
                metrics={
                    name: trial_.Metric(-m.value)
                    for name, m in t.final_measurement.metrics.items()
                },
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


class DiscretizingExperimenter(_Wrapper):
    """Restricts selected DOUBLE parameters to discrete feasible points."""

    def __init__(
        self,
        exptr: base.Experimenter,
        discretization: Dict[str, Sequence[float]],
    ):
        super().__init__(exptr)
        original = exptr.problem_statement()
        space = pc.SearchSpace()
        for p in original.search_space.parameters:
            if p.name in discretization:
                space.root.add_discrete_param(
                    p.name, list(discretization[p.name]), auto_cast=False
                )
            else:
                space.parameters = space.parameters + [p]
        self._problem = base_study_config.ProblemStatement(
            search_space=space,
            metric_information=original.metric_information,
            metadata=original.metadata,
        )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


class NormalizingExperimenter(_Wrapper):
    """Normalizes metrics by |f| statistics sampled on a random grid."""

    def __init__(self, exptr: base.Experimenter, *, num_samples: int = 100, seed: int = 0):
        super().__init__(exptr)
        problem = exptr.problem_statement()
        rng = np.random.default_rng(seed)
        from vizier_tpu.designers import random as random_designer

        probes = []
        for _ in range(num_samples):
            params = random_designer.sample_point(problem.search_space, rng)
            probes.append(trial_.Trial(parameters=params))
        exptr.evaluate(probes)
        names = [m.name for m in problem.metric_information]
        self._scale = {}
        for name in names:
            vals = [
                t.final_measurement.metrics[name].value
                for t in probes
                if t.final_measurement is not None and name in t.final_measurement.metrics
            ]
            std = float(np.std(vals)) if vals else 1.0
            self._scale[name] = std if std > 1e-12 else 1.0

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            t.final_measurement = trial_.Measurement(
                metrics={
                    name: trial_.Metric(m.value / self._scale.get(name, 1.0))
                    for name, m in t.final_measurement.metrics.items()
                },
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )


class InfeasibleExperimenter(_Wrapper):
    """Marks a random fraction of evaluations infeasible."""

    def __init__(
        self, exptr: base.Experimenter, *, infeasible_prob: float = 0.1, seed: int = 0
    ):
        super().__init__(exptr)
        self._prob = infeasible_prob
        self._rng = np.random.default_rng(seed)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if self._rng.uniform() < self._prob:
                t.final_measurement = None
                t.infeasibility_reason = "Randomly infeasible (benchmark wrapper)."
