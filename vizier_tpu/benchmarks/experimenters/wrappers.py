"""Experimenter wrappers: noise, shifting, discretizing, sign-flip, etc.

Parity with the reference wrapper experimenters
(``/root/reference/vizier/_src/benchmarks/experimenters/``: noisy_experimenter,
shifting_experimenter, discretizing_experimenter, normalizing_experimenter,
sign_flip_experimenter, infeasible_experimenter, permuting_experimenter).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class _Wrapper(base.Experimenter):
    def __init__(self, exptr: base.Experimenter):
        self._exptr = exptr

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._exptr.problem_statement()


# BBOB-noisy noise models (Hansen et al., "Real-Parameter Black-Box
# Optimization Benchmarking: Noisy Functions Definitions"). Constants match
# the reference's noise-type zoo (noisy_experimenter.py:74-199) so noise
# robustness experiments reproduce. Names are SEVERITY_FAMILY strings.
_LOGNORMAL_SIGMA = {"MODERATE": 0.01, "SEVERE": 0.1}
_UNIFORM_EXPONENT = {"MODERATE": 0.01, "SEVERE": 0.1}
_CAUCHY_STRENGTH_FREQ = {"MODERATE": (0.01, 0.05), "SEVERE": (0.1, 0.25)}
_ADDITIVE_STDDEV = {"LIGHT": 0.01, "MODERATE": 0.1, "SEVERE": 1.0}

NOISE_TYPES = (
    "NO_NOISE",
    "MODERATE_GAUSSIAN",
    "SEVERE_GAUSSIAN",
    "MODERATE_UNIFORM",
    "SEVERE_UNIFORM",
    "MODERATE_SELDOM_CAUCHY",
    "SEVERE_SELDOM_CAUCHY",
    "LIGHT_ADDITIVE_GAUSSIAN",
    "MODERATE_ADDITIVE_GAUSSIAN",
    "SEVERE_ADDITIVE_GAUSSIAN",
)


def make_noise_fn(
    noise_type: str,
    dimension: int,
    rng: np.random.Generator,
    target_value: float = 1e-8,
):
    """``float -> float`` noise model for one of :data:`NOISE_TYPES`.

    The multiplicative families (gaussian / uniform / seldom-cauchy) are
    stabilized: values below ``target_value`` (near the BBOB optimum) pass
    through unnoised, and noised values get a ``+1.01 * target_value``
    floor offset, per the BBOB-noisy post-processing. Additive-gaussian is
    plain ``v + N(0, σ)`` with no stabilization, matching the reference.
    """
    if noise_type not in NOISE_TYPES:
        raise ValueError(
            f"Unknown noise type {noise_type!r}; choices: {NOISE_TYPES}"
        )
    severity, _, family = noise_type.partition("_")

    if noise_type == "NO_NOISE":
        noise = lambda v: v
    elif family == "GAUSSIAN":
        sigma = _LOGNORMAL_SIGMA[severity]
        noise = lambda v: v * rng.lognormal(0.0, sigma)
    elif family == "UNIFORM":
        # Noise strength grows as the value approaches 0 (the optimum):
        # v · U^max(0,β) · max(1, (1e9 / (v + ε))^(α·U')).
        exponent = _UNIFORM_EXPONENT[severity]
        alpha = exponent * (0.49 + 1.0 / dimension)
        beta = exponent

        def noise(v, alpha=alpha, beta=beta):
            shrink = rng.uniform() ** max(0.0, beta)
            amplify = (1e9 / (v + 1e-99)) ** (alpha * rng.uniform())
            return v * shrink * max(1.0, amplify)

    elif family == "SELDOM_CAUCHY":
        # Infrequent heavy-tailed outliers: with probability p add
        # α · max(0, 1000 + cauchy()).
        strength, freq = _CAUCHY_STRENGTH_FREQ[severity]

        def noise(v, strength=strength, freq=freq):
            c = (rng.uniform() < freq) * rng.standard_cauchy()
            return v + strength * max(0.0, 1000.0 + c)

    else:  # ADDITIVE_GAUSSIAN, the only remaining family in NOISE_TYPES
        stddev = _ADDITIVE_STDDEV[severity]
        return lambda v: v + rng.normal(0.0, stddev)

    def stabilized(v):
        if v < target_value:
            return v
        return noise(v) + 1.01 * target_value

    return stabilized


class NoisyExperimenter(_Wrapper):
    """Applies a noise model to every metric after evaluation.

    The unnoised value is preserved as ``<metric>_before_noise`` (reference
    ``noisy_experimenter.py:60-69``). The default constructor is additive
    Gaussian with ``noise_std``; :meth:`from_type` builds the BBOB-noisy
    model zoo (uniform / seldom-cauchy / multiplicative-gaussian families).
    """

    def __init__(
        self,
        exptr: base.Experimenter,
        *,
        noise_std: float = 1.0,
        seed: Optional[int] = None,
        noise_fn=None,
    ):
        super().__init__(exptr)
        self._rng = np.random.default_rng(seed)
        if noise_fn is None:
            std = noise_std
            noise_fn = lambda v: v + self._rng.normal(0.0, std)
        self._noise_fn = noise_fn

    @classmethod
    def from_type(
        cls,
        exptr: base.Experimenter,
        noise_type: str,
        seed: Optional[int] = None,
    ) -> "NoisyExperimenter":
        """Builds the named BBOB-noisy model (reference ``from_type``).

        ``seed=None`` defaults to 0, matching the reference's
        ``np.random.default_rng(seed or 0)`` — default runs must be
        reproducible, not OS-entropy seeded.
        """
        dim = len(exptr.problem_statement().search_space.parameters)
        self = cls(exptr, seed=seed or 0)
        self._noise_fn = make_noise_fn(noise_type, dimension=dim, rng=self._rng)
        return self

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            noisy: Dict[str, trial_.Metric] = {}
            for name, m in t.final_measurement.metrics.items():
                noisy[name] = trial_.Metric(float(self._noise_fn(m.value)))
                noisy[name + "_before_noise"] = m
            t.final_measurement = trial_.Measurement(
                metrics=noisy,
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )


class ShiftingExperimenter(_Wrapper):
    """Shifts the optimum: evaluates f(x - shift) with clipped bounds."""

    def __init__(self, exptr: base.Experimenter, shift: np.ndarray):
        super().__init__(exptr)
        self._shift = np.asarray(shift, dtype=np.float64)
        self._params = [
            p for p in exptr.problem_statement().search_space.parameters
        ]
        if len(self._shift) != len(self._params):
            raise ValueError(
                f"shift has {len(self._shift)} dims for {len(self._params)} parameters."
            )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        shifted = []
        for t in suggestions:
            params = trial_.ParameterDict()
            for p, s in zip(self._params, self._shift):
                lo, hi = p.bounds
                v = float(t.parameters.get_value(p.name)) - s
                params[p.name] = float(np.clip(v, lo, hi))
            shifted.append(trial_.Trial(id=t.id, parameters=params))
        self._exptr.evaluate(shifted)
        for orig, sh in zip(suggestions, shifted):
            orig.final_measurement = sh.final_measurement
            orig.infeasibility_reason = sh.infeasibility_reason
            orig.completion_time = sh.completion_time


class SignFlipExperimenter(_Wrapper):
    """Negates metrics and flips goals (MINIMIZE ⇄ MAXIMIZE)."""

    def __init__(self, exptr: base.Experimenter):
        super().__init__(exptr)
        original = exptr.problem_statement()
        self._problem = base_study_config.ProblemStatement(
            search_space=original.search_space,
            metric_information=base_study_config.MetricsConfig(
                [m.flip_goal() for m in original.metric_information]
            ),
            metadata=original.metadata,
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            t.final_measurement = trial_.Measurement(
                metrics={
                    name: trial_.Metric(-m.value)
                    for name, m in t.final_measurement.metrics.items()
                },
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


class DiscretizingExperimenter(_Wrapper):
    """Restricts selected DOUBLE parameters to discrete feasible points."""

    def __init__(
        self,
        exptr: base.Experimenter,
        discretization: Dict[str, Sequence[float]],
    ):
        super().__init__(exptr)
        original = exptr.problem_statement()
        space = pc.SearchSpace()
        for p in original.search_space.parameters:
            if p.name in discretization:
                space.root.add_discrete_param(
                    p.name, list(discretization[p.name]), auto_cast=False
                )
            else:
                space.parameters = space.parameters + [p]
        self._problem = base_study_config.ProblemStatement(
            search_space=space,
            metric_information=original.metric_information,
            metadata=original.metadata,
        )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


class NormalizingExperimenter(_Wrapper):
    """Normalizes metrics by |f| statistics sampled on a random grid."""

    def __init__(self, exptr: base.Experimenter, *, num_samples: int = 100, seed: int = 0):
        super().__init__(exptr)
        problem = exptr.problem_statement()
        rng = np.random.default_rng(seed)
        from vizier_tpu.designers import random as random_designer

        probes = []
        for _ in range(num_samples):
            params = random_designer.sample_point(problem.search_space, rng)
            probes.append(trial_.Trial(parameters=params))
        exptr.evaluate(probes)
        names = [m.name for m in problem.metric_information]
        self._scale = {}
        for name in names:
            vals = [
                t.final_measurement.metrics[name].value
                for t in probes
                if t.final_measurement is not None and name in t.final_measurement.metrics
            ]
            std = float(np.std(vals)) if vals else 1.0
            self._scale[name] = std if std > 1e-12 else 1.0

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if t.final_measurement is None:
                continue
            t.final_measurement = trial_.Measurement(
                metrics={
                    name: trial_.Metric(m.value / self._scale.get(name, 1.0))
                    for name, m in t.final_measurement.metrics.items()
                },
                elapsed_secs=t.final_measurement.elapsed_secs,
                steps=t.final_measurement.steps,
            )


class InfeasibleExperimenter(_Wrapper):
    """Marks a random fraction of evaluations infeasible."""

    def __init__(
        self, exptr: base.Experimenter, *, infeasible_prob: float = 0.1, seed: int = 0
    ):
        super().__init__(exptr)
        self._prob = infeasible_prob
        self._rng = np.random.default_rng(seed)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        self._exptr.evaluate(suggestions)
        for t in suggestions:
            if self._rng.uniform() < self._prob:
                t.final_measurement = None
                t.infeasibility_reason = "Randomly infeasible (benchmark wrapper)."


class SparseExperimenter(_Wrapper):
    """Expands the search space with placeholder parameters that do nothing.

    Reference ``SparseExperimenter``: tests that a designer can optimize when
    only a subset of the parameters affect the objective. The added ("sparse")
    parameters are copies of ``extra_space``'s parameters, renamed with
    ``prefix``; evaluation strips them before delegating.
    """

    def __init__(
        self,
        exptr: base.Experimenter,
        extra_space: "pc.SearchSpace",
        *,
        prefix: str = "_SPARSE",
    ):
        super().__init__(exptr)
        self._prefix = prefix
        inner = exptr.problem_statement()
        self._inner_names = set(inner.search_space.parameter_names())
        self._problem = copy.deepcopy(inner)
        for cfg in extra_space.parameters:
            name = f"{prefix}_{cfg.name}"
            if name in self._inner_names:
                raise ValueError(f"Sparse parameter {name!r} collides.")
            self._problem.search_space.root.add(
                dataclasses.replace(cfg, name=name)
            )

    @classmethod
    def create_default(
        cls,
        exptr: base.Experimenter,
        num_float: int = 0,
        num_int: int = 0,
        num_discrete: int = 0,
        num_categorical: int = 0,
        *,
        prefix: str = "_SPARSE",
    ) -> "SparseExperimenter":
        """Convenience: N placeholder params of each type with default domains."""
        space = pc.SearchSpace()
        for i in range(num_float):
            space.root.add_float_param(f"float{i}", -5.0, 5.0)
        for i in range(num_int):
            space.root.add_int_param(f"int{i}", -5, 5)
        for i in range(num_discrete):
            space.root.add_discrete_param(f"discrete{i}", [0, 1, 2, 3, 4])
        for i in range(num_categorical):
            space.root.add_categorical_param(
                f"categorical{i}", ["a", "b", "c", "d", "e", "f"]
            )
        return cls(exptr, space, prefix=prefix)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        stripped = []
        for t in suggestions:
            s = trial_.Trial(
                id=t.id,
                parameters={
                    k: v.value
                    for k, v in t.parameters.items()
                    if k in self._inner_names
                },
            )
            stripped.append(s)
        self._exptr.evaluate(stripped)
        for t, s in zip(suggestions, stripped):
            if s.final_measurement is not None:
                t.complete(s.final_measurement)
            else:
                t.complete(
                    infeasibility_reason=s.infeasibility_reason
                    or "Inner experimenter returned no measurement."
                )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


class PermutingExperimenter(_Wrapper):
    """Permutes chosen discrete/categorical parameter values before evaluation.

    Reference ``PermutingExperimenter``: breaks any accidental ordinal
    structure of categorical values, so designers that (wrongly) assume
    category order degrade while order-agnostic ones do not.
    """

    def __init__(
        self,
        exptr: base.Experimenter,
        parameters_to_permute: Sequence[str],
        seed: Optional[int] = None,
    ):
        super().__init__(exptr)
        problem = exptr.problem_statement()
        if problem.search_space.is_conditional:
            raise ValueError("PermutingExperimenter requires a flat space.")
        rng = np.random.default_rng(seed)
        self._maps: Dict[str, Dict] = {}
        for name in parameters_to_permute:
            cfg = problem.search_space.get(name)
            if cfg.type == pc.ParameterType.DOUBLE:
                raise ValueError(
                    f"Parameter {name!r} is continuous; only finite-domain "
                    "parameters can be permuted."
                )
            values = list(cfg.feasible_values)
            permuted = list(rng.permutation(np.asarray(values, dtype=object)))
            self._maps[name] = dict(zip(values, permuted))

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        permuted = []
        for t in suggestions:
            s = copy.deepcopy(t)
            for name, mapping in self._maps.items():
                if name in s.parameters:
                    raw = s.parameters[name].value
                    key = type(next(iter(mapping)))(raw)
                    s.parameters[name] = mapping[key]
            permuted.append(s)
        self._exptr.evaluate(permuted)
        for t, s in zip(suggestions, permuted):
            if s.final_measurement is not None:
                t.complete(s.final_measurement)
            else:
                t.complete(
                    infeasibility_reason=s.infeasibility_reason
                    or "Inner experimenter returned no measurement."
                )


class SwitchExperimenter(base.Experimenter):
    """Conditional-space benchmark: a switch selects one sub-experimenter.

    Reference ``SwitchExperimenter``: the root ``switch`` parameter activates
    the selected experimenter's parameters as conditional children; the
    objective is relayed under one common metric name. This is the
    tree-structured (NAS-style) search-space testbed for conditional-capable
    designers (grid/random/quasi-random).
    """

    def __init__(
        self,
        experimenters: Sequence[base.Experimenter],
        *,
        switch_param_name: str = "switch",
        metric_name: str = "switch_metric",
    ):
        if not experimenters:
            raise ValueError("Need at least one experimenter.")
        self._experimenters = list(experimenters)
        self._switch = switch_param_name
        self._metric = metric_name
        self._problems = [e.problem_statement() for e in self._experimenters]
        self._objectives = [
            p.metric_information.item().name for p in self._problems
        ]
        goals = {p.metric_information.item().goal for p in self._problems}
        if len(goals) > 1:
            # Relaying raw values under one goal would silently invert the
            # benchmark for sub-experimenters with the other goal.
            raise ValueError(
                f"All sub-experimenters must share one optimization goal; "
                f"got {sorted(g.name for g in goals)}."
            )
        goal = self._problems[0].metric_information.item().goal
        self._problem = base_study_config.ProblemStatement()
        selector = self._problem.search_space.root.add_categorical_param(
            self._switch, [str(i) for i in range(len(self._experimenters))]
        )
        for i, p in enumerate(self._problems):
            child = selector.select_values([str(i)])
            for cfg in p.search_space.parameters:
                child.add(copy.deepcopy(cfg))
        self._problem.metric_information.append(
            base_study_config.MetricInformation(name=self._metric, goal=goal)
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            idx = int(str(t.parameters[self._switch].value))
            sub = copy.deepcopy(t)
            del sub.parameters[self._switch]
            self._experimenters[idx].evaluate([sub])
            if sub.final_measurement is None:
                t.complete(
                    infeasibility_reason=sub.infeasibility_reason
                    or "Sub-experimenter returned no measurement."
                )
                continue
            value = sub.final_measurement.metrics[self._objectives[idx]].value
            t.complete(trial_.Measurement(metrics={self._metric: value}))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)
