"""Benchmark experimenters: base protocol, synthetic suites, wrappers,
combinatorial (COMBO) problems, and data-backed surrogate handlers."""

from vizier_tpu.benchmarks.experimenters.base import Experimenter, NumpyExperimenter
from vizier_tpu.benchmarks.experimenters.combinatorial import (
    CentroidExperimenter,
    ContaminationExperimenter,
    IsingExperimenter,
    L1CategoricalExperimenter,
    MAXSATExperimenter,
    PestControlExperimenter,
)
from vizier_tpu.benchmarks.experimenters.nasbench101 import (
    NASBench101Experimenter,
    TabularNASBench101,
)
from vizier_tpu.benchmarks.experimenters.surrogates import (
    Atari100kExperimenter,
    Atari100kHandler,
    HPOBHandler,
    NASBench201Handler,
    PredictorExperimenter,
    TabularSurrogateExperimenter,
)
from vizier_tpu.benchmarks.experimenters.synthetic.classic import (
    BernoulliMultiArmExperimenter,
    Branin2DExperimenter,
    FixedMultiArmExperimenter,
    HartmannExperimenter,
)
from vizier_tpu.benchmarks.experimenters.wrappers import (
    DiscretizingExperimenter,
    InfeasibleExperimenter,
    NoisyExperimenter,
    NormalizingExperimenter,
    PermutingExperimenter,
    ShiftingExperimenter,
    SignFlipExperimenter,
    SparseExperimenter,
    SwitchExperimenter,
)
