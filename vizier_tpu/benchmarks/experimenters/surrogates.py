"""Surrogate-based benchmark experimenters (HPO-B, NASBench, COMBO).

Parity in role with the reference's data-backed experimenters
(``hpob/handler.py``, ``nasbench101/201``, ``combo``): those require large
external datasets not bundled in this image. This module ships the handler
structure plus a generic ``TabularSurrogateExperimenter`` that serves any
(configs, objectives) table — load HPO-B/NASBench dumps into it when the
data is available; construction without data raises a clear error.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class TabularSurrogateExperimenter(base.Experimenter):
    """Nearest-neighbor lookup over a finite table of evaluated configs.

    ``rows``: list of {param_name: value}; ``objectives``: [N] values.
    Evaluation snaps a suggestion to the nearest tabulated config: exact
    match REQUIRED for categoricals (no tabulated row with the suggested
    categorical combination ⇒ infeasible), nearest scaled L2 for numerics —
    the standard way NAS/HPO tabular benchmarks are served.
    """

    def __init__(
        self,
        problem: base_study_config.ProblemStatement,
        rows: Sequence[Dict],
        objectives: Sequence[float],
        *,
        metric_name: Optional[str] = None,
    ):
        if len(rows) != len(objectives):
            raise ValueError("rows and objectives must align.")
        if not rows:
            raise ValueError("Empty surrogate table.")
        self._problem = problem
        self._metric = metric_name or problem.metric_information.item().name
        self._objectives = np.asarray(objectives, dtype=np.float64)
        from vizier_tpu.converters import core as converters

        self._enc = converters.SearchSpaceEncoder(problem.search_space)
        table_trials = [trial_.Trial(id=i + 1, parameters=r) for i, r in enumerate(rows)]
        self._table_cont, self._table_cat = self._enc.encode(table_trials)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        cont, cat = self._enc.encode(suggestions)
        # Continuous distance via the |a|²-2ab+|b|² expansion (no [M,N,D]
        # intermediate); categorical mismatches are disqualifying.
        a2 = np.sum(cont**2, axis=1, keepdims=True)
        b2 = np.sum(self._table_cont**2, axis=1, keepdims=True).T
        d = np.maximum(a2 + b2 - 2.0 * cont @ self._table_cont.T, 0.0)
        if self._enc.num_categorical:
            mismatch = (cat[:, None, :] != self._table_cat[None, :, :]).any(axis=-1)
            d = np.where(mismatch, np.inf, d)
        nearest = d.argmin(axis=1)
        for t, idx, row in zip(suggestions, nearest, d):
            if not np.isfinite(row[idx]):
                t.complete(
                    infeasibility_reason="No tabulated config with this "
                    "categorical combination."
                )
                continue
            t.complete(
                trial_.Measurement(
                    metrics={self._metric: float(self._objectives[idx])}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


def _require_file(path: Optional[str], what: str) -> str:
    if not path or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what} data not found at {path!r}. Download the dataset and pass "
            "its path; this image bundles no benchmark data."
        )
    return path


class HPOBHandler:
    """HPO-B v3 benchmark handler (parity with ``hpob/handler.py:35``).

    Reads the real HPO-B layout — ``meta-train-dataset.json`` (plus the
    ``-augmented`` variant), ``meta-validation-dataset.json``,
    ``meta-test-dataset.json``, and ``bo-initializations.json`` under one
    root — with the reference's mode semantics:

    - ``v3-test``: only the meta-test split (the evaluation protocol split).
    - ``v3-train-augmented``: all splits, augmented meta-train.
    - ``v1`` / ``v2`` / ``v3``: all splits; v1 uses the augmented train
      dump; v1/v2 merge every split into one table per search space.

    ``evaluate`` runs the benchmark's own discrete protocol: 5 tabulated
    initial points chosen by the published ``bo-initializations`` ids, then
    ``n_trials`` rounds of the method's ``observe_and_suggest(X_obs, y_obs,
    X_pen) -> index`` over the remaining tabulated candidates, returning
    the normalized incumbent trace. ``evaluate_continuous`` (XGBoost
    surrogates, reference ``handler.py:232``) is gated on xgboost being
    importable. Loading is lazy so constructing a handler without data is
    cheap; the first data access raises ``FileNotFoundError``.
    """

    SEEDS = ("test0", "test1", "test2", "test3", "test4")
    MODES = ("v1", "v2", "v3", "v3-test", "v3-train-augmented")
    N_INITIAL_EVALUATIONS = 5

    def __init__(
        self,
        root_dir: Optional[str] = None,
        mode: str = "v3-test",
        surrogates_dir: Optional[str] = None,
    ):
        """``surrogates_dir`` mirrors the reference signature for the
        continuous protocol's saved XGBoost surrogates; serving them is NOT
        implemented (xgboost is absent from this image), so it is stored
        for forward compatibility only — ``evaluate_continuous`` raises."""
        if mode not in self.MODES:
            raise ValueError(
                f"Unknown HPO-B mode {mode!r}; choices: {list(self.MODES)}"
            )
        self.root_dir = root_dir
        self.mode = mode
        self.surrogates_dir = surrogates_dir
        self.seeds = list(self.SEEDS)
        self._loaded = False
        self.meta_train_data: Dict = {}
        self.meta_validation_data: Dict = {}
        self.meta_test_data: Dict = {}
        self.bo_initializations: Dict = {}

    # -- data loading -------------------------------------------------------

    def _read(self, filename: str) -> Dict:
        path = _require_file(
            self.root_dir and os.path.join(self.root_dir, filename), "HPO-B"
        )
        with open(path) as f:
            return json.load(f)

    def load_data(
        self,
        rootdir: Optional[str] = None,
        version: str = "v3",
        only_test: bool = True,
        augmented_train: bool = False,
    ) -> None:
        """Loads the dumps with the reference's exact split semantics."""
        if rootdir is not None:
            self.root_dir = rootdir
        self.meta_test_data = self._read("meta-test-dataset.json")
        self.bo_initializations = self._read("bo-initializations.json")
        self.meta_train_data = {}
        self.meta_validation_data = {}
        if not only_test:
            train_file = (
                "meta-train-dataset-augmented.json"
                if (augmented_train or version == "v1")
                else "meta-train-dataset.json"
            )
            self.meta_train_data = self._read(train_file)
            self.meta_validation_data = self._read(
                "meta-validation-dataset.json"
            )
        if version in ("v1", "v2"):
            # Older versions evaluate on the union of all splits.
            merged: Dict = {}
            for ss, datasets in self.meta_train_data.items():
                merged[ss] = dict(datasets)
                if ss in self.meta_test_data:
                    merged[ss].update(self.meta_test_data[ss])
                    merged[ss].update(self.meta_validation_data.get(ss, {}))
            self.meta_test_data = merged
        self._loaded = True

    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        if self.mode == "v3-test":
            self.load_data(only_test=True)
        elif self.mode == "v3-train-augmented":
            self.load_data(only_test=False, augmented_train=True)
        else:  # v1 | v2 | v3
            self.load_data(version=self.mode, only_test=False)

    # -- protocol -----------------------------------------------------------

    def get_seeds(self) -> List[str]:
        return list(self.seeds)

    @staticmethod
    def normalize(y, y_min=None, y_max=None):
        y = np.asarray(y, dtype=np.float64)
        if y_min is None:
            return (y - np.min(y)) / (np.max(y) - np.min(y))
        return (y - y_min) / (y_max - y_min)

    def evaluate(
        self,
        bo_method=None,
        search_space_id: Optional[str] = None,
        dataset_id: Optional[str] = None,
        seed: Optional[str] = None,
        n_trials: int = 10,
    ) -> List[float]:
        """Discrete protocol: incumbent trace over tabulated candidates."""
        if bo_method is None or not hasattr(bo_method, "observe_and_suggest"):
            raise ValueError(
                "bo_method must define observe_and_suggest(X_obs, y_obs, "
                "X_pen) -> pending index."
            )
        if search_space_id is None or dataset_id is None or seed is None:
            raise ValueError("search_space_id, dataset_id and seed are required.")
        self._ensure_loaded()
        entry = self.meta_test_data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = self.normalize(np.asarray(entry["y"], dtype=np.float64).reshape(-1))
        pending = list(range(len(xs)))
        current: List[int] = []
        init_ids = self.bo_initializations[search_space_id][dataset_id][seed]
        for i in range(self.N_INITIAL_EVALUATIONS):
            idx = init_ids[i]
            pending.remove(idx)
            current.append(idx)
        history = [float(np.max(ys[current]))]
        for _ in range(n_trials):
            pick = bo_method.observe_and_suggest(
                xs[current], ys[current], xs[pending]
            )
            idx = pending[int(pick)]
            pending.remove(idx)
            current.append(idx)
            history.append(float(np.max(ys[current])))
        return history

    def evaluate_continuous(
        self,
        bo_method=None,
        search_space_id: Optional[str] = None,
        dataset_id: Optional[str] = None,
        seed: Optional[str] = None,
        n_trials: int = 10,
    ) -> List[float]:
        """Continuous protocol against the published XGBoost surrogates.

        NOT implemented: raises ImportError without xgboost, else
        NotImplementedError (the surrogate-serving wiring needs both the
        package and the saved-surrogates dump)."""
        try:
            import xgboost as xgb  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "evaluate_continuous needs the xgboost package (absent from "
                "this image) to serve the published HPO-B surrogate models; "
                "use the discrete evaluate() protocol instead."
            ) from e
        raise NotImplementedError(
            "XGBoost surrogate serving requires the saved-surrogates dump; "
            "wire surrogates_dir when both xgboost and the data exist."
        )

    # -- experimenter bridge ------------------------------------------------

    def make_experimenter(
        self, search_space_id: str, dataset_id: str
    ) -> base.Experimenter:
        """Serves one (search space, dataset) table as an Experimenter."""
        self._ensure_loaded()
        entry = self.meta_test_data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = np.asarray(entry["y"], dtype=np.float64).reshape(-1)
        problem = base_study_config.ProblemStatement()
        for j in range(xs.shape[1]):
            problem.search_space.root.add_float_param(f"x{j}", 0.0, 1.0)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="objective", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        rows = [{f"x{j}": float(v) for j, v in enumerate(row)} for row in xs]
        return TabularSurrogateExperimenter(problem, rows, ys)


@dataclasses.dataclass
class NASBench201Handler:
    """NASBench-201 handler: 6 categorical ops cells → accuracy table."""

    OPS = ("none", "skip_connect", "nor_conv_1x1", "nor_conv_3x3", "avg_pool_3x3")

    data_path: Optional[str] = None

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for i in range(6):
            problem.search_space.root.add_categorical_param(f"op{i}", list(self.OPS))
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="accuracy", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        path = _require_file(self.data_path, "NASBench-201")
        with open(path) as f:
            table = json.load(f)  # [{"op0": ..., ..., "accuracy": ...}, ...]
        rows = [{k: v for k, v in row.items() if k != "accuracy"} for row in table]
        ys = [row["accuracy"] for row in table]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, ys, metric_name="accuracy"
        )

    def make_synthetic_experimenter(
        self, *, num_rows: Optional[int] = None, seed: int = 0
    ) -> base.Experimenter:
        """NASBench-201-STYLE surrogate over a synthetic accuracy table.

        Not real NASBench data (none is bundled in this image): a
        deterministic structured objective over the same 6-op categorical
        cell space — op quality + pairwise interactions — so the full
        tabular-benchmark pipeline (suggest → snap-to-table → accuracy)
        runs end to end without the dataset.

        Like the real NASBench-201, EVERY architecture is tabulated (all
        5^6 = 15625 cells) by default, so no suggestion can fall outside
        the table; ``num_rows`` subsamples for cheap tests (off-table
        suggestions then complete infeasible, the exact-match contract).
        """
        rng = np.random.default_rng(seed)
        n_ops = len(self.OPS)
        quality = rng.normal(size=(6, n_ops))
        pair = rng.normal(scale=0.3, size=(6, 6, n_ops, n_ops))
        all_idx = np.stack(
            np.meshgrid(*[np.arange(n_ops)] * 6, indexing="ij"), axis=-1
        ).reshape(-1, 6)  # [5^6, 6]
        score = quality[np.arange(6)[None, :], all_idx].sum(axis=1)
        for i in range(6):
            for j in range(i + 1, 6):
                score = score + pair[i, j, all_idx[:, i], all_idx[:, j]]
        accs = 100.0 / (1.0 + np.exp(-score / 4.0))  # accuracy-like range
        if num_rows is not None and num_rows < len(all_idx):
            keep = rng.choice(len(all_idx), size=num_rows, replace=False)
            all_idx, accs = all_idx[keep], accs[keep]
        rows: List[Dict] = [
            {f"op{i}": self.OPS[idx[i]] for i in range(6)} for idx in all_idx
        ]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, [float(a) for a in accs],
            metric_name="accuracy",
        )


@dataclasses.dataclass
class Atari100kHandler:
    """Atari-100k RL-tuning surrogate handler (reference ``atari100k``).

    Expects a json table of {hyperparam columns..., "score": float} records
    for one game; data is not bundled — pass the dump's path.
    """

    data_path: Optional[str] = None
    # The Atari100k search space of the reference experimenter.
    _FLOATS = (
        ("learning_rate", 1e-5, 1e-2, pc.ScaleType.LOG),
        ("epsilon", 1e-8, 1e-3, pc.ScaleType.LOG),
    )
    _INTS = (("n_steps", 1, 20), ("update_horizon", 1, 20))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for name, lo, hi, scale in self._FLOATS:
            problem.search_space.root.add_float_param(name, lo, hi, scale_type=scale)
        for name, lo, hi in self._INTS:
            problem.search_space.root.add_int_param(name, lo, hi)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="score", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        path = _require_file(self.data_path, "Atari100k")
        with open(path) as f:
            table = json.load(f)
        rows = [{k: v for k, v in row.items() if k != "score"} for row in table]
        ys = [row["score"] for row in table]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, ys, metric_name="score"
        )
