"""Surrogate-based benchmark experimenters (HPO-B, NASBench, COMBO).

Parity in role with the reference's data-backed experimenters
(``hpob/handler.py``, ``nasbench101/201``, ``combo``): those require large
external datasets not bundled in this image. This module ships the handler
structure plus a generic ``TabularSurrogateExperimenter`` that serves any
(configs, objectives) table — load HPO-B/NASBench dumps into it when the
data is available; construction without data raises a clear error.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class TabularSurrogateExperimenter(base.Experimenter):
    """Nearest-neighbor lookup over a finite table of evaluated configs.

    ``rows``: list of {param_name: value}; ``objectives``: [N] values.
    Evaluation snaps a suggestion to the nearest tabulated config: exact
    match REQUIRED for categoricals (no tabulated row with the suggested
    categorical combination ⇒ infeasible), nearest scaled L2 for numerics —
    the standard way NAS/HPO tabular benchmarks are served.
    """

    def __init__(
        self,
        problem: base_study_config.ProblemStatement,
        rows: Sequence[Dict],
        objectives: Sequence[float],
        *,
        metric_name: Optional[str] = None,
    ):
        if len(rows) != len(objectives):
            raise ValueError("rows and objectives must align.")
        if not rows:
            raise ValueError("Empty surrogate table.")
        self._problem = problem
        self._metric = metric_name or problem.metric_information.item().name
        self._objectives = np.asarray(objectives, dtype=np.float64)
        from vizier_tpu.converters import core as converters

        self._enc = converters.SearchSpaceEncoder(problem.search_space)
        table_trials = [trial_.Trial(id=i + 1, parameters=r) for i, r in enumerate(rows)]
        self._table_cont, self._table_cat = self._enc.encode(table_trials)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        cont, cat = self._enc.encode(suggestions)
        # Continuous distance via the |a|²-2ab+|b|² expansion (no [M,N,D]
        # intermediate); categorical mismatches are disqualifying.
        a2 = np.sum(cont**2, axis=1, keepdims=True)
        b2 = np.sum(self._table_cont**2, axis=1, keepdims=True).T
        d = np.maximum(a2 + b2 - 2.0 * cont @ self._table_cont.T, 0.0)
        if self._enc.num_categorical:
            mismatch = (cat[:, None, :] != self._table_cat[None, :, :]).any(axis=-1)
            d = np.where(mismatch, np.inf, d)
        nearest = d.argmin(axis=1)
        for t, idx, row in zip(suggestions, nearest, d):
            if not np.isfinite(row[idx]):
                t.complete(
                    infeasibility_reason="No tabulated config with this "
                    "categorical combination."
                )
                continue
            t.complete(
                trial_.Measurement(
                    metrics={self._metric: float(self._objectives[idx])}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


def _require_file(path: Optional[str], what: str) -> str:
    if not path or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what} data not found at {path!r}. Download the dataset and pass "
            "its path; this image bundles no benchmark data."
        )
    return path


@dataclasses.dataclass
class HPOBHandler:
    """HPO-B benchmark handler (parity with ``hpob/handler.py``).

    Expects the public HPO-B json dumps; builds a
    ``TabularSurrogateExperimenter`` per (search_space_id, dataset_id).
    """

    root_dir: Optional[str] = None
    mode: str = "v3-test"

    # Public HPO-B dump filenames by mode (the dataset ships these names).
    _MODE_FILES = {
        "v3-test": "meta-test-dataset.json",
        "v3-train": "meta-train-dataset.json",
        "v3-validation": "meta-validation-dataset.json",
    }

    def make_experimenter(
        self, search_space_id: str, dataset_id: str
    ) -> base.Experimenter:
        filename = self._MODE_FILES.get(self.mode)
        if filename is None:
            raise ValueError(
                f"Unknown HPO-B mode {self.mode!r}; choices: {sorted(self._MODE_FILES)}"
            )
        path = _require_file(
            self.root_dir and os.path.join(self.root_dir, filename), "HPO-B"
        )
        with open(path) as f:
            data = json.load(f)
        entry = data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = np.asarray(entry["y"], dtype=np.float64).reshape(-1)
        problem = base_study_config.ProblemStatement()
        for j in range(xs.shape[1]):
            problem.search_space.root.add_float_param(f"x{j}", 0.0, 1.0)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="objective", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        rows = [{f"x{j}": float(v) for j, v in enumerate(row)} for row in xs]
        return TabularSurrogateExperimenter(problem, rows, ys)


@dataclasses.dataclass
class NASBench201Handler:
    """NASBench-201 handler: 6 categorical ops cells → accuracy table."""

    OPS = ("none", "skip_connect", "nor_conv_1x1", "nor_conv_3x3", "avg_pool_3x3")

    data_path: Optional[str] = None

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for i in range(6):
            problem.search_space.root.add_categorical_param(f"op{i}", list(self.OPS))
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="accuracy", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        path = _require_file(self.data_path, "NASBench-201")
        with open(path) as f:
            table = json.load(f)  # [{"op0": ..., ..., "accuracy": ...}, ...]
        rows = [{k: v for k, v in row.items() if k != "accuracy"} for row in table]
        ys = [row["accuracy"] for row in table]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, ys, metric_name="accuracy"
        )

    def make_synthetic_experimenter(
        self, *, num_rows: Optional[int] = None, seed: int = 0
    ) -> base.Experimenter:
        """NASBench-201-STYLE surrogate over a synthetic accuracy table.

        Not real NASBench data (none is bundled in this image): a
        deterministic structured objective over the same 6-op categorical
        cell space — op quality + pairwise interactions — so the full
        tabular-benchmark pipeline (suggest → snap-to-table → accuracy)
        runs end to end without the dataset.

        Like the real NASBench-201, EVERY architecture is tabulated (all
        5^6 = 15625 cells) by default, so no suggestion can fall outside
        the table; ``num_rows`` subsamples for cheap tests (off-table
        suggestions then complete infeasible, the exact-match contract).
        """
        rng = np.random.default_rng(seed)
        n_ops = len(self.OPS)
        quality = rng.normal(size=(6, n_ops))
        pair = rng.normal(scale=0.3, size=(6, 6, n_ops, n_ops))
        all_idx = np.stack(
            np.meshgrid(*[np.arange(n_ops)] * 6, indexing="ij"), axis=-1
        ).reshape(-1, 6)  # [5^6, 6]
        score = quality[np.arange(6)[None, :], all_idx].sum(axis=1)
        for i in range(6):
            for j in range(i + 1, 6):
                score = score + pair[i, j, all_idx[:, i], all_idx[:, j]]
        accs = 100.0 / (1.0 + np.exp(-score / 4.0))  # accuracy-like range
        if num_rows is not None and num_rows < len(all_idx):
            keep = rng.choice(len(all_idx), size=num_rows, replace=False)
            all_idx, accs = all_idx[keep], accs[keep]
        rows: List[Dict] = [
            {f"op{i}": self.OPS[idx[i]] for i in range(6)} for idx in all_idx
        ]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, [float(a) for a in accs],
            metric_name="accuracy",
        )


@dataclasses.dataclass
class Atari100kHandler:
    """Atari-100k RL-tuning surrogate handler (reference ``atari100k``).

    Expects a json table of {hyperparam columns..., "score": float} records
    for one game; data is not bundled — pass the dump's path.
    """

    data_path: Optional[str] = None
    # The Atari100k search space of the reference experimenter.
    _FLOATS = (
        ("learning_rate", 1e-5, 1e-2, pc.ScaleType.LOG),
        ("epsilon", 1e-8, 1e-3, pc.ScaleType.LOG),
    )
    _INTS = (("n_steps", 1, 20), ("update_horizon", 1, 20))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for name, lo, hi, scale in self._FLOATS:
            problem.search_space.root.add_float_param(name, lo, hi, scale_type=scale)
        for name, lo, hi in self._INTS:
            problem.search_space.root.add_int_param(name, lo, hi)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="score", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        path = _require_file(self.data_path, "Atari100k")
        with open(path) as f:
            table = json.load(f)
        rows = [{k: v for k, v in row.items() if k != "score"} for row in table]
        ys = [row["score"] for row in table]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, ys, metric_name="score"
        )
