"""Surrogate-based benchmark experimenters (HPO-B, NASBench, COMBO).

Parity in role with the reference's data-backed experimenters
(``hpob/handler.py``, ``nasbench101/201``, ``combo``): those require large
external datasets not bundled in this image. This module ships the handler
structure plus a generic ``TabularSurrogateExperimenter`` that serves any
(configs, objectives) table — load HPO-B/NASBench dumps into it when the
data is available; construction without data raises a clear error.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_


class TabularSurrogateExperimenter(base.Experimenter):
    """Nearest-neighbor lookup over a finite table of evaluated configs.

    ``rows``: list of {param_name: value}; ``objectives``: [N] values.
    Evaluation snaps a suggestion to the nearest tabulated config: exact
    match REQUIRED for categoricals (no tabulated row with the suggested
    categorical combination ⇒ infeasible), nearest scaled L2 for numerics —
    the standard way NAS/HPO tabular benchmarks are served.
    """

    def __init__(
        self,
        problem: base_study_config.ProblemStatement,
        rows: Sequence[Dict],
        objectives: Sequence[float],
        *,
        metric_name: Optional[str] = None,
    ):
        if len(rows) != len(objectives):
            raise ValueError("rows and objectives must align.")
        if not rows:
            raise ValueError("Empty surrogate table.")
        self._problem = problem
        self._metric = metric_name or problem.metric_information.item().name
        self._objectives = np.asarray(objectives, dtype=np.float64)
        from vizier_tpu.converters import core as converters

        self._enc = converters.SearchSpaceEncoder(problem.search_space)
        table_trials = [trial_.Trial(id=i + 1, parameters=r) for i, r in enumerate(rows)]
        self._table_cont, self._table_cat = self._enc.encode(table_trials)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        cont, cat = self._enc.encode(suggestions)
        # Continuous distance via the |a|²-2ab+|b|² expansion (no [M,N,D]
        # intermediate); categorical mismatches are disqualifying.
        a2 = np.sum(cont**2, axis=1, keepdims=True)
        b2 = np.sum(self._table_cont**2, axis=1, keepdims=True).T
        d = np.maximum(a2 + b2 - 2.0 * cont @ self._table_cont.T, 0.0)
        if self._enc.num_categorical:
            mismatch = (cat[:, None, :] != self._table_cat[None, :, :]).any(axis=-1)
            d = np.where(mismatch, np.inf, d)
        nearest = d.argmin(axis=1)
        for t, idx, row in zip(suggestions, nearest, d):
            if not np.isfinite(row[idx]):
                t.complete(
                    infeasibility_reason="No tabulated config with this "
                    "categorical combination."
                )
                continue
            t.complete(
                trial_.Measurement(
                    metrics={self._metric: float(self._objectives[idx])}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem


def _require_file(path: Optional[str], what: str) -> str:
    if not path or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what} data not found at {path!r}. Download the dataset and pass "
            "its path; this image bundles no benchmark data."
        )
    return path


class HPOBHandler:
    """HPO-B v3 benchmark handler (parity with ``hpob/handler.py:35``).

    Reads the real HPO-B layout — ``meta-train-dataset.json`` (plus the
    ``-augmented`` variant), ``meta-validation-dataset.json``,
    ``meta-test-dataset.json``, and ``bo-initializations.json`` under one
    root — with the reference's mode semantics:

    - ``v3-test``: only the meta-test split (the evaluation protocol split).
    - ``v3-train-augmented``: all splits, augmented meta-train.
    - ``v1`` / ``v2`` / ``v3``: all splits; v1 uses the augmented train
      dump; v1/v2 merge every split into one table per search space.

    ``evaluate`` runs the benchmark's own discrete protocol: 5 tabulated
    initial points chosen by the published ``bo-initializations`` ids, then
    ``n_trials`` rounds of the method's ``observe_and_suggest(X_obs, y_obs,
    X_pen) -> index`` over the remaining tabulated candidates, returning
    the normalized incumbent trace. ``evaluate_continuous`` (reference
    ``handler.py:232``) runs the full continuous protocol — published init
    ids, stats-normalized labels, surrogate-scored free suggestions — with
    only the XGBoost model serving gated on the library (inject
    ``predictor=`` to run without it). Loading is lazy so constructing a
    handler without data is cheap; the first data access raises
    ``FileNotFoundError``.
    """

    SEEDS = ("test0", "test1", "test2", "test3", "test4")
    MODES = ("v1", "v2", "v3", "v3-test", "v3-train-augmented")
    N_INITIAL_EVALUATIONS = 5

    def __init__(
        self,
        root_dir: Optional[str] = None,
        mode: str = "v3-test",
        surrogates_dir: Optional[str] = None,
    ):
        """``surrogates_dir`` holds the continuous protocol's saved
        surrogate dumps plus ``summary-stats.json`` (y_min/y_max per
        surrogate); only :meth:`surrogate_predictor`'s XGBoost call needs
        the library itself."""
        if mode not in self.MODES:
            raise ValueError(
                f"Unknown HPO-B mode {mode!r}; choices: {list(self.MODES)}"
            )
        self.root_dir = root_dir
        self.mode = mode
        self.surrogates_dir = surrogates_dir
        self.seeds = list(self.SEEDS)
        self._loaded = False
        self.meta_train_data: Dict = {}
        self.meta_validation_data: Dict = {}
        self.meta_test_data: Dict = {}
        self.bo_initializations: Dict = {}

    # -- data loading -------------------------------------------------------

    def _read(self, filename: str) -> Dict:
        path = _require_file(
            self.root_dir and os.path.join(self.root_dir, filename), "HPO-B"
        )
        with open(path) as f:
            return json.load(f)

    def load_data(
        self,
        rootdir: Optional[str] = None,
        version: str = "v3",
        only_test: bool = True,
        augmented_train: bool = False,
    ) -> None:
        """Loads the dumps with the reference's exact split semantics."""
        if rootdir is not None:
            self.root_dir = rootdir
        self.meta_test_data = self._read("meta-test-dataset.json")
        self.bo_initializations = self._read("bo-initializations.json")
        self.meta_train_data = {}
        self.meta_validation_data = {}
        if not only_test:
            train_file = (
                "meta-train-dataset-augmented.json"
                if (augmented_train or version == "v1")
                else "meta-train-dataset.json"
            )
            self.meta_train_data = self._read(train_file)
            self.meta_validation_data = self._read(
                "meta-validation-dataset.json"
            )
        if version in ("v1", "v2"):
            # Older versions evaluate on the union of all splits.
            merged: Dict = {}
            for ss, datasets in self.meta_train_data.items():
                merged[ss] = dict(datasets)
                if ss in self.meta_test_data:
                    merged[ss].update(self.meta_test_data[ss])
                    merged[ss].update(self.meta_validation_data.get(ss, {}))
            self.meta_test_data = merged
        self._loaded = True

    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        if self.mode == "v3-test":
            self.load_data(only_test=True)
        elif self.mode == "v3-train-augmented":
            self.load_data(only_test=False, augmented_train=True)
        else:  # v1 | v2 | v3
            self.load_data(version=self.mode, only_test=False)

    # -- protocol -----------------------------------------------------------

    def get_seeds(self) -> List[str]:
        return list(self.seeds)

    @staticmethod
    def normalize(y, y_min=None, y_max=None):
        y = np.asarray(y, dtype=np.float64)
        if y_min is None:
            y_min, y_max = np.min(y), np.max(y)
        span = y_max - y_min
        if span == 0:
            # Constant-y dataset (or single row): a 0/0 here would poison
            # every incumbent trace with NaN; all-zeros is the only value
            # consistent with "distance above the minimum".
            return np.zeros_like(y)
        return (y - y_min) / span

    def evaluate(
        self,
        bo_method=None,
        search_space_id: Optional[str] = None,
        dataset_id: Optional[str] = None,
        seed: Optional[str] = None,
        n_trials: int = 10,
    ) -> List[float]:
        """Discrete protocol: incumbent trace over tabulated candidates."""
        if bo_method is None or not hasattr(bo_method, "observe_and_suggest"):
            raise ValueError(
                "bo_method must define observe_and_suggest(X_obs, y_obs, "
                "X_pen) -> pending index."
            )
        if search_space_id is None or dataset_id is None or seed is None:
            raise ValueError("search_space_id, dataset_id and seed are required.")
        self._ensure_loaded()
        entry = self.meta_test_data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = self.normalize(np.asarray(entry["y"], dtype=np.float64).reshape(-1))
        pending = list(range(len(xs)))
        current: List[int] = []
        init_ids = self.bo_initializations[search_space_id][dataset_id][seed]
        for i in range(self.N_INITIAL_EVALUATIONS):
            idx = init_ids[i]
            pending.remove(idx)
            current.append(idx)
        history = [float(np.max(ys[current]))]
        for _ in range(n_trials):
            pick = bo_method.observe_and_suggest(
                xs[current], ys[current], xs[pending]
            )
            idx = pending[int(pick)]
            pending.remove(idx)
            current.append(idx)
            history.append(float(np.max(ys[current])))
        return history

    def surrogates_stats(self) -> Dict:
        """Parses ``summary-stats.json`` from ``surrogates_dir`` (the
        published y_min/y_max per surrogate; reference ``handler.py:131``)."""
        if self.surrogates_dir is None:
            raise ValueError(
                "surrogates_dir is required for the continuous protocol "
                "(it holds summary-stats.json and the surrogate dumps)."
            )
        path = _require_file(
            os.path.join(self.surrogates_dir, "summary-stats.json"), "HPO-B"
        )
        with open(path) as f:
            return json.load(f)

    def surrogate_predictor(self, search_space_id: str, dataset_id: str):
        """``[N, dim] -> [N]`` callable serving the saved XGBoost surrogate.

        The only xgboost-gated piece of the continuous protocol: loads
        ``surrogate-<ss>-<ds>.json`` from ``surrogates_dir`` into a Booster
        (reference ``handler.py:265-267``). Everything around it —
        stats parsing, init ids, normalize/clip, the suggest loop — is
        plain code; tests inject a fake predictor instead.
        """
        if self.surrogates_dir is None:
            raise ValueError(
                "surrogates_dir is required to serve saved surrogates "
                "(pass predictor= to evaluate_continuous to go without)."
            )
        try:
            import xgboost as xgb
        except ImportError as e:
            raise ImportError(
                "Serving the published HPO-B surrogates needs the xgboost "
                "package (absent from this image); pass predictor= to "
                "evaluate_continuous instead."
            ) from e
        model_path = _require_file(
            os.path.join(
                self.surrogates_dir,
                f"surrogate-{search_space_id}-{dataset_id}.json",
            ),
            "HPO-B",
        )
        booster = xgb.Booster()
        booster.load_model(model_path)
        return lambda x: np.asarray(booster.predict(xgb.DMatrix(x))).reshape(-1)

    def evaluate_continuous(
        self,
        bo_method=None,
        search_space_id: Optional[str] = None,
        dataset_id: Optional[str] = None,
        seed: Optional[str] = None,
        n_trials: int = 10,
        predictor=None,
    ) -> List[float]:
        """Continuous protocol against the published surrogates.

        Parity with reference ``handler.py:232-306``: seed the 5 published
        initial points, then ``n_trials`` rounds of ``observe_and_suggest
        (X_obs, y_obs_normalized) -> new_x`` where ``new_x`` is any point
        in the unit cube, scored by the saved surrogate and appended to the
        observations. Labels are min-max normalized with the surrogate's
        published ``y_min``/``y_max`` and clipped to [0, 1]; the returned
        trace is the incumbent before each suggest plus one final entry
        (which here includes the last suggested point — the reference
        re-appends the pre-suggest incumbent).

        ``predictor`` is a ``[N, dim] -> [N]`` callable; defaults to the
        xgboost-served surrogate from :meth:`surrogate_predictor`.
        """
        if bo_method is None or not hasattr(bo_method, "observe_and_suggest"):
            raise ValueError(
                "bo_method must define observe_and_suggest(X_obs, y_obs) "
                "-> new continuous point."
            )
        if search_space_id is None or dataset_id is None or seed is None:
            raise ValueError("search_space_id, dataset_id and seed are required.")
        self._ensure_loaded()
        if predictor is None:
            predictor = self.surrogate_predictor(search_space_id, dataset_id)
        stats = self.surrogates_stats()
        stats_key = f"surrogate-{search_space_id}-{dataset_id}"
        if stats_key not in stats:
            raise KeyError(
                f"{stats_key!r} missing from summary-stats.json; cannot "
                "normalize surrogate outputs."
            )
        y_min = stats[stats_key]["y_min"]
        y_max = stats[stats_key]["y_max"]

        entry = self.meta_test_data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = np.asarray(entry["y"], dtype=np.float64).reshape(-1)
        dim = xs.shape[1]
        init_ids = self.bo_initializations[search_space_id][dataset_id][seed]
        observed_x = xs[init_ids[: self.N_INITIAL_EVALUATIONS]]
        observed_y = ys[init_ids[: self.N_INITIAL_EVALUATIONS]]

        history: List[float] = []
        for _ in range(n_trials):
            y_tf = np.clip(self.normalize(observed_y, y_min, y_max), 0.0, 1.0)
            history.append(float(np.max(y_tf)))
            new_x = np.asarray(
                bo_method.observe_and_suggest(observed_x, y_tf), dtype=np.float64
            ).reshape(-1, dim)
            new_y = predictor(new_x)
            observed_x = np.concatenate([observed_x, new_x], axis=0)
            observed_y = np.append(observed_y, new_y)
        y_tf = np.clip(self.normalize(observed_y, y_min, y_max), 0.0, 1.0)
        history.append(float(np.max(y_tf)))
        return history

    # -- experimenter bridge ------------------------------------------------

    def make_experimenter(
        self, search_space_id: str, dataset_id: str
    ) -> base.Experimenter:
        """Serves one (search space, dataset) table as an Experimenter."""
        self._ensure_loaded()
        entry = self.meta_test_data[search_space_id][dataset_id]
        xs = np.asarray(entry["X"], dtype=np.float64)
        ys = np.asarray(entry["y"], dtype=np.float64).reshape(-1)
        problem = base_study_config.ProblemStatement()
        for j in range(xs.shape[1]):
            problem.search_space.root.add_float_param(f"x{j}", 0.0, 1.0)
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="objective", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        rows = [{f"x{j}": float(v) for j, v in enumerate(row)} for row in xs]
        return TabularSurrogateExperimenter(problem, rows, ys)


@dataclasses.dataclass
class NASBench201Handler:
    """NASBench-201 handler: 6 categorical ops cells → accuracy table."""

    OPS = ("none", "skip_connect", "nor_conv_1x1", "nor_conv_3x3", "avg_pool_3x3")

    data_path: Optional[str] = None

    def problem_statement(self) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for i in range(6):
            problem.search_space.root.add_categorical_param(f"op{i}", list(self.OPS))
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="accuracy", goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        path = _require_file(self.data_path, "NASBench-201")
        with open(path) as f:
            table = json.load(f)  # [{"op0": ..., ..., "accuracy": ...}, ...]
        rows = [{k: v for k, v in row.items() if k != "accuracy"} for row in table]
        ys = [row["accuracy"] for row in table]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, ys, metric_name="accuracy"
        )

    def make_synthetic_experimenter(
        self, *, num_rows: Optional[int] = None, seed: int = 0
    ) -> base.Experimenter:
        """NASBench-201-STYLE surrogate over a synthetic accuracy table.

        Not real NASBench data (none is bundled in this image): a
        deterministic structured objective over the same 6-op categorical
        cell space — op quality + pairwise interactions — so the full
        tabular-benchmark pipeline (suggest → snap-to-table → accuracy)
        runs end to end without the dataset.

        Like the real NASBench-201, EVERY architecture is tabulated (all
        5^6 = 15625 cells) by default, so no suggestion can fall outside
        the table; ``num_rows`` subsamples for cheap tests (off-table
        suggestions then complete infeasible, the exact-match contract).
        """
        rng = np.random.default_rng(seed)
        n_ops = len(self.OPS)
        quality = rng.normal(size=(6, n_ops))
        pair = rng.normal(scale=0.3, size=(6, 6, n_ops, n_ops))
        all_idx = np.stack(
            np.meshgrid(*[np.arange(n_ops)] * 6, indexing="ij"), axis=-1
        ).reshape(-1, 6)  # [5^6, 6]
        score = quality[np.arange(6)[None, :], all_idx].sum(axis=1)
        for i in range(6):
            for j in range(i + 1, 6):
                score = score + pair[i, j, all_idx[:, i], all_idx[:, j]]
        accs = 100.0 / (1.0 + np.exp(-score / 4.0))  # accuracy-like range
        if num_rows is not None and num_rows < len(all_idx):
            keep = rng.choice(len(all_idx), size=num_rows, replace=False)
            all_idx, accs = all_idx[keep], accs[keep]
        rows: List[Dict] = [
            {f"op{i}": self.OPS[idx[i]] for i in range(6)} for idx in all_idx
        ]
        return TabularSurrogateExperimenter(
            self.problem_statement(), rows, [float(a) for a in accs],
            metric_name="accuracy",
        )


ATARI100K_AGENTS = ("DER", "DrQ", "DrQ_eps", "OTRainbow")


def atari100k_search_space() -> pc.SearchSpace:
    """The published Rainbow/Atari-100k tuning space (reference
    ``atari100k_experimenter.py`` ``default_search_space``): gin-bindable
    agent hyperparameters, names included."""
    ss = pc.SearchSpace()
    root = ss.root
    root.add_float_param(
        "JaxDQNAgent.gamma", 0.7, 0.999999,
        scale_type=pc.ScaleType.REVERSE_LOG,
    )
    root.add_int_param("JaxDQNAgent.update_horizon", 1, 20)
    root.add_int_param("JaxDQNAgent.update_period", 1, 10)
    root.add_int_param("JaxDQNAgent.target_update_period", 1, 10000)
    root.add_int_param("JaxDQNAgent.min_replay_history", 100, 100000)
    root.add_float_param(
        "JaxDQNAgent.epsilon_train", 1e-7, 1.0, scale_type=pc.ScaleType.LOG
    )
    root.add_int_param("JaxDQNAgent.epsilon_decay_period", 1000, 10000)
    root.add_bool_param("JaxFullRainbowAgent.noisy")
    root.add_bool_param("JaxFullRainbowAgent.dueling")
    root.add_bool_param("JaxFullRainbowAgent.double_dqn")
    root.add_int_param("JaxFullRainbowAgent.num_atoms", 1, 100)
    root.add_bool_param("Atari100kRainbowAgent.data_augmentation")
    root.add_float_param(
        "create_optimizer.learning_rate", 1e-7, 1.0,
        scale_type=pc.ScaleType.LOG,
    )
    root.add_float_param(
        "create_optimizer.eps", 1e-7, 1.0, scale_type=pc.ScaleType.LOG
    )
    return ss


def _atari100k_problem() -> base_study_config.ProblemStatement:
    problem = base_study_config.ProblemStatement(
        search_space=atari100k_search_space()
    )
    problem.metric_information.append(
        base_study_config.MetricInformation(
            name="eval_average_return",
            goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
        )
    )
    return problem


def _gin_native_value(name: str, value):
    """Trial parameter → the python value gin must see.

    Bool params travel as the strings "True"/"False" (categorical
    encoding); binding those into gin would make every ``if noisy:`` check
    truthy, so BOOLEAN-typed parameters convert back to real bools here.
    """
    cfg = _ATARI100K_PARAMS.get(name)
    if cfg is not None and cfg.external_type == pc.ExternalType.BOOLEAN:
        return str(value) == "True"
    return value


_ATARI100K_PARAMS = {p.name: p for p in atari100k_search_space().parameters}


class Atari100kExperimenter(base.Experimenter):
    """Live Atari-100k Rainbow tuning (reference ``Atari100kExperimenter``).

    Each trial's parameters are gin bindings applied over the chosen agent
    base config (DER / DrQ / DrQ_eps / OTRainbow); evaluation runs real
    dopamine training + eval with ``eval_average_return`` as the
    objective. The dopamine/gin stack is absent from this image, so
    ``evaluate`` is import-gated; the problem surface (the published
    14-parameter space) works everywhere. ``gin_config_dir`` must point at
    the published agent configs (e.g. dopamine's or the reference's
    ``atari100k_configs/`` directory — they are data, not shipped here).
    """

    def __init__(
        self,
        game_name: str = "Pong",
        agent_name: str = "DER",
        initial_gin_bindings: Optional[Dict] = None,
        gin_config_dir: Optional[str] = None,
    ):
        if agent_name not in ATARI100K_AGENTS:
            raise ValueError(
                f"agent_name must be one of {ATARI100K_AGENTS}, got {agent_name!r}."
            )
        self._game_name = game_name
        self._agent_name = agent_name
        self._initial_gin_bindings = dict(initial_gin_bindings or {})
        self._gin_config_dir = gin_config_dir

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return _atari100k_problem()

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        try:
            import gin  # noqa: F401
            from dopamine.labs.atari_100k import (  # noqa: F401
                eval_run_experiment,
            )
        except ImportError as e:
            raise ImportError(
                "Atari100kExperimenter.evaluate needs the dopamine-rl + gin "
                "stack (absent from this image) to run real Rainbow "
                "training; use Atari100kHandler for offline tabular dumps."
            ) from e
        if not self._gin_config_dir:
            raise ValueError(
                "Pass gin_config_dir= pointing at the published Atari-100k "
                "agent .gin configs (DER/DrQ/DrQ_eps/OTRainbow) to run live."
            )
        gin_file = os.path.join(
            self._gin_config_dir, f"{self._agent_name}.gin"
        )
        _require_file(gin_file, "Atari100k gin config")
        for t in suggestions:
            with gin.unlock_config():
                gin.parse_config_file(gin_file)
                gin.bind_parameter(
                    "atari_lib.create_atari_environment.game_name",
                    self._game_name,
                )
                for name, value in self._initial_gin_bindings.items():
                    gin.bind_parameter(name, value)
                for name in t.parameters:
                    gin.bind_parameter(
                        name,
                        _gin_native_value(name, t.parameters.get_value(name)),
                    )
            runner = eval_run_experiment.MaxEpisodeEvalRunner(base_dir="/tmp/")
            statistics = runner.run_experiment()
            final = trial_.Measurement(
                metrics={
                    "eval_average_return": float(
                        statistics.data_lists["eval_average_return"][-1]
                    )
                }
            )
            t.complete(final)


@dataclasses.dataclass
class Atari100kHandler:
    """Atari-100k offline tabular handler over the REAL tuning space.

    Expects a json table of records keyed by the published gin-parameter
    names (``atari100k_search_space``) plus an ``eval_average_return``
    metric column (the metric column — only — may also use the legacy name
    ``score``); data is not bundled — pass the dump's path.

    With ``data_path`` set, ``problem_statement()`` reflects the table's
    columns — the (sub)space the dump actually swept — and matches
    ``make_experimenter().problem_statement()`` exactly; without data it
    returns the full published 14-parameter space.
    """

    data_path: Optional[str] = None

    _VALUE_COLS = ("eval_average_return", "score")

    def problem_statement(self) -> base_study_config.ProblemStatement:
        if self.data_path and os.path.exists(self.data_path):
            rows, _ = self._load_table()
            return self._table_problem(rows)
        return _atari100k_problem()

    def _load_table(self):
        path = _require_file(self.data_path, "Atari100k")
        with open(path) as f:
            table = json.load(f)
        if not table:
            raise ValueError(f"Empty Atari100k table at {path!r}.")
        full = set(_ATARI100K_PARAMS)
        expected_keys = None
        ys = []
        rows = []
        for i, row in enumerate(table):
            param_keys = frozenset(k for k in row if k not in self._VALUE_COLS)
            unknown = param_keys - full
            if unknown:
                raise ValueError(
                    f"Unknown Atari100k column {sorted(unknown)[0]!r} in row "
                    f"{i}; expected gin parameter names from "
                    "atari100k_search_space()."
                )
            if expected_keys is None:
                expected_keys = param_keys
            elif param_keys != expected_keys:
                raise ValueError(
                    f"Row {i} columns {sorted(param_keys)} differ from row "
                    f"0's {sorted(expected_keys)}; every row must sweep the "
                    "same parameters."
                )
            for col in self._VALUE_COLS:
                if col in row:
                    ys.append(row[col])
                    break
            else:
                raise ValueError(
                    f"Row {i} needs an 'eval_average_return' (or legacy "
                    "'score') metric column."
                )
            rows.append({k: row[k] for k in param_keys})
        return rows, ys

    def _table_problem(self, rows) -> base_study_config.ProblemStatement:
        problem = base_study_config.ProblemStatement()
        for name in sorted(rows[0]):
            problem.search_space.root.add(_ATARI100K_PARAMS[name])
        problem.metric_information.append(
            base_study_config.MetricInformation(
                name="eval_average_return",
                goal=base_study_config.ObjectiveMetricGoal.MAXIMIZE,
            )
        )
        return problem

    def make_experimenter(self) -> base.Experimenter:
        rows, ys = self._load_table()
        return TabularSurrogateExperimenter(
            self._table_problem(rows), rows, ys,
            metric_name="eval_average_return",
        )


class PredictorExperimenter(base.Experimenter):
    """Serves a trained ``Predictor``'s posterior mean as the objective.

    Parity with the reference ``PredictorExperimenter``
    (``surrogate_experimenter.py:26``): any designer implementing the
    Predictor mixin (e.g. a GP bandit fit on real measurements) becomes a
    cheap stand-in objective for benchmarking other algorithms.
    """

    def __init__(
        self,
        predictor,
        problem_statement: base_study_config.ProblemStatement,
        seed: int = 0,
    ):
        name = problem_statement.single_objective_metric_name
        if name is None:
            raise ValueError(
                "PredictorExperimenter needs a single-objective problem."
            )
        self._predictor = predictor
        self._problem = copy.deepcopy(problem_statement)
        self._objective_name = name
        self._rng = np.random.default_rng(seed)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        as_suggestions = [
            trial_.TrialSuggestion(parameters=t.parameters)
            for t in suggestions
        ]
        prediction = self._predictor.predict(as_suggestions, self._rng)
        means = np.asarray(prediction.mean).reshape(len(suggestions), -1)
        for t, mean in zip(suggestions, means):
            t.complete(
                trial_.Measurement(
                    metrics={self._objective_name: float(mean[0])}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)
