"""Combinatorial benchmark experimenters (COMBO suite + L1-categorical).

Parity with the reference's combinatorial objectives
(``combo_experimenter.py:34,100,185,273`` and
``l1_categorical_experimenter.py:28``; problems from Oh et al., "Combinatorial
Bayesian Optimization using the Graph Cartesian Product", NeurIPS 2019, and
Baptista & Poloczek, "Bayesian Optimization of Combinatorial Structures",
ICML 2018). These are the standard data-free combinatorial BO testbeds that
exercise BOCS / categorical-kernel designers.

Implementation is batched numpy throughout: the Ising spin enumeration is a
single einsum over all 2^16 configurations instead of a python loop, and the
KLD pairwise sum is two vectorized edge contractions. Spins are indexed
row-major ((r, c) -> r*W + c) consistently for non-square grids.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from vizier_tpu.benchmarks.experimenters import base
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_

Interaction = Tuple[np.ndarray, np.ndarray]  # (horizontal [H, W-1], vertical [H-1, W])


# ---------------------------------------------------------------------------
# Ising grid math (batched).
# ---------------------------------------------------------------------------


def random_ising_interaction(
    grid_h: int, grid_w: int, rng: np.random.Generator
) -> Interaction:
    """Random ±[0.05, 5] couplings on the edges of an H×W grid."""

    def draw(n: int) -> np.ndarray:
        sign = rng.integers(0, 2, n) * 2 - 1
        return sign * (rng.uniform(size=n) * (5.0 - 0.05) + 0.05)

    horizontal = draw(grid_h * (grid_w - 1)).reshape(grid_h, grid_w - 1)
    vertical = draw((grid_h - 1) * grid_w).reshape(grid_h - 1, grid_w)
    return horizontal, vertical


def _all_spin_grids(grid_h: int, grid_w: int) -> np.ndarray:
    """[2^n, H, W] array of every ±1 spin configuration (row-major bits)."""
    n = grid_h * grid_w
    if n > 20:
        raise ValueError(f"Exact Ising enumeration infeasible for {n} spins.")
    codes = np.arange(1 << n, dtype=np.uint32)
    bits = (codes[:, None] >> np.arange(n, dtype=np.uint32)[None, :]) & 1
    return (bits.astype(np.int8) * 2 - 1).reshape(-1, grid_h, grid_w)


def _interaction_energies(spins: np.ndarray, interaction: Interaction) -> np.ndarray:
    """[C] log interaction energy 2·Σ J_ij s_i s_j for every configuration."""
    h, v = interaction
    e_h = np.einsum("chw,hw->c", (spins[:, :, :-1] * spins[:, :, 1:]).astype(np.float64), h)
    e_v = np.einsum("chw,hw->c", (spins[:, :-1, :] * spins[:, 1:, :]).astype(np.float64), v)
    return 2.0 * (e_h + e_v)


def log_partition(interaction: Interaction, grid_shape: Tuple[int, int]) -> float:
    """log Σ exp(energy) over all spin configurations (stable logsumexp)."""
    energies = _interaction_energies(_all_spin_grids(*grid_shape), interaction)
    peak = np.max(energies)
    return float(peak + np.log(np.sum(np.exp(energies - peak))))


def spin_covariance(
    interaction: Interaction, grid_shape: Tuple[int, int]
) -> Tuple[np.ndarray, float]:
    """(⟨s_i s_j⟩ covariance [n, n], log partition) under the Gibbs density."""
    spins = _all_spin_grids(*grid_shape)
    energies = _interaction_energies(spins, interaction)
    peak = np.max(energies)
    density = np.exp(energies - peak)
    log_z = float(peak + np.log(density.sum()))
    density = density / density.sum()
    flat = spins.reshape(spins.shape[0], -1).astype(np.float64)
    covariance = flat.T @ (flat * density[:, None])
    return covariance, log_z


def ising_kl_divergence(
    interaction_original: Interaction,
    interaction_new: Interaction,
    covariance: np.ndarray,
    log_z_original: float,
    log_z_new: float,
    grid_shape: Tuple[int, int],
) -> float:
    """KL(p_original || p_new) between two Ising Gibbs distributions.

    KL = 2·Σ_edges (J_orig − J_new)·⟨s_i s_j⟩ + log Z_new − log Z_orig,
    with both edge families contracted in one vectorized pass.
    """
    grid_h, grid_w = grid_shape
    diff_h = interaction_original[0] - interaction_new[0]  # [H, W-1]
    diff_v = interaction_original[1] - interaction_new[1]  # [H-1, W]
    idx = np.arange(grid_h * grid_w).reshape(grid_h, grid_w)
    h_cov = covariance[idx[:, :-1].ravel(), idx[:, 1:].ravel()].reshape(diff_h.shape)
    v_cov = covariance[idx[:-1, :].ravel(), idx[1:, :].ravel()].reshape(diff_v.shape)
    kld = np.sum(diff_h * h_cov) + np.sum(diff_v * v_cov)
    return float(2.0 * kld + log_z_new - log_z_original)


# ---------------------------------------------------------------------------
# Experimenters.
# ---------------------------------------------------------------------------


def _bool_problem(n: int, metric: str = "main_objective") -> base_study_config.ProblemStatement:
    problem = base_study_config.ProblemStatement()
    for i in range(n):
        problem.search_space.root.add_bool_param(f"x_{i}")
    problem.metric_information.append(
        base_study_config.MetricInformation(
            name=metric, goal=base_study_config.ObjectiveMetricGoal.MINIMIZE
        )
    )
    return problem


def _bool_vector(t: trial_.Trial, n: int) -> np.ndarray:
    return np.array(
        [str(t.parameters[f"x_{i}"].value) == "True" for i in range(n)], dtype=float
    )


class IsingExperimenter(base.Experimenter):
    """Ising sparsification: drop couplings, pay KL divergence + L1 cost.

    Each boolean keeps (True) or removes (False) one grid edge; the score is
    KL(original ‖ sparsified) + λ·#kept — MINIMIZE finds the cheapest
    faithful sparsification (reference ``IsingExperimenter``).
    """

    def __init__(
        self,
        lamda: float = 1e-2,
        grid_h: int = 4,
        grid_w: int = 4,
        seed: Optional[int] = None,
    ):
        self._lamda = lamda
        self._grid = (grid_h, grid_w)
        self._n_h = grid_h * (grid_w - 1)
        self._n_edges = self._n_h + (grid_h - 1) * grid_w
        rng = np.random.default_rng(seed)
        self._interaction = random_ising_interaction(grid_h, grid_w, rng)
        self._covariance, self._log_z = spin_covariance(self._interaction, self._grid)
        self._problem = _bool_problem(self._n_edges)

    def _split(self, x: np.ndarray) -> Interaction:
        grid_h, grid_w = self._grid
        return (
            x[: self._n_h].reshape(grid_h, grid_w - 1),
            x[self._n_h :].reshape(grid_h - 1, grid_w),
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        metric = self._problem.metric_information.item().name
        for t in suggestions:
            x = _bool_vector(t, self._n_edges)
            keep_h, keep_v = self._split(x)
            sparsified = (
                keep_h * self._interaction[0],
                keep_v * self._interaction[1],
            )
            kld = ising_kl_divergence(
                self._interaction,
                sparsified,
                self._covariance,
                self._log_z,
                log_partition(sparsified, self._grid),
                self._grid,
            )
            t.complete(
                trial_.Measurement(
                    metrics={metric: kld + self._lamda * float(x.sum())}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


class ContaminationExperimenter(base.Experimenter):
    """Contamination control over a 25-stage food chain (reference parity).

    Each boolean applies a costly intervention at one stage; contamination
    propagates via random rates; the score is intervention cost minus the
    chance-constraint margin, + λ·#interventions. Monte-Carlo dynamics are
    drawn once at construction (one seeded Generator).
    """

    def __init__(
        self,
        lamda: float = 1e-2,
        n_stages: int = 25,
        seed: Optional[int] = None,
        n_simulations: int = 100,
    ):
        self._lamda = lamda
        self._n = n_stages
        self._sims = n_simulations
        rng = np.random.default_rng(seed)
        self._init_z = rng.beta(1.0, 30.0, size=n_simulations)
        self._lambdas = rng.beta(1.0, 17.0 / 3.0, size=(n_stages, n_simulations))
        self._gammas = rng.beta(1.0, 3.0 / 7.0, size=(n_stages, n_simulations))
        self._problem = _bool_problem(n_stages)

    def _score(self, x: np.ndarray, u: float = 0.1, eps: float = 0.05) -> float:
        z = np.empty((self._n, self._sims))
        prev = self._init_z
        for i in range(self._n):
            z[i] = self._lambdas[i] * (1.0 - x[i]) * (1.0 - prev) + (
                1.0 - self._gammas[i] * x[i]
            ) * prev
            prev = z[i]
        constraints = np.mean(z < u, axis=1) - (1.0 - eps)
        return float(np.sum(x - constraints))

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        metric = self._problem.metric_information.item().name
        for t in suggestions:
            x = _bool_vector(t, self._n)
            t.complete(
                trial_.Measurement(
                    metrics={metric: self._score(x) + self._lamda * float(x.sum())}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


class CentroidExperimenter(base.Experimenter):
    """Ising centroid: pick each edge's coupling from one of K models.

    Categorical generalization of sparsification (reference
    ``CentroidExperimenter``): minimize the average KL divergence from the K
    source models to the mixed model.
    """

    def __init__(
        self,
        n_choice: int = 3,
        grid: Tuple[int, int] = (4, 4),
        n_models: int = 3,
        seed: Optional[int] = None,
    ):
        self._n_choice = n_choice
        self._grid = grid
        grid_h, grid_w = grid
        self._n_h = grid_h * (grid_w - 1)
        self._n_edges = self._n_h + (grid_h - 1) * grid_w
        rng = np.random.default_rng(seed)
        self._models: List[Interaction] = []
        self._covs: List[np.ndarray] = []
        self._log_zs: List[float] = []
        for _ in range(n_models):
            inter = random_ising_interaction(grid_h, grid_w, rng)
            cov, log_z = spin_covariance(inter, grid)
            self._models.append(inter)
            self._covs.append(cov)
            self._log_zs.append(log_z)
        # Flat per-edge coupling table [K, n_edges] for vectorized selection.
        self._edge_table = np.stack(
            [np.concatenate([m[0].ravel(), m[1].ravel()]) for m in self._models]
        )
        self._problem = base_study_config.ProblemStatement()
        for i in range(self._n_edges):
            self._problem.search_space.root.add_categorical_param(
                f"x_{i}", [str(j) for j in range(n_choice)]
            )
        self._problem.metric_information.append(
            base_study_config.MetricInformation(
                name="main_objective",
                goal=base_study_config.ObjectiveMetricGoal.MINIMIZE,
            )
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        grid_h, grid_w = self._grid
        for t in suggestions:
            choice = np.array(
                [int(str(t.parameters[f"x_{i}"].value)) for i in range(self._n_edges)]
            )
            mixed_flat = self._edge_table[
                np.minimum(choice, len(self._models) - 1), np.arange(self._n_edges)
            ]
            mixed = (
                mixed_flat[: self._n_h].reshape(grid_h, grid_w - 1),
                mixed_flat[self._n_h :].reshape(grid_h - 1, grid_w),
            )
            log_z_mixed = log_partition(mixed, self._grid)
            klds = [
                ising_kl_divergence(
                    self._models[i], mixed, self._covs[i],
                    self._log_zs[i], log_z_mixed, self._grid,
                )
                for i in range(len(self._models))
            ]
            t.complete(
                trial_.Measurement(
                    metrics={"main_objective": float(np.mean(klds))}
                )
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


class PestControlExperimenter(base.Experimenter):
    """Pest control: choose one of K pesticides (or none) at each stage.

    Sequential dynamics with pesticide-specific control rates, tolerance
    development, and bulk discounts (reference ``PestControlExperimenter``).
    Random rates come from one seeded Generator (drawn per stage, unlike the
    reference's re-seeded identical draws — same benchmark family, cleaner
    stochasticity).
    """

    def __init__(
        self,
        n_choice: int = 5,
        n_stages: int = 25,
        seed: Optional[int] = None,
        n_simulations: int = 100,
    ):
        self._n_choice = n_choice
        self._n = n_stages
        self._sims = n_simulations
        self._seed = seed
        self._problem = base_study_config.ProblemStatement()
        for i in range(n_stages):
            self._problem.search_space.root.add_categorical_param(
                f"x_{i}", [str(j) for j in range(n_choice)]
            )
        self._problem.metric_information.append(
            base_study_config.MetricInformation(
                name="main_objective",
                goal=base_study_config.ObjectiveMetricGoal.MINIMIZE,
            )
        )

    def _score(self, x: np.ndarray) -> float:
        u = 0.1
        rng = np.random.default_rng(self._seed)
        control_price = {1: 1.0, 2: 0.8, 3: 0.7, 4: 0.5}
        max_discount = {1: 0.2, 2: 0.3, 3: 0.3, 4: 0.0}
        tolerance_rate = {1: 1.0 / 7, 2: 2.5 / 7, 3: 2.0 / 7, 4: 0.5 / 7}
        control_beta: Dict[int, float] = {1: 2.0 / 7, 2: 3.0 / 7, 3: 3.0 / 7, 4: 5.0 / 7}
        pest = rng.beta(1.0, 30.0, size=self._sims)
        price_sum = 0.0
        above = 0.0
        for i in range(self._n):
            spread = rng.beta(1.0, 17.0 / 3.0, size=self._sims)
            k = int(x[i])
            if k > 0:
                control = rng.beta(1.0, control_beta[k], size=self._sims)
                nxt = (1.0 - control) * pest
                # Pests develop tolerance to a pesticide the more it is used.
                control_beta[k] += tolerance_rate[k] / float(self._n)
                # Bulk discount grows with how often this pesticide appears.
                price = control_price[k] * (
                    1.0 - max_discount[k] / float(self._n) * float(np.sum(x == k))
                )
            else:
                nxt = spread * (1.0 - pest) + pest
                price = 0.0
            price_sum += price
            above += float(np.mean(pest > u))
            pest = nxt
        return price_sum + above

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            x = np.array(
                [int(str(t.parameters[f"x_{i}"].value)) for i in range(self._n)]
            )
            t.complete(
                trial_.Measurement(metrics={"main_objective": self._score(x)})
            )

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


class L1CategoricalExperimenter(base.Experimenter):
    """Hamming distance to a hidden categorical optimum (MINIMIZE to 0).

    Reference ``L1CategorialExperimenter``: parameter c{i} has
    ``num_categories[i]`` values; the loss counts mismatches against the
    (possibly random) optimum — the simplest categorical convergence gate.
    """

    def __init__(
        self,
        *,
        num_categories: Sequence[int],
        optimum: Optional[Sequence[int]] = None,
        seed: Optional[int] = None,
    ):
        rng = np.random.default_rng(seed)
        self._problem = base_study_config.ProblemStatement()
        self._optimum: Dict[str, str] = {}
        for i, k in enumerate(num_categories):
            name = f"c{i}"
            self._problem.search_space.root.add_categorical_param(
                name, [str(v) for v in range(k)]
            )
            if optimum is None:
                self._optimum[name] = str(rng.integers(0, k))
            elif optimum[i] >= k:
                raise ValueError(
                    f"Optimum index {optimum[i]} out of range for {k} categories."
                )
            else:
                self._optimum[name] = str(optimum[i])
        self._problem.metric_information.append(
            base_study_config.MetricInformation(
                name="objective",
                goal=base_study_config.ObjectiveMetricGoal.MINIMIZE,
            )
        )

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        for t in suggestions:
            loss = sum(
                1.0
                for name, best in self._optimum.items()
                if str(t.parameters[name].value) != best
            )
            t.complete(trial_.Measurement(metrics={"objective": loss}))

    @property
    def optimal_trial(self) -> trial_.Trial:
        t = trial_.Trial(id=0, parameters=dict(self._optimum))
        self.evaluate([t])
        return t

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)


# ---------------------------------------------------------------------------
# MAXSAT (weighted CNF).
# ---------------------------------------------------------------------------


def parse_wcnf(
    text: str,
) -> Tuple[int, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Parse DIMACS WCNF into padded clause tensors.

    Returns ``(n_variables, weights [C], var_idx [C, L], want_true [C, L],
    literal_mask [C, L])`` where ``L`` is the longest clause. Mirrors the
    reference's parse (``combo_experimenter.py:384-404``: header ``p wcnf
    V C``, per-line ``weight lit ... 0``) but materializes the clauses as
    padded arrays so evaluation is one vectorized reduction instead of a
    per-clause python loop.
    """
    n_variables = n_clauses = None
    weights: List[float] = []
    clauses: List[List[int]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("c"):
            continue
        if line.startswith("p "):
            parts = line.split()
            n_variables, n_clauses = int(parts[2]), int(parts[3])
            continue
        # DIMACS allows several "weight lit... 0" clauses on one line; walk
        # the token stream splitting at each 0 terminator so a mid-line 0 is
        # a clause boundary, never a literal.
        tokens = line.split()
        pos = 0
        while pos < len(tokens):
            weight = float(tokens[pos])
            pos += 1
            lits: List[int] = []
            while pos < len(tokens) and tokens[pos] != "0":
                lits.append(int(tokens[pos]))
                pos += 1
            pos += 1  # skip the 0 terminator (or run off a missing one)
            if not lits:
                continue
            weights.append(weight)
            clauses.append(lits)
    if n_variables is None:
        raise ValueError("WCNF text has no 'p wcnf <vars> <clauses>' header.")
    if not clauses:
        raise ValueError("WCNF text contains no clauses.")
    if n_clauses is not None and len(clauses) != n_clauses:
        raise ValueError(
            f"WCNF header declares {n_clauses} clauses, found {len(clauses)}."
        )
    max_len = max(len(c) for c in clauses)
    var_idx = np.zeros((len(clauses), max_len), dtype=np.int64)
    want_true = np.zeros((len(clauses), max_len), dtype=bool)
    mask = np.zeros((len(clauses), max_len), dtype=bool)
    for i, lits in enumerate(clauses):
        for j, lit in enumerate(lits):
            var_idx[i, j] = abs(lit) - 1
            want_true[i, j] = lit > 0
            mask[i, j] = True
    if var_idx.max() >= n_variables:
        raise ValueError("WCNF clause references a variable beyond the header.")
    return n_variables, np.asarray(weights, np.float64), var_idx, want_true, mask


def random_wcnf(
    n_variables: int, n_clauses: int, rng: np.random.Generator, max_clause_len: int = 3
) -> str:
    """Synthetic DIMACS WCNF text (for tests; no COMBO data download)."""
    lines = [f"c synthetic random wcnf", f"p wcnf {n_variables} {n_clauses}"]
    for _ in range(n_clauses):
        k = int(rng.integers(1, max_clause_len + 1))
        vars_ = rng.choice(n_variables, size=k, replace=False) + 1
        signs = rng.integers(0, 2, size=k) * 2 - 1
        w = float(rng.uniform(1.0, 10.0))
        lits = " ".join(str(int(v * s)) for v, s in zip(vars_, signs))
        lines.append(f"{w:.3f} {lits} 0")
    return "\n".join(lines) + "\n"


class MAXSATExperimenter(base.Experimenter):
    """Weighted MAXSAT over boolean assignments.

    Parity target: ``combo_experimenter.py:380-447`` (MAXSATExperimenter) —
    same normalized-weight objective ``-Σ w̃_c · satisfied_c`` (MINIMIZE,
    weights z-scored across clauses) and the same ``x_i`` bool search
    space. Evaluation here is batched: all suggestions' assignments are
    stacked into ``[B, n]`` and every clause is checked with one gather +
    ``any`` reduction over the padded literal tensors.

    Data files (maxsat2018 ``.wcnf``) are external downloads in the
    reference too; use :meth:`from_file` when present, or construct
    directly from WCNF text (``random_wcnf`` for synthetic instances).
    """

    def __init__(self, wcnf_text: str):
        (
            self._n_variables,
            raw_weights,
            self._var_idx,
            self._want_true,
            self._mask,
        ) = parse_wcnf(wcnf_text)
        std = np.std(raw_weights)
        # Reference z-scores clause weights (combo_experimenter.py:396-399).
        # Unweighted instances (all weights equal) would z-score to an
        # identically-zero objective; keep the raw weights there so the
        # clause-count signal survives.
        if std:
            self._weights = (raw_weights - np.mean(raw_weights)) / std
        else:
            self._weights = raw_weights
        self._problem = _bool_problem(self._n_variables)

    @classmethod
    def from_file(cls, path: str) -> "MAXSATExperimenter":
        with open(path, "rt") as f:
            return cls(f.read())

    @property
    def num_variables(self) -> int:
        return self._n_variables

    @property
    def num_clauses(self) -> int:
        return len(self._weights)

    def evaluate_batch(self, assignments: np.ndarray) -> np.ndarray:
        """``[B, n] bool -> [B]`` objective values (vectorized)."""
        x = np.asarray(assignments, dtype=bool)
        lit_ok = x[:, self._var_idx] == self._want_true[None]  # [B, C, L]
        satisfied = (lit_ok & self._mask[None]).any(axis=-1)  # [B, C]
        return -(satisfied @ self._weights)

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        x = np.stack(
            [_bool_vector(t, self._n_variables).astype(bool) for t in suggestions]
        )
        values = self.evaluate_batch(x)
        for t, v in zip(suggestions, values):
            t.complete(trial_.Measurement(metrics={"main_objective": float(v)}))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return copy.deepcopy(self._problem)
