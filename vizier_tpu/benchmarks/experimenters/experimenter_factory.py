"""Experimenter factories: named benchmark construction.

Parity with
``/root/reference/vizier/_src/benchmarks/experimenters/experimenter_factory.py:44,110``:
``BBOBFactory``/``SingleObjectiveExperimenterFactory`` build (optionally
shifted/noised/discretized) objectives by name — the configuration unit
benchmark sweeps iterate over.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base, wrappers
from vizier_tpu.benchmarks.experimenters.synthetic import bbob


@dataclasses.dataclass
class SingleObjectiveExperimenterFactory:
    """Builds a BBOB experimenter by name with standard wrappers."""

    name: str
    dim: int = 4
    shift: Optional[np.ndarray] = None
    noise_std: Optional[float] = None
    discrete_dict: Optional[dict] = None
    seed: int = 0

    def __call__(self) -> base.Experimenter:
        if self.name not in bbob.BBOB_FUNCTIONS:
            raise ValueError(
                f"Unknown BBOB function {self.name!r}; "
                f"choices: {sorted(bbob.BBOB_FUNCTIONS)}"
            )
        exptr: base.Experimenter = base.NumpyExperimenter(
            bbob.BBOB_FUNCTIONS[self.name], base.bbob_problem(self.dim)
        )
        if self.shift is not None:
            exptr = wrappers.ShiftingExperimenter(exptr, np.asarray(self.shift))
        if self.discrete_dict:
            exptr = wrappers.DiscretizingExperimenter(exptr, self.discrete_dict)
        if self.noise_std is not None:
            exptr = wrappers.NoisyExperimenter(
                exptr, noise_std=self.noise_std, seed=self.seed
            )
        return exptr

    @property
    def description(self) -> str:
        parts = [f"{self.name}_{self.dim}d"]
        if self.shift is not None:
            parts.append("shifted")
        if self.noise_std:
            parts.append(f"noise{self.noise_std}")
        return "_".join(parts)
