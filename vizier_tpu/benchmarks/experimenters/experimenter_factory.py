"""Experimenter factories: named benchmark construction.

Parity with
``/root/reference/vizier/_src/benchmarks/experimenters/experimenter_factory.py:44,110``:
``BBOBFactory``/``SingleObjectiveExperimenterFactory`` build (optionally
shifted/noised/discretized) objectives by name — the configuration unit
benchmark sweeps iterate over.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from vizier_tpu.benchmarks.experimenters import base, wrappers
from vizier_tpu.benchmarks.experimenters.synthetic import bbob


@dataclasses.dataclass
class SingleObjectiveExperimenterFactory:
    """Builds a BBOB experimenter by name with standard wrappers."""

    name: str
    dim: int = 4
    shift: Optional[np.ndarray] = None
    noise_std: Optional[float] = None
    noise_type: Optional[str] = None  # BBOB-noisy zoo (wrappers.NOISE_TYPES)
    discrete_dict: Optional[dict] = None
    seed: int = 0

    def __call__(self) -> base.Experimenter:
        if self.name not in bbob.BBOB_FUNCTIONS:
            raise ValueError(
                f"Unknown BBOB function {self.name!r}; "
                f"choices: {sorted(bbob.BBOB_FUNCTIONS)}"
            )
        if self.noise_std is not None and self.noise_type is not None:
            raise ValueError("Pass noise_std OR noise_type, not both.")
        exptr: base.Experimenter = base.NumpyExperimenter(
            bbob.BBOB_FUNCTIONS[self.name], base.bbob_problem(self.dim)
        )
        if self.shift is not None:
            exptr = wrappers.ShiftingExperimenter(exptr, np.asarray(self.shift))
        if self.discrete_dict:
            exptr = wrappers.DiscretizingExperimenter(exptr, self.discrete_dict)
        if self.noise_std is not None:
            exptr = wrappers.NoisyExperimenter(
                exptr, noise_std=self.noise_std, seed=self.seed
            )
        elif self.noise_type is not None:
            # Reference factory parity (experimenter_factory.py:199-201):
            # the named BBOB-noisy model, case-insensitive.
            exptr = wrappers.NoisyExperimenter.from_type(
                exptr, self.noise_type.upper(), seed=self.seed
            )
        return exptr

    @property
    def description(self) -> str:
        parts = [f"{self.name}_{self.dim}d"]
        if self.shift is not None:
            parts.append("shifted")
        if self.noise_std:
            parts.append(f"noise{self.noise_std}")
        if self.noise_type:
            parts.append(self.noise_type.lower())
        return "_".join(parts)


def shifted_bbob_instance(
    fn_name: str, seed: int, dim: int = 20, shift_range: float = 2.0
) -> base.Experimenter:
    """THE pinned per-seed shifted BBOB instance the repo's evidence uses.

    One definition shared by ``parity_suite.py`` (the committed
    ``regret_report_r4.json``), the CI convergence gate
    (``tests/designers/test_convergence_gates.py::TestShifted20DGates``)
    and ``tools/budget_policy_ab.py`` — editing the recipe here moves all
    three together, so the gate can never silently diverge from the
    published evidence. Mirrors the reference factory's shift application
    (``experimenter_factory.py:151-153``): the optimum moves off the
    search-box center, so center-seeding cannot fake convergence.
    """
    shift = np.random.default_rng(1000 + seed).uniform(
        -shift_range, shift_range, size=dim
    )
    fn = bbob.BBOB_FUNCTIONS.get(fn_name) or bbob.EXTRA_FUNCTIONS.get(fn_name)
    if fn is None:
        valid = sorted(bbob.BBOB_FUNCTIONS) + sorted(bbob.EXTRA_FUNCTIONS)
        raise ValueError(f"Unknown function {fn_name!r}; choices: {valid}")
    return wrappers.ShiftingExperimenter(
        base.NumpyExperimenter(fn, base.bbob_problem(dim)),
        shift=shift,
    )
