"""Experimenter ABC and numpy-function experimenter.

Parity with
``/root/reference/vizier/_src/benchmarks/experimenters/experimenter.py:40``
and ``numpy_experimenter.py:147``: an Experimenter evaluates trials in place
(attaching final measurements) and owns its problem statement.
"""

from __future__ import annotations

import abc
import math
from typing import Callable, List, Optional, Sequence

import numpy as np

from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


class Experimenter(abc.ABC):
    """A benchmark objective."""

    @abc.abstractmethod
    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        """Completes each trial with a final measurement (in place)."""

    @abc.abstractmethod
    def problem_statement(self) -> base_study_config.ProblemStatement:
        ...


class NumpyExperimenter(Experimenter):
    """Wraps ``f: [N, D] -> [N]`` over a flat double search space.

    The objective name is ``value`` and the goal is MINIMIZE by default
    (BBOB convention).
    """

    def __init__(
        self,
        impl: Callable[[np.ndarray], np.ndarray],
        problem: base_study_config.ProblemStatement,
        *,
        metric_name: Optional[str] = None,
    ):
        self._impl = impl
        self._problem = problem
        self._metric_name = metric_name or problem.metric_information.item().name
        self._param_names = [p.name for p in problem.search_space.parameters]

    def evaluate(self, suggestions: Sequence[trial_.Trial]) -> None:
        if not suggestions:
            return
        xs = np.asarray(
            [
                [float(t.parameters.get_value(name)) for name in self._param_names]
                for t in suggestions
            ]
        )
        values = np.atleast_1d(np.asarray(self._impl(xs)))
        if values.ndim == 1 and len(values) == len(suggestions):
            pass
        elif values.size == len(suggestions):
            values = values.reshape(len(suggestions))
        else:
            raise ValueError(
                f"Objective returned shape {values.shape} for {len(suggestions)} trials."
            )
        for t, v in zip(suggestions, values):
            v = float(v)
            if math.isnan(v):
                t.complete(infeasibility_reason="NaN objective.")
            else:
                t.complete(trial_.Measurement(metrics={self._metric_name: v}))

    def problem_statement(self) -> base_study_config.ProblemStatement:
        return self._problem

    def __repr__(self) -> str:
        return f"NumpyExperimenter({getattr(self._impl, '__name__', self._impl)!r})"


def bbob_problem(
    dimension: int,
    *,
    low: float = -5.0,
    high: float = 5.0,
    metric_name: str = "bbob_eval",
) -> base_study_config.ProblemStatement:
    """The standard BBOB problem shell: D doubles in [-5, 5], MINIMIZE."""
    problem = base_study_config.ProblemStatement()
    root = problem.search_space.root
    for i in range(dimension):
        root.add_float_param(f"x{i}", low, high)
    problem.metric_information.append(
        base_study_config.MetricInformation(
            name=metric_name, goal=base_study_config.ObjectiveMetricGoal.MINIMIZE
        )
    )
    return problem
