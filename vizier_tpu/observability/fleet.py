"""Fleet aggregation: merge per-replica observability dumps into one view.

A sharded tier has no single span ring or metrics registry: every replica
process (or every replica of an in-process :class:`ReplicaManager`) owns a
slice of the fleet's traces. This module defines the **dump format** —
three files per source, ``<source>-spans.jsonl`` (one span per line,
exactly what ``Tracer.dump_jsonl`` writes), ``<source>-metrics.json``
(``MetricsRegistry.snapshot()``), and ``<source>-recorder.json`` (the
flight recorder's time-ordered event list) — and the **merge**: spans from
N sources stitched back into single cross-replica traces (trace context
already propagates across the wire via the request protos), plus the
failover timeline reconstructed from the recorder's ``replica_*`` events.

File-based on purpose: a dump directory survives the processes that wrote
it, ships in a bug report, and needs no collector sidecar. Stdlib-only —
``tools/obs_report.py --fleet`` runs this on machines without jax.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

SPAN_SUFFIX = "-spans.jsonl"
METRICS_SUFFIX = "-metrics.json"
RECORDER_SUFFIX = "-recorder.json"

# Recorder event kinds that make up the failover timeline.
_TIMELINE_KINDS = (
    "replica_killed",
    "replica_failover",
    "replica_revive",
    "slo_breach",
)


def dump_process(
    out_dir: str,
    source: str,
    tracer=None,
    registry=None,
    recorder=None,
) -> Dict[str, str]:
    """Writes one source's span/metric/recorder dumps into ``out_dir``.

    ``source`` is the replica id (or ``"client"`` for unattributed spans).
    Pass only the pieces the process has; missing ones write no file.
    Returns the paths written, keyed ``spans``/``metrics``/``recorder``.
    """
    os.makedirs(out_dir, exist_ok=True)
    written: Dict[str, str] = {}
    if tracer is not None and getattr(tracer, "enabled", True):
        path = os.path.join(out_dir, source + SPAN_SUFFIX)
        tracer.dump_jsonl(path)
        written["spans"] = path
    if registry is not None:
        path = os.path.join(out_dir, source + METRICS_SUFFIX)
        with open(path, "w") as f:
            json.dump(registry.snapshot(), f, sort_keys=True)
        written["metrics"] = path
    if recorder is not None and getattr(recorder, "enabled", False):
        path = os.path.join(out_dir, source + RECORDER_SUFFIX)
        recorder.dump_json(path)
        written["recorder"] = path
    return written


def write_spans(out_dir: str, source: str, spans: List[dict]) -> str:
    """Writes an explicit span list as ``<source>-spans.jsonl`` (the
    split-by-replica path of ``ReplicaManager.dump_observability``)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, source + SPAN_SUFFIX)
    with open(path, "w") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
    return path


def _load_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                item = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(item, dict):
                out.append(item)
    return out


def load_fleet_dir(dump_dir: str) -> Dict[str, Dict[str, Any]]:
    """Reads every dump in ``dump_dir``:
    ``{"spans": {source: [span...]}, "metrics": {...}, "recorder": {...}}``.
    """
    spans: Dict[str, List[dict]] = {}
    metrics: Dict[str, dict] = {}
    recorder: Dict[str, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(dump_dir, "*" + SPAN_SUFFIX))):
        source = os.path.basename(path)[: -len(SPAN_SUFFIX)]
        spans[source] = _load_jsonl(path)
    for path in sorted(glob.glob(os.path.join(dump_dir, "*" + METRICS_SUFFIX))):
        source = os.path.basename(path)[: -len(METRICS_SUFFIX)]
        try:
            with open(path) as f:
                loaded = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(loaded, dict):
            metrics[source] = loaded
    for path in sorted(
        glob.glob(os.path.join(dump_dir, "*" + RECORDER_SUFFIX))
    ):
        source = os.path.basename(path)[: -len(RECORDER_SUFFIX)]
        try:
            with open(path) as f:
                loaded = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(loaded, list):
            recorder[source] = [e for e in loaded if isinstance(e, dict)]
    return {"spans": spans, "metrics": metrics, "recorder": recorder}


def merge_spans(per_source: Dict[str, List[dict]]) -> List[dict]:
    """One flat span list, each span stamped with its dump ``source``,
    ordered by start time — the cross-replica trace substrate."""
    merged: List[dict] = []
    for source, spans in sorted(per_source.items()):
        for span in spans:
            span = dict(span)
            span["source"] = source
            merged.append(span)
    merged.sort(key=lambda s: s.get("start_time", 0.0))
    return merged


def cross_replica_traces(merged: List[dict]) -> List[dict]:
    """Traces whose spans came from 2+ distinct dump sources — one request
    observed end-to-end across processes/replicas, stitched back together
    by the propagated trace id."""
    by_trace: Dict[str, Dict[str, Any]] = {}
    for span in merged:
        trace_id = span.get("trace_id")
        if not trace_id:
            continue
        row = by_trace.setdefault(
            trace_id, {"trace_id": trace_id, "sources": set(), "spans": 0}
        )
        row["sources"].add(span.get("source", ""))
        row["spans"] += 1
    out = [
        {**row, "sources": sorted(row["sources"])}
        for row in by_trace.values()
        if len(row["sources"]) >= 2
    ]
    out.sort(key=lambda row: (-row["spans"], row["trace_id"]))
    return out


def failover_timeline(
    per_source_events: Dict[str, List[dict]],
) -> List[dict]:
    """The fleet's topology-change history, time-ordered: kill, failover
    (with successor list), revive, and SLO breach events from every
    source's flight-recorder dump."""
    timeline: List[dict] = []
    for source, events in sorted(per_source_events.items()):
        for event in events:
            if event.get("kind") not in _TIMELINE_KINDS:
                continue
            row = {
                "time": event.get("time"),
                "kind": event.get("kind"),
                "source": source,
            }
            row.update(event.get("attributes") or {})
            timeline.append(row)
    timeline.sort(key=lambda row: row.get("time") or 0.0)
    return timeline


def slo_series(metrics_snapshot: dict) -> Dict[str, Any]:
    """The ``vizier_slo_*`` families from one ``MetricsRegistry.snapshot()``
    dump, keyed by metric name — the SLO section of a merged report."""
    out: Dict[str, Any] = {}
    for name, family in sorted(metrics_snapshot.items()):
        if name.startswith("vizier_slo_") and isinstance(family, dict):
            out[name] = family.get("series", {})
    return out


# Frontend-side spans of the remote Pythia hop (distributed.compute_tier
# stamps frontend=<replica_id> on these, so a merged dump can attribute
# fan-in per frontend).
_COMPUTE_TIER_SPANS = (
    "compute_tier.remote_suggest",
    "compute_tier.remote_early_stop",
)


def compute_tier_section(
    merged: List[dict], metrics: Dict[str, dict]
) -> Dict[str, Any]:
    """The disaggregated-compute view of a merged dump: which frontends
    crossed the remote Pythia hop (fan-in), and the compute server's
    batch-flush occupancy — the number the tier exists to raise (N
    frontends' same-bucket suggests fusing into one vmapped flush)."""
    per_frontend: Dict[str, int] = {}
    remote_spans = 0
    for span in merged:
        if span.get("name") not in _COMPUTE_TIER_SPANS:
            continue
        remote_spans += 1
        frontend = (span.get("attributes") or {}).get("frontend") or span.get(
            "source", ""
        )
        per_frontend[frontend] = per_frontend.get(frontend, 0) + 1
    occupancy: Dict[str, float] = {}
    for source, snapshot in sorted(metrics.items()):
        family = snapshot.get("vizier_batch_occupancy")
        if not isinstance(family, dict):
            continue
        total = count = 0.0
        for series in (family.get("series") or {}).values():
            total += float(series.get("sum", 0.0))
            count += float(series.get("count", 0.0))
        if count > 0:
            occupancy[source] = round(total / count, 3)
    return {
        "remote_spans": remote_spans,
        "frontends": sorted(per_frontend),
        "fan_in": len(per_frontend),
        "per_frontend": dict(sorted(per_frontend.items())),
        "batch_occupancy": occupancy,
    }


def fleet_report(dump_dir: str) -> Dict[str, Any]:
    """The merged fleet view of one dump directory (JSON-ready)."""
    loaded = load_fleet_dir(dump_dir)
    merged = merge_spans(loaded["spans"])
    crossing = cross_replica_traces(merged)
    trace_ids = {s.get("trace_id") for s in merged if s.get("trace_id")}
    slo: Dict[str, Any] = {}
    for _source, snapshot in sorted(loaded["metrics"].items()):
        for name, series in slo_series(snapshot).items():
            slo.setdefault(name, {}).update(series)
    return {
        "dump_dir": dump_dir,
        "sources": sorted(loaded["spans"]),
        "spans": len(merged),
        "traces": len(trace_ids),
        "cross_replica_traces": len(crossing),
        "cross_replica_examples": crossing[:10],
        "failover_timeline": failover_timeline(loaded["recorder"]),
        "slo": slo,
        "compute_tier": compute_tier_section(merged, loaded["metrics"]),
    }


def merged_trace(dump_dir: str, trace_id: str) -> List[dict]:
    """One cross-replica trace's spans (source-stamped, time-ordered)."""
    loaded = load_fleet_dir(dump_dir)
    merged = merge_spans(loaded["spans"])
    return [s for s in merged if s.get("trace_id") == trace_id]


def render_fleet_report(report: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`fleet_report`'s output."""
    lines = [
        f"fleet dump: {report['dump_dir']}",
        f"sources: {', '.join(report['sources']) or '(none)'}",
        f"{report['spans']} spans across {report['traces']} traces; "
        f"{report['cross_replica_traces']} cross-replica",
    ]
    for row in report["cross_replica_examples"]:
        lines.append(
            f"  trace {row['trace_id']}: {row['spans']} spans over "
            f"{', '.join(row['sources'])}"
        )
    timeline = report["failover_timeline"]
    if timeline:
        lines.append("failover timeline:")
        for event in timeline:
            extras = {
                k: v
                for k, v in event.items()
                if k not in ("time", "kind", "source")
            }
            note = f" {extras}" if extras else ""
            lines.append(
                f"  t={event.get('time'):.3f} [{event['source']}] "
                f"{event['kind']}{note}"
            )
    else:
        lines.append("failover timeline: (no events)")
    if report["slo"]:
        lines.append("slo gauges: " + ", ".join(sorted(report["slo"])))
    tier = report.get("compute_tier") or {}
    if tier.get("remote_spans"):
        occupancy = tier.get("batch_occupancy") or {}
        occ_note = (
            "; ".join(f"{src} occupancy {val}" for src, val in occupancy.items())
            or "no occupancy histograms"
        )
        lines.append(
            f"compute tier: {tier['remote_spans']} remote hops from "
            f"{tier['fan_in']} frontend(s) "
            f"({', '.join(tier['frontends'])}); {occ_note}"
        )
    return "\n".join(lines)
