"""Lightweight tracer: spans, contextvar nesting, cross-process propagation.

One trace follows a SuggestTrials request across all four hops — client RPC
→ Vizier service → Pythia dispatch (worker thread) → designer compute. The
active span lives in a ``contextvars.ContextVar`` so nesting is automatic
within a thread; across threads and processes the ``trace_id``/``span_id``
pair travels as a compact ``"<trace_id>-<span_id>"`` string in request
protos (``trace_context`` fields, see ``tools/regen_protos.py``) and is
re-attached with :meth:`Tracer.use_context`.

Timing is monotonic (``time.perf_counter`` for durations; ``time.time``
only stamps the start for human-readable export). Finished spans land in a
bounded ring buffer (no leak under sustained traffic) and can be dumped as
JSON lines — no third-party deps anywhere.

With observability off, :func:`get_tracer` returns the singleton
:data:`NOOP_TRACER` whose ``span()`` hands back a reusable no-op context
manager: no allocation, no contextvar write, ≈ zero overhead.
"""

from __future__ import annotations

import collections
import contextvars
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Union

from vizier_tpu.observability import config as config_lib

# The active span (or a remote SpanContext attached via use_context).
_SPAN_VAR: contextvars.ContextVar = contextvars.ContextVar(
    "vizier_tpu_active_span", default=None
)


class SpanContext:
    """The propagatable identity of a span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SpanContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __repr__(self) -> str:
        return f"SpanContext({self.trace_id!r}, {self.span_id!r})"


def format_context(ctx: Optional[SpanContext]) -> str:
    """Wire form for request metadata; '' when there is nothing to carry."""
    if ctx is None:
        return ""
    return f"{ctx.trace_id}-{ctx.span_id}"


def parse_context(wire: str) -> Optional[SpanContext]:
    """Inverse of :func:`format_context`; malformed input degrades to None
    (a bad header must never fail the request it rides on)."""
    if not wire or "-" not in wire:
        return None
    trace_id, _, span_id = wire.rpartition("-")
    if not trace_id or not span_id:
        return None
    return SpanContext(trace_id, span_id)


# Span/trace ids only need collision-resistance, not UUID semantics; a
# process-seeded Mersenne generator is ~10x cheaper than uuid4 per id, and
# id minting sits on every traced hop of the suggest hot path (measured 6
# ids per served trial). getrandbits is one atomic C call — thread-safe
# under the GIL.
_ID_RNG = random.Random(os.urandom(16))


def _new_trace_id() -> str:
    return f"{_ID_RNG.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_ID_RNG.getrandbits(64):016x}"


class Span:
    """One timed operation; mutable until :meth:`end`."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "attributes",
        "events",
        "links",
        "status",
        "start_time",
        "duration_secs",
        "_t0",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        attributes: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[Dict[str, Any]] = []
        self.links: List[Dict[str, str]] = []
        self.status = "ok"
        self.start_time = time.time()
        self.duration_secs: Optional[float] = None
        self._t0 = time.perf_counter()

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append(
            {
                "name": name,
                "offset_secs": time.perf_counter() - self._t0,
                **({"attributes": attributes} if attributes else {}),
            }
        )

    def add_link(self, ctx: Optional[SpanContext], name: str = "") -> None:
        """Associates another span (e.g. a coalesced leader's computation)
        without making it a parent."""
        if ctx is None:
            return
        link = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
        if name:
            link["name"] = name
        self.links.append(link)

    def record_exception(self, error: BaseException) -> None:
        self.status = "error"
        self.attributes.setdefault("error.type", type(error).__name__)
        self.attributes.setdefault("error.message", str(error)[:500])

    def end(self) -> None:
        if self.duration_secs is None:
            self.duration_secs = time.perf_counter() - self._t0

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "duration_secs": self.duration_secs,
            "status": self.status,
        }
        if self.attributes:
            out["attributes"] = self.attributes
        if self.events:
            out["events"] = self.events
        if self.links:
            out["links"] = self.links
        return out


class _NoopSpan:
    """Absorbs the whole Span API; one shared instance, zero state."""

    __slots__ = ()

    def context(self):
        return None

    def set_attribute(self, key, value):
        pass

    def add_event(self, name, **attributes):
        pass

    def add_link(self, ctx, name=""):
        pass

    def record_exception(self, error):
        pass

    def end(self):
        pass

    def to_dict(self):
        return {}


NOOP_SPAN = _NoopSpan()


class _NoopSpanCM:
    """Reusable no-op context manager — ``span()`` off the hot path."""

    __slots__ = ()

    def __enter__(self):
        return NOOP_SPAN

    def __exit__(self, *exc):
        return False


_NOOP_CM = _NoopSpanCM()


class _SpanCM:
    """Context manager for one active span (cheaper than a generator CM)."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._token = _SPAN_VAR.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _SPAN_VAR.reset(self._token)
        if exc is not None:
            self._span.record_exception(exc)
        self._span.end()
        self._tracer._export(self._span)
        return False


class _ContextCM:
    """Attaches a remote SpanContext as the ambient parent for a block."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[SpanContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None:
            self._token = _SPAN_VAR.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._token is not None:
            _SPAN_VAR.reset(self._token)
        return False


Parent = Union[Span, SpanContext, None]


class Tracer:
    """Creates spans, tracks the active one, rings finished ones."""

    enabled = True

    def __init__(
        self,
        max_spans: int = 4096,
        export_path: Optional[str] = None,
    ):
        self._lock = threading.Lock()
        self._finished: "collections.deque[Span]" = collections.deque(
            maxlen=max(1, max_spans)
        )
        self._export_path = export_path or None
        self._export_file = None

    # -- span lifecycle ----------------------------------------------------

    def span(self, name: str, parent: Parent = None, **attributes: Any) -> _SpanCM:
        """Context manager: opens a child of ``parent`` (default: the
        ambient span/context), makes it current, exports it on exit."""
        if parent is None:
            parent = _SPAN_VAR.get()
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, SpanContext):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        span = Span(name, trace_id, _new_span_id(), parent_id, attributes)
        return _SpanCM(self, span)

    def use_context(self, ctx: Optional[SpanContext]) -> _ContextCM:
        """Re-attaches a propagated context (thread hop / wire hop)."""
        return _ContextCM(ctx)

    def current_span(self) -> Optional[Span]:
        cur = _SPAN_VAR.get()
        return cur if isinstance(cur, Span) else None

    def current_context(self) -> Optional[SpanContext]:
        cur = _SPAN_VAR.get()
        if isinstance(cur, Span):
            return cur.context()
        if isinstance(cur, SpanContext):
            return cur
        return None

    # -- export ------------------------------------------------------------

    def _export(self, span: Span) -> None:
        with self._lock:
            self._finished.append(span)
            if self._export_path is not None:
                try:
                    if self._export_file is None:
                        self._export_file = open(self._export_path, "a")
                    self._export_file.write(json.dumps(span.to_dict()) + "\n")
                    self._export_file.flush()
                except OSError:
                    self._export_path = None  # sink gone; keep serving

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def drain(self) -> List[Span]:
        """Pops and returns every finished span (oldest first)."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
        return out

    def spans_for_trace(self, trace_id: str) -> List[Span]:
        """One trace's finished spans, ordered by start time."""
        return sorted(
            (s for s in self.finished_spans() if s.trace_id == trace_id),
            key=lambda s: s.start_time,
        )

    def dump_jsonl(self, path: str) -> int:
        """Writes the ring buffer to ``path`` as JSON lines; returns count."""
        spans = self.finished_spans()
        with open(path, "w") as f:
            for span in spans:
                f.write(json.dumps(span.to_dict()) + "\n")
        return len(spans)

    def close(self) -> None:
        with self._lock:
            if self._export_file is not None:
                try:
                    self._export_file.close()
                finally:
                    self._export_file = None


class NoopTracer:
    """The off switch: same API, no state, no allocation per span."""

    enabled = False

    def span(self, name: str, parent: Parent = None, **attributes: Any):
        return _NOOP_CM

    def use_context(self, ctx):
        return _NOOP_CM

    def current_span(self):
        return None

    def current_context(self):
        return None

    def finished_spans(self):
        return []

    def drain(self):
        return []

    def spans_for_trace(self, trace_id: str):
        return []

    def dump_jsonl(self, path: str) -> int:
        return 0

    def close(self) -> None:
        pass


NOOP_TRACER = NoopTracer()

_global_tracer: Optional[Union[Tracer, NoopTracer]] = None
_global_lock = threading.Lock()


def _tracer_from_config(
    config: config_lib.ObservabilityConfig,
) -> Union[Tracer, NoopTracer]:
    if not config.tracing_on:
        return NOOP_TRACER
    return Tracer(
        max_spans=config.span_buffer_size,
        export_path=config.span_log_path or None,
    )


def get_tracer() -> Union[Tracer, NoopTracer]:
    """The process-global tracer, built from the env config on first use."""
    global _global_tracer
    tracer = _global_tracer
    if tracer is None:
        with _global_lock:
            if _global_tracer is None:
                _global_tracer = _tracer_from_config(
                    config_lib.ObservabilityConfig.from_env()
                )
            tracer = _global_tracer
    return tracer


def set_tracer(
    tracer: Optional[Union[Tracer, NoopTracer]],
) -> Optional[Union[Tracer, NoopTracer]]:
    """Swaps the global tracer (tests/tools); None re-derives from env on
    next use. Returns the previous tracer."""
    global _global_tracer
    with _global_lock:
        old, _global_tracer = _global_tracer, tracer
    return old


def add_current_event(name: str, **attributes: Any) -> None:
    """Adds an event to the active span, if any (deep-callee convenience —
    e.g. breaker transitions firing inside a designer computation)."""
    span = get_tracer().current_span()
    if span is not None:
        span.add_event(name, **attributes)
