"""SLO engine: declarative objectives over sliding metric windows.

Production serving is operated against *objectives*, not raw counters:
"99% of suggests under X ms", "speculative hit rate above Y", "fallback
rate below Z". This module evaluates those objectives over sliding windows
of the existing :class:`~vizier_tpu.observability.metrics.MetricsRegistry`
— the engine snapshots the metrics it needs on every evaluation and
differences the snapshots at each window boundary, so cumulative counters
and histograms become windowed rates without a scrape pipeline.

Each (SLO, window) pair yields an **error-budget burn rate**: the
fraction of the window's traffic that violated the objective, divided by
the fraction the objective allows. Burn 1.0 = spending budget exactly at
the allowed rate; > 1.0 sustained = the objective is being missed. Multi-
window evaluation (fast + slow windows, Google SRE style) separates a
transient spike from a sustained regression. Results are exported as
``vizier_slo_*`` gauges in the same registry, and surface through
``ServingRuntime.slo_report()``.

A breach (burn over the threshold in any window, with enough samples)
triggers the **black-box dump**: the breaching SLO statuses, the latency
histogram's exemplar trace ids (with their full traces from the span
ring, when available), the flight-recorder rings, and a metrics snapshot
— one JSON file that answers "why did p99 spike" after the fact.

Everything is opt-in (``VIZIER_SLO=1``) and stdlib-only; off = no engine
object, no sampling thread, zero overhead.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from vizier_tpu.analysis import registry as _registry
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib

_logger = logging.getLogger(__name__)

_SUGGEST_HIST = "vizier_suggest_latency_seconds"
_OCCUPANCY_HIST = "vizier_batch_occupancy"
_FLUSH_COUNTER = "vizier_batch_flushes"


def _parse_windows(raw: str) -> Tuple[float, ...]:
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            value = float(part)
        except ValueError:
            continue
        if value > 0:
            out.append(value)
    return tuple(out) or (60.0, 300.0)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Knobs for the SLO engine (``VIZIER_SLO*``)."""

    # Off by default: arming SLOs starts the sampler and (optionally) the
    # background evaluator thread.
    enabled: bool = False
    # Sliding windows (seconds) every SLO is evaluated over.
    windows: Tuple[float, ...] = (60.0, 300.0)
    # Background evaluation cadence; 0 = manual ``evaluate()`` only.
    eval_interval_s: float = 1.0
    # Objective: 99% of suggests (per hop) complete under this many ms.
    suggest_p99_ms: float = 5000.0
    # Objective: speculative serve outcomes hit at least this rate
    # (evaluated only when speculative traffic exists in the window).
    speculative_hit_rate: float = 0.8
    # Objective: at most this fraction of suggests served by the
    # quasi-random reliability fallback.
    fallback_rate: float = 0.05
    # Objective: at most this fraction of suggests shed by the admission
    # controller (vizier_tpu.serving.admission; evaluated only when the
    # window saw any admission traffic).
    shed_rate: float = 0.05
    # Objective: mean batch-flush occupancy at least this many real slots
    # (padding-waste proxy; 1.0 = always satisfied, raise to enforce).
    occupancy_min: float = 1.0
    # Objective: busiest/least-busy mesh placement flush share ratio at
    # most this (skipped below two active placements).
    mesh_imbalance_max: float = 4.0
    # Breach handling: black-box dumps land here ('' = no dumps, the
    # breach still exports gauges and records a flight-recorder event).
    dump_dir: str = ""
    # A window needs at least this many observations before it can breach.
    min_samples: int = 5
    # Burn rate at or above which a window counts as breaching.
    burn_threshold: float = 1.0
    # Minimum seconds between black-box dumps for the same SLO.
    breach_cooldown_s: float = 30.0

    @classmethod
    def from_env(cls) -> "SloConfig":
        return cls(
            enabled=_registry.env_on("VIZIER_SLO"),
            windows=_parse_windows(
                _registry.env_str("VIZIER_SLO_WINDOWS", "60,300")
            ),
            eval_interval_s=_registry.env_float(
                "VIZIER_SLO_EVAL_INTERVAL_S", 1.0
            ),
            suggest_p99_ms=_registry.env_float(
                "VIZIER_SLO_SUGGEST_P99_MS", 5000.0
            ),
            speculative_hit_rate=_registry.env_float(
                "VIZIER_SLO_SPECULATIVE_HIT_RATE", 0.8
            ),
            fallback_rate=_registry.env_float("VIZIER_SLO_FALLBACK_RATE", 0.05),
            shed_rate=_registry.env_float("VIZIER_SLO_SHED_RATE", 0.05),
            dump_dir=_registry.env_str("VIZIER_SLO_DUMP_DIR"),
        )

    def as_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["windows"] = list(self.windows)
        return out


@dataclasses.dataclass
class SloStatus:
    """One (SLO, window) evaluation result."""

    slo: str
    window_secs: float
    # The windowed value of whatever the SLO measures (p99 seconds, hit
    # rate, fallback rate, mean occupancy, imbalance ratio); None when the
    # window held no relevant traffic.
    value: Optional[float]
    threshold: float
    total: int
    bad: int
    burn_rate: Optional[float]
    breached: bool

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _Sample:
    """One point-in-time snapshot of the metrics the SLOs consume."""

    __slots__ = ("t", "counters", "hists")

    def __init__(self, t: float):
        self.t = t
        # name -> {labelkey: value}
        self.counters: Dict[str, Dict] = {}
        # name -> {labelkey: (bucket_counts, count, sum)}
        self.hists: Dict[str, Dict] = {}


_EMPTY: Dict = {}


def _delta_counter(
    new: _Sample, old: Optional[_Sample], name: str
) -> Dict[Any, float]:
    """Per-series counter increase between two samples (>= 0)."""
    new_series = new.counters.get(name, _EMPTY)
    old_series = old.counters.get(name, _EMPTY) if old is not None else _EMPTY
    return {
        key: max(0.0, value - old_series.get(key, 0.0))
        for key, value in new_series.items()
    }


def _delta_hist(
    new: _Sample, old: Optional[_Sample], name: str
) -> Dict[Any, Tuple[List[int], int, float]]:
    """Per-series histogram delta ``(bucket_counts, count, sum)``."""
    new_series = new.hists.get(name, _EMPTY)
    old_series = old.hists.get(name, _EMPTY) if old is not None else _EMPTY
    out = {}
    for key, (counts, count, total) in new_series.items():
        old_counts, old_count, old_sum = old_series.get(
            key, ([0] * len(counts), 0, 0.0)
        )
        if len(old_counts) != len(counts):  # bucket layout changed: restart
            old_counts, old_count, old_sum = [0] * len(counts), 0, 0.0
        out[key] = (
            [max(0, n - o) for n, o in zip(counts, old_counts)],
            max(0, count - old_count),
            max(0.0, total - old_sum),
        )
    return out


def _hist_quantile(
    buckets: Sequence[float], counts: Sequence[int], q: float
) -> Optional[float]:
    """Bucket-interpolated quantile of a (windowed) bucket-count vector —
    the same estimator :meth:`Histogram.percentile` applies to cumulative
    state, applied here to a delta."""
    total = sum(counts)
    if total == 0:
        return None
    rank = (q / 100.0) * total
    cumulative = 0
    for i, c in enumerate(counts):
        if cumulative + c >= rank and c > 0:
            if i >= len(buckets):
                return buckets[-1]
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            frac = (rank - cumulative) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cumulative += c
    return buckets[-1]


def _count_above(
    buckets: Sequence[float], counts: Sequence[int], threshold: float
) -> float:
    """Observations above ``threshold``, interpolating inside the crossing
    bucket (bucket-resolution, like every histogram-derived number here)."""
    above = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo = buckets[i - 1] if 0 < i <= len(buckets) else 0.0
        hi = buckets[i] if i < len(buckets) else float("inf")
        if lo >= threshold:
            above += c
        elif hi > threshold and hi != float("inf"):
            above += c * (hi - threshold) / (hi - lo)
        elif hi == float("inf") and threshold <= lo:
            above += c
    return above


class SloEngine:
    """Samples the registry, evaluates the objectives, handles breaches."""

    def __init__(
        self,
        config: SloConfig,
        registry: metrics_lib.MetricsRegistry,
        recorder=None,
    ):
        self.config = config
        self._registry = registry
        self._recorder = (
            recorder if recorder is not None else recorder_lib.get_recorder()
        )
        self._lock = threading.Lock()
        self._samples: List[_Sample] = []
        self._last_dump: Dict[str, float] = {}  # slo name -> dump time
        self.dumps: List[str] = []
        self._counter_names = (
            "vizier_serving_speculative_hits",
            "vizier_serving_speculative_misses",
            "vizier_serving_speculative_stale",
            "vizier_serving_fallbacks",
            "vizier_serving_admission_sheds",
            _FLUSH_COUNTER,
        )
        self._hist_names = (_SUGGEST_HIST, _OCCUPANCY_HIST)
        # vizier_slo_* export surface, co-located with everything else.
        self._burn = registry.gauge(
            "vizier_slo_burn_rate",
            help="Error-budget burn rate per SLO and window (1.0 = on budget).",
        )
        self._value = registry.gauge(
            "vizier_slo_value",
            help="Windowed value of what each SLO measures.",
        )
        self._breached = registry.gauge(
            "vizier_slo_breached",
            help="1 when the SLO breached in any window at last evaluation.",
        )
        self._mesh_util = registry.gauge(
            "vizier_slo_mesh_utilization",
            help="Per-placement share of windowed batch flushes.",
        )
        self._evaluations = registry.counter(
            "vizier_slo_evaluations", help="SLO engine evaluation sweeps."
        )
        self._breaches = registry.counter(
            "vizier_slo_breach_events", help="SLO breach events handled."
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ------------------------------------------------------------

    def _take_sample(self, now: float) -> _Sample:
        sample = _Sample(now)
        for name in self._counter_names:
            metric = self._registry.get(name)
            if isinstance(metric, metrics_lib.Counter):
                sample.counters[name] = metric.series_values()
        for name in self._hist_names:
            metric = self._registry.get(name)
            if isinstance(metric, metrics_lib.Histogram):
                sample.hists[name] = metric.series_data()
        return sample

    def _baseline(self, now: float, window: float) -> Optional[_Sample]:
        """The newest sample at least ``window`` old — or the oldest one
        when the engine has not been alive that long (partial window); None
        means "delta against zero" (everything since process start)."""
        target = now - window
        best = None
        for sample in self._samples:
            if sample.t <= target:
                best = sample
            else:
                break
        if best is None and self._samples:
            oldest = self._samples[0]
            # Within one eval of "now": no usable window yet; fall through
            # to the zero baseline so a single-evaluation run still reports.
            if oldest.t <= target or now - oldest.t >= window * 0.5:
                best = oldest
        return best

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[SloStatus]:
        """One sweep: sample, evaluate every (SLO, window), export gauges,
        and handle any breach. Thread-safe; also the background loop body."""
        now = time.time() if now is None else now
        sample = self._take_sample(now)
        with self._lock:
            statuses = self._evaluate_locked(sample, now)
            breaching = [s for s in statuses if s.breached]
            dump_path = self._handle_breaches_locked(breaching, now)
        self._export(statuses)
        self._evaluations.inc()
        if dump_path is not None:
            # Recorder/log writes outside the engine lock (leaf-lock rule).
            self._recorder.record(
                recorder_lib.FLEET,
                "slo_breach",
                slos=sorted({s.slo for s in breaching}),
                dump=dump_path or None,
            )
            self._breaches.inc()
        return statuses

    def _evaluate_locked(self, sample: _Sample, now: float) -> List[SloStatus]:
        self._samples.append(sample)
        horizon = now - max(self.config.windows) * 1.5 - 2 * max(
            1.0, self.config.eval_interval_s
        )
        while len(self._samples) > 2 and self._samples[0].t < horizon:
            self._samples.pop(0)
        statuses: List[SloStatus] = []
        for window in self.config.windows:
            base = self._baseline(now, window)
            statuses.extend(self._latency_slos(sample, base, window))
            statuses.append(self._hit_rate_slo(sample, base, window))
            statuses.append(self._fallback_slo(sample, base, window))
            statuses.append(self._shed_slo(sample, base, window))
            statuses.append(self._occupancy_slo(sample, base, window))
            statuses.append(self._mesh_slo(sample, base, window))
        return statuses

    def _status(
        self,
        slo: str,
        window: float,
        value: Optional[float],
        threshold: float,
        total: float,
        bad: float,
        allowed_bad_fraction: float,
    ) -> SloStatus:
        burn = None
        breached = False
        if total >= max(1, self.config.min_samples) and value is not None:
            bad_fraction = bad / total
            allowed = max(allowed_bad_fraction, 1e-9)
            burn = bad_fraction / allowed
            breached = burn >= self.config.burn_threshold
        return SloStatus(
            slo=slo,
            window_secs=window,
            value=value,
            threshold=threshold,
            total=int(total),
            bad=int(round(bad)),
            burn_rate=round(burn, 4) if burn is not None else None,
            breached=breached,
        )

    def _latency_slos(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> List[SloStatus]:
        """suggest p99 per hop: 99% of the window's suggests under the
        configured threshold."""
        metric = self._registry.get(_SUGGEST_HIST)
        buckets = metric.buckets if metric is not None else ()
        threshold = self.config.suggest_p99_ms / 1e3
        deltas = _delta_hist(sample, base, _SUGGEST_HIST)
        out = []
        for key, (counts, count, _sum) in sorted(deltas.items()):
            labels = dict(key)
            hop = labels.get("hop", "")
            # The admission plane splits the service hop per tenant: each
            # tenant series becomes its own p99 objective, so one hot
            # tenant's collapse cannot hide inside the fleet aggregate.
            tenant = labels.get("tenant")
            name = f"suggest_p99:{hop}" + (f":{tenant}" if tenant else "")
            p99 = _hist_quantile(buckets, counts, 99) if count else None
            bad = _count_above(buckets, counts, threshold) if count else 0.0
            out.append(
                self._status(
                    name, window, p99, threshold, count, bad,
                    allowed_bad_fraction=0.01,
                )
            )
        return out

    def _hit_rate_slo(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> SloStatus:
        hits = sum(
            _delta_counter(sample, base, "vizier_serving_speculative_hits").values()
        )
        misses = sum(
            _delta_counter(
                sample, base, "vizier_serving_speculative_misses"
            ).values()
        )
        stale = sum(
            _delta_counter(
                sample, base, "vizier_serving_speculative_stale"
            ).values()
        )
        total = hits + misses + stale
        rate = hits / total if total else None
        return self._status(
            "speculative_hit_rate", window, rate,
            self.config.speculative_hit_rate, total, misses + stale,
            allowed_bad_fraction=1.0 - self.config.speculative_hit_rate,
        )

    def _fallback_slo(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> SloStatus:
        fallbacks = sum(
            _delta_counter(sample, base, "vizier_serving_fallbacks").values()
        )
        # Request volume = the pythia hop's windowed suggest count (the hop
        # every served suggestion crosses, fallback or not).
        suggests = 0
        for key, (_counts, count, _sum) in _delta_hist(
            sample, base, _SUGGEST_HIST
        ).items():
            if dict(key).get("hop") == "pythia":
                suggests += count
        rate = fallbacks / suggests if suggests else None
        return self._status(
            "reliability_fallback_rate", window, rate,
            self.config.fallback_rate, suggests, fallbacks,
            allowed_bad_fraction=self.config.fallback_rate,
        )

    def _shed_slo(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> SloStatus:
        """Admission shed fraction: sheds over (sheds + served pythia
        suggests) in the window — the overload plane's own error budget."""
        sheds = sum(
            _delta_counter(
                sample, base, "vizier_serving_admission_sheds"
            ).values()
        )
        suggests = 0
        for key, (_counts, count, _sum) in _delta_hist(
            sample, base, _SUGGEST_HIST
        ).items():
            if dict(key).get("hop") == "pythia":
                suggests += count
        total = suggests + sheds
        rate = sheds / total if total else None
        return self._status(
            "admission_shed_rate", window, rate, self.config.shed_rate,
            total, sheds, allowed_bad_fraction=self.config.shed_rate,
        )

    def _occupancy_slo(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> SloStatus:
        """Mean real slots per flush across every bucket/device series —
        the padding-waste proxy (each padded slot is compute bought and
        thrown away)."""
        total_count, total_sum = 0, 0.0
        for _key, (_counts, count, series_sum) in _delta_hist(
            sample, base, _OCCUPANCY_HIST
        ).items():
            total_count += count
            total_sum += series_sum
        mean = total_sum / total_count if total_count else None
        # "bad" for a floor objective: the occupancy shortfall, expressed
        # as a fraction of the floor, scaled to flush count.
        bad = 0.0
        if mean is not None and self.config.occupancy_min > 0:
            shortfall = max(0.0, self.config.occupancy_min - mean)
            bad = total_count * min(1.0, shortfall / self.config.occupancy_min)
        return self._status(
            "batch_occupancy_mean", window, mean, self.config.occupancy_min,
            total_count, bad, allowed_bad_fraction=1e-9,
        )

    def _mesh_slo(
        self, sample: _Sample, base: Optional[_Sample], window: float
    ) -> SloStatus:
        """Per-placement utilization balance from windowed flush counts."""
        per_device: Dict[str, float] = {}
        for key, value in _delta_counter(
            sample, base, _FLUSH_COUNTER
        ).items():
            device = dict(key).get("device")
            if device is not None:
                per_device[device] = per_device.get(device, 0.0) + value
        total = sum(per_device.values())
        active = {d: v for d, v in per_device.items() if v > 0}
        for device, value in sorted(per_device.items()):
            self._mesh_util.set(value / total if total else 0.0, device=device)
        if len(active) < 2:
            return self._status(
                "mesh_utilization_balance", window, None,
                self.config.mesh_imbalance_max, 0, 0, 1e-9,
            )
        imbalance = max(active.values()) / min(active.values())
        bad = total if imbalance > self.config.mesh_imbalance_max else 0.0
        return self._status(
            "mesh_utilization_balance", window, imbalance,
            self.config.mesh_imbalance_max, total, bad,
            allowed_bad_fraction=1e-9,
        )

    def _export(self, statuses: List[SloStatus]) -> None:
        breached_slos: Dict[str, bool] = {}
        for status in statuses:
            window = f"{int(status.window_secs)}s"
            if status.burn_rate is not None:
                self._burn.set(status.burn_rate, slo=status.slo, window=window)
            if status.value is not None:
                self._value.set(status.value, slo=status.slo, window=window)
            breached_slos[status.slo] = (
                breached_slos.get(status.slo, False) or status.breached
            )
        for slo, breached in breached_slos.items():
            self._breached.set(1.0 if breached else 0.0, slo=slo)

    # -- breach handling -----------------------------------------------------

    def _handle_breaches_locked(
        self, breaching: List[SloStatus], now: float
    ) -> Optional[str]:
        """Returns the dump path ('' when dumps are disabled) on a breach
        worth reporting, None when nothing new breached."""
        due = [
            s
            for s in breaching
            if now - self._last_dump.get(s.slo, -1e18)
            >= self.config.breach_cooldown_s
        ]
        if not due:
            return None
        for status in due:
            self._last_dump[status.slo] = now
        if not self.config.dump_dir:
            return ""
        try:
            path = self._write_blackbox(due, now)
        except OSError as e:  # a full disk must not take serving down
            _logger.warning("SLO black-box dump failed: %s", e)
            return ""
        self.dumps.append(path)
        return path

    def _write_blackbox(self, breaching: List[SloStatus], now: float) -> str:
        """The black-box artifact: enough context to reconstruct the breach
        without the process that served it."""
        os.makedirs(self.config.dump_dir, exist_ok=True)
        exemplars: Dict[str, list] = {}
        metric = self._registry.get(_SUGGEST_HIST)
        if isinstance(metric, metrics_lib.Histogram):
            for key in metric.label_keys():
                labels = dict(key)
                kept = metric.exemplars(**labels)
                if kept:
                    exemplars[labels.get("hop", str(labels))] = kept
        trace_ids = sorted(
            {e["trace_id"] for kept in exemplars.values() for e in kept}
        )
        tracer = tracing_lib.get_tracer()
        exemplar_traces = {
            trace_id: [s.to_dict() for s in tracer.spans_for_trace(trace_id)]
            for trace_id in trace_ids
        }
        payload = {
            "version": 1,
            "time": now,
            "breaching": [s.as_dict() for s in breaching],
            "exemplars": exemplars,
            "exemplar_traces": exemplar_traces,
            "flight_recorder": self._recorder.snapshot(),
            "metrics": self._registry.snapshot(),
            "config": self.config.as_dict(),
        }
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        slug = breaching[0].slo.replace(":", "_").replace("/", "_")
        path = os.path.join(
            self.config.dump_dir,
            f"blackbox-{slug}-{stamp}-{len(self.dumps)}.json",
        )
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
        return path

    # -- report / lifecycle --------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """Evaluates now and returns the JSON-ready SLO report."""
        statuses = self.evaluate()
        return {
            "armed": True,
            "config": self.config.as_dict(),
            "statuses": [s.as_dict() for s in statuses],
            "breaching": sorted({s.slo for s in statuses if s.breached}),
            "dumps": list(self.dumps),
        }

    def start(self) -> bool:
        """Starts the background evaluator (idempotent; False when the
        cadence is 0 = manual-only)."""
        if self.config.eval_interval_s <= 0:
            return False
        with self._lock:
            if self._thread is not None:
                return False
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="vizier-slo-eval", daemon=True
            )
            self._thread.start()
        return True

    def close(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.eval_interval_s):
            try:
                self.evaluate()
            except Exception as e:  # the sweep must never kill the loop
                _logger.warning("SLO evaluation failed: %s", e)
