"""Metrics registry: named counters/gauges/histograms, Prometheus text dump.

Stdlib-only (no prometheus_client in the image). Each metric owns a family
of labeled series; histograms use fixed exponential buckets and estimate
p50/p95/p99 by linear interpolation inside the bucket that crosses the
quantile — the same estimator ``histogram_quantile`` applies server-side,
done here so in-process callers (bench, chaos A/B, obs_report) get
percentiles without a scrape pipeline.

Thread safety: one lock per metric guards its whole series family; metric
*creation* is guarded by the registry lock. Observation cost is a dict
lookup + bisect under a short lock — noise against a multi-ms designer run.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    """``count`` upper bounds growing geometrically from ``start``."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(
            f"Need start > 0, factor > 1, count >= 1; got {start}, {factor}, {count}."
        )
    out = []
    bound = start
    for _ in range(count):
        out.append(bound)
        bound *= factor
    return out


# 1 ms .. ~372 s in x1.3 steps: fine enough that an interpolated p50 of a
# sub-second suggest lands within a few percent of the sample percentile.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    exponential_buckets(0.001, 1.3, 50)
)


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared shell: name, help text, per-metric lock, labeled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, object] = {}

    def label_keys(self) -> List[LabelKey]:
        with self._lock:
            return list(self._series)


class Counter(_Metric):
    """Monotonic counter. Rendered with the ``_total`` suffix."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"Counter {self.name} cannot decrease ({amount}).")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def series_values(self) -> Dict[LabelKey, float]:
        """Every labeled series' value (sliding-window delta material)."""
        with self._lock:
            return {key: float(v) for key, v in self._series.items()}

    def reset(self) -> None:
        """Zeroes every series (in-process test/rollup convenience)."""
        with self._lock:
            for key in self._series:
                self._series[key] = 0.0

    def _render(self, lines: List[str]) -> None:
        with self._lock:
            series = sorted(self._series.items())
        if not series:
            lines.append(f"{self.name}_total 0")
            return
        for key, value in series:
            lines.append(
                f"{self.name}_total{_render_labels(key)} {_format_value(value)}"
            )


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def _render(self, lines: List[str]) -> None:
        with self._lock:
            series = sorted(self._series.items())
        for key, value in series:
            lines.append(f"{self.name}{_render_labels(key)} {_format_value(value)}")


class _HistogramSeries:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, num_buckets: int):
        # One slot per finite bucket plus the +Inf overflow slot.
        self.counts = [0] * (num_buckets + 1)
        self.sum = 0.0
        self.count = 0
        # Top-valued exemplars: [value, trace_id, time] triples, unordered.
        self.exemplars: List[list] = []


class Histogram(_Metric):
    """Fixed-bucket histogram with quantile estimation from the buckets.

    Observations may carry an **exemplar** ``trace_id``: the top
    :data:`MAX_EXEMPLARS` highest-valued observations per series keep
    their trace ids (OpenMetrics-style), so a p99 number links back to
    real traces. Capture is sampling-only metadata — it never changes what
    is counted — and costs one comparison when no trace id is supplied.
    """

    kind = "histogram"

    MAX_EXEMPLARS = 8

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ):
        super().__init__(name, help)
        bounds = sorted(buckets if buckets is not None else DEFAULT_LATENCY_BUCKETS)
        if not bounds:
            raise ValueError(f"Histogram {name} needs at least one bucket.")
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in bounds)

    def observe(
        self, value: float, trace_id: Optional[str] = None, **labels: str
    ) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistogramSeries(len(self.buckets))
            series.counts[idx] += 1
            series.sum += value
            series.count += 1
            if trace_id is not None:
                exemplars = series.exemplars
                if len(exemplars) < self.MAX_EXEMPLARS:
                    exemplars.append([value, trace_id, time.time()])
                else:
                    low = min(range(len(exemplars)), key=lambda i: exemplars[i][0])
                    if value > exemplars[low][0]:
                        exemplars[low] = [value, trace_id, time.time()]

    def exemplars(self, **labels: str) -> List[Dict[str, object]]:
        """The series' kept exemplars, highest value first."""
        with self._lock:
            series = self._series.get(_label_key(labels))
            kept = list(series.exemplars) if series is not None else []
        kept.sort(key=lambda e: e[0], reverse=True)
        return [
            {"value": value, "trace_id": trace_id, "time": t}
            for value, trace_id, t in kept
        ]

    def series_data(self) -> Dict[LabelKey, Tuple[List[int], int, float]]:
        """Per-series ``(bucket_counts, count, sum)`` snapshot — the raw
        material for sliding-window deltas (the SLO engine)."""
        with self._lock:
            return {
                key: (list(s.counts), s.count, s.sum)
                for key, s in self._series.items()
            }

    def count(self, **labels: str) -> int:
        with self._lock:
            series = self._series.get(_label_key(labels))
            return series.count if series is not None else 0

    def sum(self, **labels: str) -> float:
        with self._lock:
            series = self._series.get(_label_key(labels))
            return series.sum if series is not None else 0.0

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        """Bucket-interpolated quantile ``q`` in [0, 100]; None when empty.

        Linear interpolation inside the crossing bucket (lower bound 0 for
        the first); observations past the last finite bound clamp to it, so
        the estimate never invents a value the buckets cannot support.
        """
        if not 0 <= q <= 100:
            raise ValueError(f"Quantile must be in [0, 100], got {q}.")
        with self._lock:
            series = self._series.get(_label_key(labels))
            if series is None or series.count == 0:
                return None
            counts = list(series.counts)
            total = series.count
        rank = (q / 100.0) * total
        cumulative = 0
        for i, c in enumerate(counts):
            if cumulative + c >= rank and c > 0:
                if i >= len(self.buckets):  # +Inf overflow: clamp
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (rank - cumulative) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cumulative += c
        return self.buckets[-1]

    def _render(self, lines: List[str]) -> None:
        with self._lock:
            series = [
                (key, list(s.counts), s.sum, s.count)
                for key, s in sorted(self._series.items())
            ]
        for key, counts, total_sum, total_count in series:
            cumulative = 0
            for bound, c in zip(self.buckets, counts):
                cumulative += c
                labels = _render_labels(key, [("le", _format_value(bound))])
                lines.append(f"{self.name}_bucket{labels} {cumulative}")
            labels = _render_labels(key, [("le", "+Inf")])
            lines.append(f"{self.name}_bucket{labels} {total_count}")
            lines.append(
                f"{self.name}_sum{_render_labels(key)} {_format_value(total_sum)}"
            )
            lines.append(f"{self.name}_count{_render_labels(key)} {total_count}")


class MetricsRegistry:
    """Named metric families; get-or-create with type conflict detection."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"Metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}."
                    )
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)  # type: ignore[return-value]

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)  # type: ignore[return-value]

    def histogram(
        self, name: str, help: str = "", buckets: Optional[Sequence[float]] = None
    ) -> Histogram:
        metric = self._get_or_create(Histogram, name, help, buckets=buckets)
        if buckets is not None and tuple(sorted(float(b) for b in buckets)) != (
            metric.buckets  # type: ignore[union-attr]
        ):
            raise ValueError(f"Histogram {name!r} re-registered with other buckets.")
        return metric  # type: ignore[return-value]

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format (0.0.4)."""
        with self._lock:
            metrics = [self._metrics[name] for name in sorted(self._metrics)]
        lines: List[str] = []
        for metric in metrics:
            if metric.help:
                lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            metric._render(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, dict]:
        """JSON-ready nested dump: name -> {type, series{label_str: value}}."""
        out: Dict[str, dict] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            series: Dict[str, object] = {}
            for key in metric.label_keys():
                label_str = _render_labels(key) or "{}"
                if isinstance(metric, Histogram):
                    labels = dict(key)
                    series[label_str] = {
                        "count": metric.count(**labels),
                        "sum": metric.sum(**labels),
                        "p50": metric.percentile(50, **labels),
                        "p95": metric.percentile(95, **labels),
                        "p99": metric.percentile(99, **labels),
                    }
                else:
                    series[label_str] = metric.value(**dict(key))  # type: ignore[attr-defined]
            out[metric.name] = {"type": metric.kind, "series": series}
        return out

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


_default_registry: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-global registry (designer-level JAX phase timings)."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry


def set_default_registry(registry: Optional[MetricsRegistry]) -> None:
    """Swaps the process-global registry (tests); None resets to fresh-on-use."""
    global _default_registry
    with _default_lock:
        _default_registry = registry
