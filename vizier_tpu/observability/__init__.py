"""vizier_tpu.observability: tracing, metrics, and JAX-aware profiling.

The window into the serving stack: where a SuggestTrials request spends
its time (ARD train vs. acquisition sweep vs. lock/coalescer waits vs. RPC
hops), as spans with cross-process ``trace_id`` propagation; counts and
latency distributions as a Prometheus-dumpable metrics registry; and
compile-vs-execute device timing for the designer hot path.

Everything is stdlib-only and gated by :class:`ObservabilityConfig`
(``VIZIER_OBSERVABILITY=0`` ≈ zero overhead). See
``docs/guides/observability.md``.
"""

from vizier_tpu.observability import fleet
from vizier_tpu.observability.config import ObservabilityConfig
from vizier_tpu.observability.flight_recorder import (
    FLEET,
    FlightRecorder,
    FlightRecorderConfig,
    NOOP_RECORDER,
    NoopFlightRecorder,
    get_recorder,
    set_recorder,
)
from vizier_tpu.observability.jax_timing import device_phase
from vizier_tpu.observability.slo import SloConfig, SloEngine, SloStatus
from vizier_tpu.observability.metrics import (
    Counter,
    DEFAULT_LATENCY_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    exponential_buckets,
    set_default_registry,
)
from vizier_tpu.observability.tracing import (
    NOOP_TRACER,
    NoopTracer,
    Span,
    SpanContext,
    Tracer,
    add_current_event,
    format_context,
    get_tracer,
    parse_context,
    set_tracer,
)

__all__ = [
    "ObservabilityConfig",
    "device_phase",
    "fleet",
    "FLEET",
    "FlightRecorder",
    "FlightRecorderConfig",
    "NOOP_RECORDER",
    "NoopFlightRecorder",
    "get_recorder",
    "set_recorder",
    "SloConfig",
    "SloEngine",
    "SloStatus",
    "Counter",
    "DEFAULT_LATENCY_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "exponential_buckets",
    "set_default_registry",
    "NOOP_TRACER",
    "NoopTracer",
    "Span",
    "SpanContext",
    "Tracer",
    "add_current_event",
    "format_context",
    "get_tracer",
    "parse_context",
    "set_tracer",
]
