"""Observability knobs (tracing, metrics, JAX phase profiling).

Everything defaults ON; ``VIZIER_OBSERVABILITY=0`` turns the whole
subsystem off wholesale (no-op tracer, no histogram observations, no
device-sync in the JAX phase timers — ≈ zero overhead), and each
mechanism has its own off-switch for A/B isolation:

- ``VIZIER_OBSERVABILITY=0``         — master switch;
- ``VIZIER_OBSERVABILITY_TRACING=0`` — no spans (counters/histograms stay);
- ``VIZIER_OBSERVABILITY_METRICS=0`` — no latency histograms (the serving
  counter vocabulary — ``ServingStats`` — is core behavior and stays on);
- ``VIZIER_OBSERVABILITY_JAX=0``     — designer device-phase timers become
  no-ops and stop forcing ``block_until_ready`` syncs;
- ``VIZIER_OBSERVABILITY_SPAN_BUFFER=N`` — finished-span ring size;
- ``VIZIER_OBSERVABILITY_SPAN_LOG=path`` — append every finished span to
  ``path`` as one JSON line (off by default; the in-memory ring is always
  available via ``Tracer.finished_spans()`` / ``dump_jsonl()``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

# All VIZIER_* switches are declared in (and read through) the central
# registry (vizier_tpu.analysis.registry); enforced by the env_registry
# analysis pass.
from vizier_tpu.analysis import registry as _registry


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Knobs for the tracing/metrics/profiling subsystem."""

    # Master switch; off ≈ zero overhead everywhere.
    enabled: bool = True
    # Per-mechanism switches (each effective only when ``enabled``).
    tracing: bool = True
    metrics: bool = True
    jax_profiling: bool = True

    # Finished spans kept in the tracer's bounded ring buffer.
    span_buffer_size: int = 4096
    # Optional JSON-lines sink ("" = in-memory ring only).
    span_log_path: str = ""

    # -- effective switches (master ANDed in) ------------------------------

    @property
    def tracing_on(self) -> bool:
        return self.enabled and self.tracing

    @property
    def metrics_on(self) -> bool:
        return self.enabled and self.metrics

    @property
    def jax_profiling_on(self) -> bool:
        return self.enabled and self.jax_profiling

    @classmethod
    def from_env(cls) -> "ObservabilityConfig":
        """The default config with per-knob environment overrides applied."""
        return cls(
            enabled=_registry.env_on("VIZIER_OBSERVABILITY"),
            tracing=_registry.env_on("VIZIER_OBSERVABILITY_TRACING"),
            metrics=_registry.env_on("VIZIER_OBSERVABILITY_METRICS"),
            jax_profiling=_registry.env_on("VIZIER_OBSERVABILITY_JAX"),
            span_buffer_size=_registry.env_int(
                "VIZIER_OBSERVABILITY_SPAN_BUFFER", 4096
            ),
            span_log_path=_registry.env_str("VIZIER_OBSERVABILITY_SPAN_LOG"),
        )

    @classmethod
    def disabled(cls) -> "ObservabilityConfig":
        """Everything off: the pre-observability code paths."""
        return cls(enabled=False)

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready form, for stamping into benchmark/report output."""
        return dataclasses.asdict(self)
