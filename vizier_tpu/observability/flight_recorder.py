"""Per-study flight recorder: bounded rings of structured lifecycle events.

The black box of the serving fleet. Every study gets a bounded ring of
structured events — suggest served, trial completed, batch-flush
membership with its device placement, speculation outcome, surrogate
crossover, breaker transition, replica failover — each stamped with a
wall-clock time and (when one is active) the request's ``trace_id``, so an
SLO breach can be walked backwards: "show me exactly the requests around
the spike, and which traces they were."

Fleet-scoped events that belong to no single study (replica failover,
batch flushes, SLO breaches) land under the :data:`FLEET` pseudo-study.

Like the tracer, the recorder is a process-global singleton built from the
env config on first use: subsystems call ``get_recorder().record(...)``
and pay ≈ nothing when the switch is off (``VIZIER_FLIGHT_RECORDER=0``,
the default, yields the stateless :data:`NOOP_RECORDER`). Stdlib-only.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Any, Dict, List, Optional

# All VIZIER_* switches are declared in (and read through) the central
# registry; enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry
from vizier_tpu.observability import tracing as tracing_lib

# Pseudo-study key for events that belong to the fleet, not one study.
FLEET = "<fleet>"


@dataclasses.dataclass(frozen=True)
class FlightRecorderConfig:
    """Knobs for the per-study flight recorder."""

    # Off by default: recording every lifecycle event is an opt-in cost.
    enabled: bool = False
    # Events kept per study ring (oldest evicted first).
    ring_size: int = 256
    # Study rings kept (least-recently-recorded evicted first).
    max_studies: int = 1024

    @classmethod
    def from_env(cls) -> "FlightRecorderConfig":
        return cls(
            enabled=_registry.env_on("VIZIER_FLIGHT_RECORDER"),
            ring_size=_registry.env_int("VIZIER_FLIGHT_RECORDER_RING", 256),
            max_studies=_registry.env_int(
                "VIZIER_FLIGHT_RECORDER_STUDIES", 1024
            ),
        )

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class FlightRecorder:
    """Bounded per-study rings of JSON-ready lifecycle events."""

    enabled = True

    def __init__(self, ring_size: int = 256, max_studies: int = 1024):
        self._ring_size = max(1, ring_size)
        self._max_studies = max(1, max_studies)
        self._lock = threading.Lock()
        self._rings: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )

    def record(
        self,
        study: Optional[str],
        kind: str,
        trace_id: Optional[str] = None,
        **attributes: Any,
    ) -> None:
        """Appends one event to ``study``'s ring (:data:`FLEET` when None).

        ``trace_id`` defaults to the ambient trace so deep callees (the
        breaker, the batch executor) correlate for free; attribute values
        must be JSON-serializable.
        """
        if trace_id is None:
            ctx = tracing_lib.get_tracer().current_context()
            trace_id = ctx.trace_id if ctx is not None else None
        event: Dict[str, Any] = {
            "time": time.time(),
            "study": study or FLEET,
            "kind": kind,
        }
        if trace_id:
            event["trace_id"] = trace_id
        if attributes:
            event["attributes"] = attributes
        with self._lock:
            ring = self._rings.get(event["study"])
            if ring is None:
                while len(self._rings) >= self._max_studies:
                    self._rings.popitem(last=False)
                ring = self._rings[event["study"]] = collections.deque(
                    maxlen=self._ring_size
                )
            else:
                self._rings.move_to_end(event["study"])
            ring.append(event)

    def ring(self, study: str) -> List[dict]:
        """One study's events, oldest first (empty when never recorded)."""
        with self._lock:
            ring = self._rings.get(study)
            return list(ring) if ring is not None else []

    def studies(self) -> List[str]:
        with self._lock:
            return list(self._rings)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Every recorded event across all rings, time-ordered; optionally
        filtered by ``kind``."""
        with self._lock:
            out = [e for ring in self._rings.values() for e in ring]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        out.sort(key=lambda e: e["time"])
        return out

    def invalidate(self, study: str) -> bool:
        """Drops a study's ring (DeleteStudy hygiene)."""
        with self._lock:
            return self._rings.pop(study, None) is not None

    def snapshot(self) -> Dict[str, List[dict]]:
        """JSON-ready copy of every ring (the black-box dump payload)."""
        with self._lock:
            return {study: list(ring) for study, ring in self._rings.items()}

    def dump_json(self, path: str) -> int:
        """Writes every event (time-ordered) to ``path`` as one JSON list;
        returns the event count."""
        events = self.events()
        with open(path, "w") as f:
            json.dump(events, f)
        return len(events)


class NoopFlightRecorder:
    """The off switch: same surface, no state, no allocation per event."""

    enabled = False

    def record(self, study, kind, trace_id=None, **attributes):
        pass

    def ring(self, study):
        return []

    def studies(self):
        return []

    def events(self, kind=None):
        return []

    def invalidate(self, study):
        return False

    def snapshot(self):
        return {}

    def dump_json(self, path: str) -> int:
        return 0


NOOP_RECORDER = NoopFlightRecorder()

_global_recorder = None
_global_lock = threading.Lock()


def _recorder_from_config(config: FlightRecorderConfig):
    if not config.enabled:
        return NOOP_RECORDER
    return FlightRecorder(
        ring_size=config.ring_size, max_studies=config.max_studies
    )


def get_recorder():
    """The process-global recorder, built from the env config on first use."""
    global _global_recorder
    recorder = _global_recorder
    if recorder is None:
        with _global_lock:
            if _global_recorder is None:
                _global_recorder = _recorder_from_config(
                    FlightRecorderConfig.from_env()
                )
            recorder = _global_recorder
    return recorder


def set_recorder(recorder):
    """Swaps the global recorder (tests/tools); None re-derives from env on
    next use. Returns the previous recorder."""
    global _global_recorder
    with _global_lock:
        old, _global_recorder = _global_recorder, recorder
    return old
