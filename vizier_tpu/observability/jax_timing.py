"""JAX-aware phase timing: device-attributed spans, compile vs. execute.

JAX dispatch is asynchronous — wall-clocking a jitted call measures
*enqueue*, not device work, and the cost silently lands on whatever later
op first blocks. :func:`device_phase` wraps a designer hot-path stage in a
span and has the caller ``block()`` the stage's outputs *inside* it, so
device time is attributed to the right phase:

    with jax_timing.device_phase("gp_bandit.train_gp") as phase:
        states = self._train(...)
        phase.block(states)

The first occurrence of a phase name in the process is recorded as
``mode="compile"`` (trace + lower + compile dominates it), later ones as
``mode="execute"`` — the steady-state serving number. Both land in the
global metrics registry as ``vizier_jax_phase_seconds{phase=...,mode=...}``
and on the span as attributes.

With observability (or the JAX knob) off, the phase object is inert and —
deliberately — does NOT ``block_until_ready``: the production path keeps
JAX's async pipelining, so the off switch costs nothing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional, Set

from vizier_tpu.observability import config as config_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib

_seen_lock = threading.Lock()
_seen_phases: Set[str] = set()

_config: Optional[config_lib.ObservabilityConfig] = None


def _jax_profiling_on() -> bool:
    global _config
    if _config is None:
        _config = config_lib.ObservabilityConfig.from_env()
    return _config.jax_profiling_on


def set_config(config: Optional[config_lib.ObservabilityConfig]) -> None:
    """Overrides the env-derived config (tests); None re-reads on next use."""
    global _config
    _config = config


def reset_compile_tracking() -> None:
    """Forgets which phases have run (tests)."""
    with _seen_lock:
        _seen_phases.clear()


def _mark_seen(name: str) -> bool:
    """True iff this is the first time ``name`` runs in this process."""
    with _seen_lock:
        if name in _seen_phases:
            return False
        _seen_phases.add(name)
        return True


class _Phase:
    """Yielded by :func:`device_phase`; ``block()`` pins device time here."""

    __slots__ = ("name", "enabled", "first_call")

    def __init__(self, name: str, enabled: bool, first_call: bool):
        self.name = name
        self.enabled = enabled
        self.first_call = first_call

    def block(self, outputs: Any) -> Any:
        """``jax.block_until_ready`` on ``outputs`` (pytree-ok), returned
        unchanged. No-op — keeping async dispatch — when profiling is off."""
        if self.enabled:
            import jax

            jax.block_until_ready(outputs)
        return outputs


_DISABLED_PHASE = _Phase("", enabled=False, first_call=False)


class _PhaseCM:
    __slots__ = ("_phase", "_registry", "_span_cm", "_span", "_t0")

    def __init__(self, phase: _Phase, registry: metrics_lib.MetricsRegistry):
        self._phase = phase
        self._registry = registry
        self._span_cm = tracing_lib.get_tracer().span(
            f"jax.{phase.name}",
            jax_phase=phase.name,
            first_call=phase.first_call,
        )
        self._span = None
        self._t0 = 0.0

    def __enter__(self) -> _Phase:
        self._span = self._span_cm.__enter__()
        self._t0 = time.perf_counter()
        return self._phase

    def __exit__(self, exc_type, exc, tb) -> bool:
        duration = time.perf_counter() - self._t0
        mode = "compile" if self._phase.first_call else "execute"
        if exc is None:
            self._registry.histogram(
                "vizier_jax_phase_seconds",
                help="Designer JAX phase wall time, device-synced; "
                "mode=compile is the first call per phase.",
            ).observe(duration, phase=self._phase.name, mode=mode)
        self._span.set_attribute("mode", mode)
        return self._span_cm.__exit__(exc_type, exc, tb)


class _DisabledPhaseCM:
    __slots__ = ()

    def __enter__(self) -> _Phase:
        return _DISABLED_PHASE

    def __exit__(self, *exc) -> bool:
        return False


_DISABLED_CM = _DisabledPhaseCM()


def device_phase(
    name: str, registry: Optional[metrics_lib.MetricsRegistry] = None
):
    """Times one device phase (see module docstring for the contract)."""
    if not _jax_profiling_on():
        return _DISABLED_CM
    phase = _Phase(name, enabled=True, first_call=_mark_seen(name))
    return _PhaseCM(phase, registry or metrics_lib.default_registry())
