"""Designer and Predictor abstractions.

Parity with ``/root/reference/vizier/_src/algorithms/core/abstractions.py:31-216``:
a ``Designer`` is the suggest/update unit algorithms implement; serializable
variants checkpoint state through metadata; a ``Predictor`` exposes posterior
predictions (mean/stddev) for model-based designers.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Callable, Optional, Protocol, Sequence

import numpy as np

from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import serializable

CompletedTrials = trial_.CompletedTrials
ActiveTrials = trial_.ActiveTrials


class Designer(abc.ABC):
    """A suggestion algorithm.

    ``update`` delivers *newly* completed trials exactly once each, plus the
    full set of currently-active trials; ``suggest`` returns up to ``count``
    suggestions (returning fewer — or none — is allowed and signals that the
    designer is done or needs more data).
    """

    @abc.abstractmethod
    def update(
        self, completed: CompletedTrials, all_active: ActiveTrials = ActiveTrials()
    ) -> None:
        ...

    @abc.abstractmethod
    def suggest(self, count: Optional[int] = None) -> Sequence[trial_.TrialSuggestion]:
        ...


class PartiallySerializableDesigner(Designer, serializable.PartiallySerializable):
    """Designer whose state loads into a freshly-constructed instance."""


class SerializableDesigner(Designer, serializable.Serializable):
    """Designer fully recoverable from dumped metadata."""


@dataclasses.dataclass(frozen=True)
class Prediction:
    """Posterior prediction at a batch of points."""

    mean: np.ndarray
    stddev: np.ndarray

    def __post_init__(self):
        if np.asarray(self.mean).shape != np.asarray(self.stddev).shape:
            raise ValueError(
                f"mean shape {np.asarray(self.mean).shape} != "
                f"stddev shape {np.asarray(self.stddev).shape}"
            )


class Predictor(abc.ABC):
    """Mixin for designers that can predict unobserved points."""

    @abc.abstractmethod
    def predict(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng: Optional[np.random.Generator] = None,
        num_samples: Optional[int] = None,
    ) -> Prediction:
        ...

    def sample(
        self,
        suggestions: Sequence[trial_.TrialSuggestion],
        rng: Optional[np.random.Generator] = None,
        num_samples: int = 1,
    ) -> np.ndarray:
        """Posterior samples [num_samples, len(suggestions)]; default via normal."""
        rng = rng or np.random.default_rng(0)
        pred = self.predict(suggestions)
        return rng.normal(
            pred.mean[None, :], pred.stddev[None, :], size=(num_samples, len(pred.mean))
        )


class DesignerFactory(Protocol):
    """problem (+kwargs, e.g. seed) → Designer."""

    def __call__(self, problem: base_study_config.ProblemStatement, **kwargs) -> Designer:
        ...
