"""Designer → Policy wrappers.

Parity with
``/root/reference/vizier/_src/algorithms/policies/designer_policy.py:40,126,347,364,377``
and ``policies/trial_caches.py:33``: the stateless ``DesignerPolicy`` rebuilds
a designer per request and replays all trials; the serializable variants
checkpoint designer state + an incorporated-trial-id cache into study
metadata namespace ``designer_policy_v0`` and feed only *new* completed
trials, falling back to full replay on ``DecodeError``.

The production suggest path does NOT use the stateless wrapper: the
service's policy factory routes GP algorithms through
``vizier_tpu.serving.CachedDesignerStatePolicy`` (per-study designer cache
with TTL/LRU + warm-started ARD) unless serving is disabled, in which case
``DesignerPolicy`` below is the reference-parity fallback.
"""

from __future__ import annotations

import json
import logging
from typing import Callable, Optional, Sequence

from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.algorithms import trial_caches
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import serializable

_logger = logging.getLogger(__name__)

_NS = "designer_policy_v0"
_DESIGNER_KEY = "designer"
_CACHE_KEY = "incorporated_trial_ids"


def default_suggestion(problem: base_study_config.ProblemStatement) -> trial_.TrialSuggestion:
    """The search space's default/center point (used to seed empty studies).

    Mirrors ``suggest_default.py:33-60``: each parameter takes its default
    value (or center/first feasible), walking conditional children whose
    activation matches the chosen parent value.
    """
    params = trial_.ParameterDict()

    def assign(config: pc.ParameterConfig) -> None:
        value = config.first_feasible_value()
        params[config.name] = config.cast_value(value)
        for child in config.children:
            if any(pc.parent_value_matches(value, pv) for pv in child.matching_parent_values):
                assign(child)

    for config in problem.search_space.parameters:
        assign(config)
    return trial_.TrialSuggestion(parameters=params)


class DesignerPolicy(policy_lib.Policy):
    """Stateless wrapper: fresh designer per request, full trial replay."""

    def __init__(
        self,
        supporter: supporter_lib.PolicySupporter,
        designer_factory: core_lib.DesignerFactory,
        *,
        use_seeding: bool = False,
    ):
        self._supporter = supporter
        self._designer_factory = designer_factory
        self._use_seeding = use_seeding

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        if self._use_seeding and request.max_trial_id == 0:
            seed = default_suggestion(request.study_config.to_problem())
            rest = []
            if request.count > 1:
                rest = self._run_designer(request, request.count - 1)
            return policy_lib.SuggestDecision(suggestions=[seed] + list(rest))
        return policy_lib.SuggestDecision(
            suggestions=self._run_designer(request, request.count)
        )

    def _run_designer(
        self, request: policy_lib.SuggestRequest, count: int
    ) -> Sequence[trial_.TrialSuggestion]:
        from vizier_tpu.observability import tracing as tracing_lib

        tracer = tracing_lib.get_tracer()
        designer = self._designer_factory(request.study_config.to_problem())
        completed = self._supporter.GetTrials(
            status_matches=trial_.TrialStatus.COMPLETED
        )
        active = self._supporter.GetTrials(status_matches=trial_.TrialStatus.ACTIVE)
        with tracer.span(
            "designer.update",
            designer=type(designer).__name__,
            new_completed=len(completed),
            incremental=False,
        ):
            designer.update(
                core_lib.CompletedTrials(completed), core_lib.ActiveTrials(active)
            )
        with tracer.span(
            "designer.suggest", designer=type(designer).__name__, count=count
        ):
            return designer.suggest(count)


class _SerializableDesignerPolicyBase(policy_lib.Policy):
    """Shared logic: state + trial-id cache in study metadata, incremental updates."""

    def __init__(
        self,
        supporter: supporter_lib.PolicySupporter,
        designer_factory: core_lib.DesignerFactory,
    ):
        self._supporter = supporter
        self._designer_factory = designer_factory
        self._incorporated_ids: set = set()

    # subclass hooks -------------------------------------------------------

    def _make_or_restore_designer(
        self, problem: base_study_config.ProblemStatement, state: Optional[common.Metadata]
    ) -> core_lib.Designer:
        raise NotImplementedError

    def _dump_designer(self, designer: core_lib.Designer) -> common.Metadata:
        raise NotImplementedError

    # ---------------------------------------------------------------------

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        problem = request.study_config.to_problem()
        study_md = request.study_config.metadata.abs_ns(common.Namespace((_NS,)))
        state_md: Optional[common.Metadata] = None
        cached_ids: set = set()
        encoded_state = study_md.get(_DESIGNER_KEY)
        encoded_cache = study_md.get(_CACHE_KEY)
        if encoded_state is not None and encoded_cache is not None:
            try:
                cached_ids = trial_caches.decode_trial_ids(encoded_cache)
                state_md = common.Metadata()
                state_md.ns(_DESIGNER_KEY).update(
                    {"state": encoded_state}
                )
            except (serializable.DecodeError, ValueError, TypeError) as e:
                _logger.warning("Corrupt designer cache; replaying all trials: %s", e)
                state_md, cached_ids = None, set()

        try:
            designer = self._make_or_restore_designer(problem, state_md)
            self._incorporated_ids = set(cached_ids) if state_md is not None else set()
        except serializable.DecodeError as e:
            _logger.warning("DecodeError restoring designer; replaying all trials: %s", e)
            designer = self._make_or_restore_designer(problem, None)
            self._incorporated_ids = set()

        all_completed = self._supporter.GetTrials(status_matches=trial_.TrialStatus.COMPLETED)
        new_completed = [t for t in all_completed if t.id not in self._incorporated_ids]
        active = self._supporter.GetTrials(status_matches=trial_.TrialStatus.ACTIVE)
        designer.update(
            core_lib.CompletedTrials(new_completed), core_lib.ActiveTrials(active)
        )
        self._incorporated_ids.update(t.id for t in new_completed)

        suggestions = designer.suggest(request.count)

        delta = trial_.MetadataDelta()
        try:
            dumped = self._dump_designer(designer)
            state = dumped.ns(_DESIGNER_KEY).get("state")
            if state is not None:
                delta.assign(_NS, _DESIGNER_KEY, state)
                delta.assign(
                    _NS, _CACHE_KEY, trial_caches.encode_trial_ids(self._incorporated_ids)
                )
        except Exception as e:  # dump failure must not lose the suggestions
            _logger.warning("Failed to dump designer state: %s", e)
        return policy_lib.SuggestDecision(suggestions=list(suggestions), metadata=delta)


class PartiallySerializableDesignerPolicy(_SerializableDesignerPolicyBase):
    """Wraps a PartiallySerializableDesigner (construct, then load state)."""

    def _make_or_restore_designer(self, problem, state):
        designer = self._designer_factory(problem)
        if state is not None:
            raw = state.ns(_DESIGNER_KEY).get("state")
            md = common.Metadata()
            if isinstance(raw, str):
                try:
                    for k, v in json.loads(raw).items():
                        md[k] = v
                except (ValueError, TypeError) as e:
                    raise serializable.DecodeError(str(e))
            try:
                if hasattr(designer, "load"):
                    designer.load(md)
                elif hasattr(type(designer), "recover"):
                    designer = type(designer).recover(md)
                else:
                    raise serializable.DecodeError(
                        f"{type(designer).__name__} implements neither load nor recover."
                    )
            except serializable.DecodeError:
                raise
            except Exception as e:  # bad stored state must degrade to replay
                raise serializable.DecodeError(str(e))
        return designer

    def _dump_designer(self, designer) -> common.Metadata:
        inner = designer.dump()  # type: ignore[attr-defined]
        out = common.Metadata()
        out.ns(_DESIGNER_KEY)["state"] = json.dumps({k: inner[k] for k in inner})
        return out


class SerializableDesignerPolicy(PartiallySerializableDesignerPolicy):
    """Wraps a fully Serializable designer; identical wire format."""


class InRamDesignerPolicy(policy_lib.Policy):
    """Keeps one designer instance alive in process memory across requests.

    Useful for benchmarking (``should_be_cached`` = True); incremental
    updates without serialization overhead. For SERVING use
    ``vizier_tpu.serving.CachedDesignerStatePolicy`` instead: same
    incremental-update idea, but the designer lives in a shared TTL/LRU
    cache with explicit invalidation on study deletion rather than for
    whatever lifetime the Pythia servicer keeps this policy object.
    """

    def __init__(
        self,
        supporter: supporter_lib.PolicySupporter,
        designer_factory: core_lib.DesignerFactory,
        problem: Optional[base_study_config.ProblemStatement] = None,
    ):
        self._supporter = supporter
        self._designer_factory = designer_factory
        self._designer: Optional[core_lib.Designer] = None
        self._problem = problem
        self._incorporated_ids: set = set()

    @property
    def should_be_cached(self) -> bool:
        return True

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        if self._designer is None:
            problem = self._problem or request.study_config.to_problem()
            self._designer = self._designer_factory(problem)
        completed = [
            t
            for t in self._supporter.GetTrials(status_matches=trial_.TrialStatus.COMPLETED)
            if t.id not in self._incorporated_ids
        ]
        active = self._supporter.GetTrials(status_matches=trial_.TrialStatus.ACTIVE)
        self._designer.update(
            core_lib.CompletedTrials(completed), core_lib.ActiveTrials(active)
        )
        self._incorporated_ids.update(t.id for t in completed)
        return policy_lib.SuggestDecision(
            suggestions=list(self._designer.suggest(request.count))
        )
