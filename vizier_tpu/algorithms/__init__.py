"""Designer abstractions and designer->policy wrappers."""

from vizier_tpu.algorithms.core import (
    ActiveTrials,
    CompletedTrials,
    Designer,
    DesignerFactory,
    PartiallySerializableDesigner,
    Prediction,
    Predictor,
    SerializableDesigner,
)
from vizier_tpu.algorithms.designer_policy import (
    DesignerPolicy,
    InRamDesignerPolicy,
    PartiallySerializableDesignerPolicy,
    SerializableDesignerPolicy,
    default_suggestion,
)
from vizier_tpu.algorithms.random_policy import RandomPolicy
