"""Random sampling helpers over search spaces (numpy Generator based).

Parity with
``/root/reference/vizier/_src/algorithms/random/random_sample.py:28-124``:
per-type value samplers, closest-element snapping for DISCRETE, and
whole-search-space parameter sampling. Shared by designers that need
one-off random draws outside their jitted paths (eagle utils, ensembles).
"""

from __future__ import annotations

from typing import List, Sequence, TypeVar

import numpy as np

from vizier_tpu.pyvizier import parameter_config as pc
from vizier_tpu.pyvizier import trial as trial_

_T = TypeVar("_T")


def sample_uniform(
    rng: np.random.Generator, min_value: float = 0.0, max_value: float = 1.0
) -> float:
    return float(rng.uniform(low=min_value, high=max_value))


def sample_bernoulli(
    rng: np.random.Generator, prob1: float, value1: _T = 0, value2: _T = 1
) -> _T:
    return value1 if rng.random() < prob1 else value2


def sample_integer(
    rng: np.random.Generator, min_value: float, max_value: float
) -> int:
    return round(sample_uniform(rng, min_value, max_value))


def sample_categorical(rng: np.random.Generator, categories: Sequence[str]) -> str:
    return str(categories[int(rng.integers(len(categories)))])


def get_closest_element(array: Sequence[float], value: float) -> float:
    arr = np.asarray(list(array), dtype=float)
    return float(arr[int(np.argmin(np.abs(arr - value)))])


def sample_discrete(
    rng: np.random.Generator, feasible_points: Sequence[float]
) -> float:
    """Uniform over the continuous span, snapped to the closest point.

    (Matches the reference: NOT uniform over the point set — points with
    wide gaps around them are proportionally more likely.)
    """
    points = [float(p) for p in feasible_points]
    value = sample_uniform(rng, min(points), max(points))
    return get_closest_element(points, value)


def sample_value(
    rng: np.random.Generator, param_config: pc.ParameterConfig
) -> pc.ParameterValueTypes:
    """Random value of the parameter's own type."""
    if param_config.type == pc.ParameterType.CATEGORICAL:
        return sample_categorical(rng, [str(v) for v in param_config.feasible_values])
    if param_config.type == pc.ParameterType.DISCRETE:
        return sample_discrete(rng, [float(v) for v in param_config.feasible_values])
    min_value, max_value = param_config.bounds
    if param_config.type == pc.ParameterType.INTEGER:
        return sample_integer(rng, min_value, max_value)
    return sample_uniform(rng, min_value, max_value)


def sample_parameters(
    rng: np.random.Generator, search_space: pc.SearchSpace
) -> trial_.ParameterDict:
    """Random assignment for every top-level parameter in the space."""
    out = trial_.ParameterDict()
    for config in search_space.parameters:
        out[config.name] = trial_.ParameterValue(sample_value(rng, config))
    return out


def shuffle_list(rng: np.random.Generator, items: List[_T]) -> List[_T]:
    rng.shuffle(items)
    return items
