"""Trial-id deduplicating loader.

Parity with
``/root/reference/vizier/_src/algorithms/policies/trial_caches.py:33``
(``IdDeduplicatingTrialLoader``): tracks which completed trials a designer
has already incorporated and fetches only the new ones; serializable so the
cache survives process restarts.
"""

from __future__ import annotations

import json
from typing import List, Set

from vizier_tpu.pythia import policy_supporter as supporter_lib
from vizier_tpu.pyvizier import common
from vizier_tpu.pyvizier import trial as trial_
from vizier_tpu.utils import serializable


def encode_trial_ids(ids) -> str:
    """The ONE wire format for persisted incorporated-trial-id caches.

    Shared with ``designer_policy``'s study-metadata cache so the two
    persistence paths cannot drift.
    """
    return json.dumps(sorted(int(i) for i in ids))


def decode_trial_ids(raw: str) -> Set[int]:
    try:
        ids = json.loads(raw)
        return set(int(i) for i in ids)
    except (ValueError, TypeError) as e:
        raise serializable.DecodeError(str(e))


class IdDeduplicatingTrialLoader(serializable.PartiallySerializable):
    def __init__(self, supporter: supporter_lib.PolicySupporter):
        self._supporter = supporter
        self._incorporated: Set[int] = set()

    def new_completed_trials(self) -> List[trial_.Trial]:
        """Completed trials not yet delivered by this loader."""
        completed = self._supporter.GetTrials(
            status_matches=trial_.TrialStatus.COMPLETED
        )
        fresh = [t for t in completed if t.id not in self._incorporated]
        self._incorporated.update(t.id for t in fresh)
        return fresh

    def active_trials(self) -> List[trial_.Trial]:
        return self._supporter.GetTrials(status_matches=trial_.TrialStatus.ACTIVE)

    @property
    def num_incorporated(self) -> int:
        return len(self._incorporated)

    def dump(self) -> common.Metadata:
        md = common.Metadata()
        md["incorporated_trial_ids"] = encode_trial_ids(self._incorporated)
        return md

    def load(self, metadata: common.Metadata) -> None:
        raw = metadata.get("incorporated_trial_ids")
        if raw is None:
            raise serializable.DecodeError("Missing 'incorporated_trial_ids'.")
        self._incorporated = decode_trial_ids(raw)
