"""Curve-based automated early stopping.

The reference routes early stopping through its policies plus a
``DefaultEarlyStoppingSpec`` (``oss/automated_stopping.py:46``, servicer flow
``vizier_service.py:631``); here the median-curve rule is a first-class
policy: a trial should stop when its objective at its latest reported
step/time is below the median of other trials' objectives at a comparable
point, once ``min_num_trials`` trials carry measurements.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib


def _latest_value(
    trial: vz.Trial, metric: str, use_steps: bool
) -> Optional[Tuple[float, float]]:
    """(position, value) of the trial's latest intermediate measurement."""
    best = None
    for m in trial.measurements:
        if metric not in m.metrics:
            continue
        pos = m.steps if use_steps else m.elapsed_secs
        if best is None or pos >= best[0]:
            best = (pos, m.metrics[metric].value)
    if best is None and trial.final_measurement and metric in trial.final_measurement.metrics:
        fm = trial.final_measurement
        pos = fm.steps if use_steps else fm.elapsed_secs
        best = (pos, fm.metrics[metric].value)
    return best


def _value_at(
    trial: vz.Trial, metric: str, position: float, use_steps: bool
) -> Optional[float]:
    """The trial's objective at the last measurement with pos <= position."""
    value = None
    for m in trial.measurements:
        if metric not in m.metrics:
            continue
        pos = m.steps if use_steps else m.elapsed_secs
        if pos <= position:
            value = m.metrics[metric].value
    return value


@dataclasses.dataclass
class MedianEarlyStopPolicy(policy_lib.Policy):
    """Median rule over intermediate measurement curves."""

    supporter: supporter_lib.PolicySupporter
    use_steps: bool = True
    min_num_trials: int = 5

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        raise NotImplementedError("MedianEarlyStopPolicy only early-stops.")

    def early_stop(
        self, request: policy_lib.EarlyStopRequest
    ) -> policy_lib.EarlyStopDecisions:
        config = request.study_config
        problem = config.to_problem()
        metric_info = None
        for m in problem.metric_information:
            if not m.is_safety_metric:
                metric_info = m
                break
        if metric_info is None:
            return policy_lib.EarlyStopDecisions()
        metric = metric_info.name
        sign = 1.0 if metric_info.goal.is_maximize else -1.0

        all_trials = self.supporter.GetTrials()
        with_curves = [t for t in all_trials if t.measurements]
        decisions = []
        for tid in sorted(request.trial_ids):
            trial = next((t for t in all_trials if t.id == tid), None)
            if trial is None:
                continue
            if len(with_curves) < self.min_num_trials:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False,
                        reason=f"Fewer than {self.min_num_trials} trials with curves.",
                    )
                )
                continue
            latest = _latest_value(trial, metric, self.use_steps)
            if latest is None:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False, reason="No measurements yet."
                    )
                )
                continue
            position, value = latest
            others = [
                v
                for t in with_curves
                if t.id != tid
                and (v := _value_at(t, metric, position, self.use_steps)) is not None
            ]
            if len(others) < self.min_num_trials - 1:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False,
                        reason="Not enough comparable curves.",
                    )
                )
                continue
            median = float(np.median(np.asarray(others)))
            should = sign * value < sign * median
            decisions.append(
                policy_lib.EarlyStopDecision(
                    id=tid,
                    should_stop=should,
                    reason=(
                        f"value {value:.4g} vs median {median:.4g} at "
                        f"{'step' if self.use_steps else 'secs'} {position:g}"
                    ),
                )
            )
        return policy_lib.EarlyStopDecisions(decisions=decisions)
