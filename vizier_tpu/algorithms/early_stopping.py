"""Curve-based automated early stopping.

The reference routes early stopping through its policies plus a
``DefaultEarlyStoppingSpec`` (``oss/automated_stopping.py:46``, servicer flow
``vizier_service.py:631``); here the median-curve rule is a first-class
policy: a trial should stop when its objective at its latest reported
step/time is below the median of other trials' objectives at a comparable
point, once ``min_num_trials`` trials carry measurements.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib


def _latest_value(
    trial: vz.Trial, metric: str, use_steps: bool
) -> Optional[Tuple[float, float]]:
    """(position, value) of the trial's latest intermediate measurement."""
    best = None
    for m in trial.measurements:
        if metric not in m.metrics:
            continue
        pos = m.steps if use_steps else m.elapsed_secs
        if best is None or pos >= best[0]:
            best = (pos, m.metrics[metric].value)
    if best is None and trial.final_measurement and metric in trial.final_measurement.metrics:
        fm = trial.final_measurement
        pos = fm.steps if use_steps else fm.elapsed_secs
        best = (pos, fm.metrics[metric].value)
    return best


def _value_at(
    trial: vz.Trial, metric: str, position: float, use_steps: bool
) -> Optional[float]:
    """The trial's objective at the last measurement with pos <= position."""
    value = None
    for m in trial.measurements:
        if metric not in m.metrics:
            continue
        pos = m.steps if use_steps else m.elapsed_secs
        if pos <= position:
            value = m.metrics[metric].value
    return value


@dataclasses.dataclass
class MedianEarlyStopPolicy(policy_lib.Policy):
    """Median rule over intermediate measurement curves."""

    supporter: supporter_lib.PolicySupporter
    use_steps: bool = True
    min_num_trials: int = 5

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        raise NotImplementedError("MedianEarlyStopPolicy only early-stops.")

    def early_stop(
        self, request: policy_lib.EarlyStopRequest
    ) -> policy_lib.EarlyStopDecisions:
        config = request.study_config
        problem = config.to_problem()
        metric_info = None
        for m in problem.metric_information:
            if not m.is_safety_metric:
                metric_info = m
                break
        if metric_info is None:
            return policy_lib.EarlyStopDecisions()
        metric = metric_info.name
        sign = 1.0 if metric_info.goal.is_maximize else -1.0

        all_trials = self.supporter.GetTrials()
        with_curves = [t for t in all_trials if t.measurements]
        decisions = []
        for tid in sorted(request.trial_ids):
            trial = next((t for t in all_trials if t.id == tid), None)
            if trial is None:
                continue
            if len(with_curves) < self.min_num_trials:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False,
                        reason=f"Fewer than {self.min_num_trials} trials with curves.",
                    )
                )
                continue
            latest = _latest_value(trial, metric, self.use_steps)
            if latest is None:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False, reason="No measurements yet."
                    )
                )
                continue
            position, value = latest
            others = [
                v
                for t in with_curves
                if t.id != tid
                and (v := _value_at(t, metric, position, self.use_steps)) is not None
            ]
            if len(others) < self.min_num_trials - 1:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, should_stop=False,
                        reason="Not enough comparable curves.",
                    )
                )
                continue
            median = float(np.median(np.asarray(others)))
            should = sign * value < sign * median
            decisions.append(
                policy_lib.EarlyStopDecision(
                    id=tid,
                    should_stop=should,
                    reason=(
                        f"value {value:.4g} vs median {median:.4g} at "
                        f"{'step' if self.use_steps else 'secs'} {position:g}"
                    ),
                )
            )
        return policy_lib.EarlyStopDecisions(decisions=decisions)


@dataclasses.dataclass
class RegressionEarlyStopPolicy(policy_lib.Policy):
    """Curve-regression stopping rule (reference trial_regression_utils role).

    Trains the gradient-boosted final-objective regressor
    (``algorithms/regression.py``) on completed trials' curves and stops any
    ACTIVE trial whose predicted final objective falls below the median
    completed final — sharper than the median rule once enough curves exist
    (a trial that starts slow but trends well is kept; one plateauing below
    the pack is cut even while its current value still looks median-ish).
    Falls back to keep-running while the regressor is underfit.
    """

    supporter: supporter_lib.PolicySupporter
    min_num_trials: int = 10

    def __post_init__(self):
        # GBM training is the expensive step; cache the fit keyed by the
        # completed-trial count so repeated CheckTrialEarlyStoppingState
        # polls between completions reuse it (this policy object itself is
        # cached per study by the Pythia servicer).
        self._regressor = None
        self._trained_on = -1

    @property
    def should_be_cached(self) -> bool:
        return True

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        raise NotImplementedError("RegressionEarlyStopPolicy only early-stops.")

    def _trained_regressor(self, metric: str, completed):
        from vizier_tpu.algorithms import regression

        if len(completed) == self._trained_on:
            return self._regressor
        regressor = regression.GBMAutoRegressor(
            metric, min_train_trials=self.min_num_trials
        )
        self._regressor = regressor if regressor.train(completed) else None
        self._trained_on = len(completed)
        return self._regressor

    def early_stop(
        self, request: policy_lib.EarlyStopRequest
    ) -> policy_lib.EarlyStopDecisions:
        config = request.study_config
        problem = config.to_problem()
        metric_info = next(
            (m for m in problem.metric_information if not m.is_safety_metric), None
        )
        if metric_info is None:
            return policy_lib.EarlyStopDecisions()
        metric = metric_info.name
        sign = 1.0 if metric_info.goal.is_maximize else -1.0

        all_trials = self.supporter.GetTrials()
        completed = [t for t in all_trials if t.is_completed and not t.infeasible]
        decisions = []

        regressor = (
            self._trained_regressor(metric, completed)
            if len(completed) >= self.min_num_trials
            else None
        )
        trained = regressor is not None
        if trained:
            finals = [
                sign * t.final_measurement.metrics[metric].value
                for t in completed
                if t.final_measurement and metric in t.final_measurement.metrics
            ]
            threshold = float(np.median(finals)) if finals else -np.inf
        for tid in sorted(request.trial_ids):
            trial = next((t for t in all_trials if t.id == tid), None)
            if trial is None:
                continue
            if not trained or not trial.measurements:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid, reason="Too little curve data.", should_stop=False
                    )
                )
                continue
            pred = regressor.predict(trial)
            should = pred is not None and sign * pred < threshold
            decisions.append(
                policy_lib.EarlyStopDecision(
                    id=tid,
                    reason=(
                        f"Predicted final {pred:.4g} below completed median."
                        if should
                        else "Predicted final at or above completed median."
                    ),
                    should_stop=bool(should),
                )
            )
        return policy_lib.EarlyStopDecisions(decisions=decisions)
