"""Example pure-Pythia policy: random suggestions + random early stopping.

Parity with ``/root/reference/vizier/_src/algorithms/policies/random_policy.py:29``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from vizier_tpu.designers import random as random_designer
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pythia import policy_supporter as supporter_lib
from vizier_tpu.pyvizier import trial as trial_


class RandomPolicy(policy_lib.Policy):
    def __init__(
        self,
        policy_supporter: supporter_lib.PolicySupporter,
        *,
        seed: Optional[int] = None,
    ):
        self._supporter = policy_supporter
        self._rng = np.random.default_rng(seed)

    @property
    def should_be_cached(self) -> bool:
        # Stateless apart from the RNG (which only needs a stream, not a
        # fresh seed per request); rebuilding per suggest costs a PCG64
        # entropy init on the serving hot path for nothing.
        return True

    def suggest(self, request: policy_lib.SuggestRequest) -> policy_lib.SuggestDecision:
        space = request.study_config.search_space
        suggestions = [
            trial_.TrialSuggestion(
                parameters=random_designer.sample_point(space, self._rng)
            )
            for _ in range(request.count)
        ]
        return policy_lib.SuggestDecision(suggestions=suggestions)

    def early_stop(self, request: policy_lib.EarlyStopRequest) -> policy_lib.EarlyStopDecisions:
        """Stops one random trial among the candidates."""
        ids = sorted(request.trial_ids)
        decisions = []
        if ids:
            chosen = int(self._rng.choice(ids))
            for tid in ids:
                decisions.append(
                    policy_lib.EarlyStopDecision(
                        id=tid,
                        reason="random early stopping",
                        should_stop=(tid == chosen),
                    )
                )
        return policy_lib.EarlyStopDecisions(decisions=decisions)
