"""Feasibility classifiers and trial-regression utilities.

Parity with
``/root/reference/vizier/_src/algorithms/classification/classifiers.py:95``
and ``regression/trial_regression_utils.py``: probabilistic feasibility
models over trial features (used to down-weight acquisition in regions that
keep failing) and curve regression over intermediate measurements (used for
stopping/extrapolation decisions).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from vizier_tpu.converters import core as converters
from vizier_tpu.pyvizier import base_study_config
from vizier_tpu.pyvizier import trial as trial_


@dataclasses.dataclass
class FeasibilityClassifier:
    """P(feasible | x) from completed trials (sklearn GP/logistic backend)."""

    problem: base_study_config.ProblemStatement
    kind: str = "gp"  # 'gp' | 'logistic'
    seed: int = 0

    def __post_init__(self):
        self._converter = converters.TrialToArrayConverter.from_study_config(
            self.problem
        )
        self._model = None
        self._constant: Optional[float] = None

    def fit(self, trials: Sequence[trial_.Trial]) -> "FeasibilityClassifier":
        xs = self._converter.to_features(trials)
        ys = np.asarray([0.0 if t.infeasible else 1.0 for t in trials])
        if len(np.unique(ys)) < 2:
            # All-feasible or all-infeasible: constant predictor.
            self._constant = float(ys[0]) if len(ys) else 1.0
            self._model = None
            return self
        self._constant = None
        try:
            import sklearn  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "FeasibilityClassifier needs scikit-learn; install the "
                "'vizier-tpu[classifiers]' extra."
            ) from e
        if self.kind == "gp":
            from sklearn.gaussian_process import GaussianProcessClassifier
            from sklearn.gaussian_process.kernels import Matern

            self._model = GaussianProcessClassifier(
                kernel=Matern(nu=2.5), random_state=self.seed
            ).fit(xs, ys)
        elif self.kind == "logistic":
            from sklearn.linear_model import LogisticRegression

            # Weak regularization: features live in [0, 1], so the default
            # C=1 shrinks boundaries far too much.
            self._model = LogisticRegression(C=100.0, random_state=self.seed).fit(
                xs, ys
            )
        else:
            raise ValueError(f"Unknown classifier kind {self.kind!r}.")
        return self

    def predict_proba_feasible(
        self, suggestions: Sequence[trial_.TrialSuggestion]
    ) -> np.ndarray:
        trials = [s.to_trial(i + 1) for i, s in enumerate(suggestions)]
        if self._constant is not None or self._model is None:
            return np.full(len(trials), self._constant if self._constant is not None else 1.0)
        xs = self._converter.to_features(trials)
        proba = self._model.predict_proba(xs)
        feasible_col = list(self._model.classes_).index(1.0)
        return proba[:, feasible_col]


@dataclasses.dataclass
class TrialCurveRegressor:
    """Power-law extrapolation of a trial's measurement curve.

    Fits ``y(s) ≈ a - b·s^{-c}`` (the classic learning-curve family) by
    least squares over a small grid of exponents; ``predict(s)`` gives the
    extrapolated objective — the regression backbone for curve-based
    stopping decisions.
    """

    metric_name: str
    use_steps: bool = True

    def fit(self, trial: trial_.Trial) -> Optional["TrialCurveRegressor"]:
        xs, ys = [], []
        for m in trial.measurements:
            if self.metric_name in m.metrics:
                pos = m.steps if self.use_steps else m.elapsed_secs
                if pos > 0:
                    xs.append(pos)
                    ys.append(m.metrics[self.metric_name].value)
        if len(xs) < 3:
            return None
        xs_arr, ys_arr = np.asarray(xs, dtype=np.float64), np.asarray(ys)
        best = None
        for c in (0.25, 0.5, 1.0, 2.0):
            basis = np.stack([np.ones_like(xs_arr), -(xs_arr**-c)], axis=1)
            coef, residuals, _, _ = np.linalg.lstsq(basis, ys_arr, rcond=None)
            err = (
                float(residuals[0])
                if len(residuals)
                else float(np.sum((basis @ coef - ys_arr) ** 2))
            )
            if best is None or err < best[0]:
                best = (err, c, coef)
        _, self._c, (self._a, self._b) = best
        return self

    def predict(self, position: float) -> float:
        return float(self._a - self._b * position**-self._c)

    @property
    def asymptote(self) -> float:
        """The predicted converged value (position → ∞)."""
        return float(self._a)
