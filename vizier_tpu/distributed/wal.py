"""Snapshot + write-ahead-log persistence for the RAM datastore.

``PersistentDataStore`` wraps a ``NestedDictRAMDataStore``: reads hit RAM
directly; every mutation is applied to RAM first and then appended to a
per-shard WAL as a proto-serialized record, so a replica restarted over
the same directory replays itself back to the exact pre-crash state
("restart warm"). Every ``snapshot_interval`` mutations the log is
compacted: the full store state is written as a *snapshot* — itself just a
compacted WAL whose records recreate the state — and the live log is
truncated.

Durability protocol (one writer per directory):

- ``wal.log``      — active log: ``[u32 length][u32 crc32][payload]``
  records, appended + flushed per mutation. A flush hands the record to
  the OS, so by default an acknowledged mutation survives a PROCESS
  crash only — an OS crash / power loss may still drop flushed-but-
  unsynced tail records. ``VIZIER_DISTRIBUTED_WAL_FSYNC=1`` (or
  ``fsync=True``) adds an fsync per append, extending the guarantee to
  OS crashes at a per-mutation disk-sync cost.
- ``snapshot.bin`` — last compaction, same record framing. Written to
  ``snapshot.bin.tmp`` + fsync + atomic rename, THEN the log is truncated
  (snapshots are always fsynced, in both modes).

Crash windows:

- mid-append: the torn tail record fails its length/CRC check and is
  dropped on replay (the mutation was never acknowledged);
- mid-snapshot-write: the tmp file is ignored; old snapshot + full log
  still replay;
- after the snapshot rename but before the log truncate: replay applies
  log records already folded into the snapshot — replay is *tolerant*
  (create-of-existing applies as an update, delete-of-missing is skipped),
  and re-applying a record sequence in order is state-idempotent, so the
  double apply converges to the same state.

Lock order: ``PersistentDataStore._lock`` serializes mutate+append so the
log order equals the apply order; it nests OVER the inner RAM store's lock
and the WAL's file lock, and nothing below ever calls back up (leaf-ward
only — checked by the lock_order pass).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterable, List, Optional, Tuple

from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import ram_datastore
from vizier_tpu.service import resources
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

# -- record vocabulary -----------------------------------------------------

CREATE_STUDY = 1
UPDATE_STUDY = 2
DELETE_STUDY = 3
CREATE_TRIAL = 4
UPDATE_TRIAL = 5
DELETE_TRIAL = 6
CREATE_SUGGESTION_OP = 7
UPDATE_SUGGESTION_OP = 8
CREATE_EARLY_STOPPING_OP = 9
UPDATE_EARLY_STOPPING_OP = 10
UPDATE_METADATA = 11

_OPCODES = frozenset(range(CREATE_STUDY, UPDATE_METADATA + 1))

_HEADER = struct.Struct("<II")  # payload length, crc32(opcode byte + payload)

SNAPSHOT_FILE = "snapshot.bin"
LOG_FILE = "wal.log"


def study_key_of(opcode: int, payload: bytes) -> str:
    """The owning study resource name of a record (failover re-placement)."""
    if opcode in (CREATE_STUDY, UPDATE_STUDY):
        study = study_pb2.Study.FromString(payload)
        return study.name
    if opcode == DELETE_STUDY:
        return payload.decode("utf-8")
    if opcode in (CREATE_TRIAL, UPDATE_TRIAL):
        trial = study_pb2.Trial.FromString(payload)
        return resources.TrialResource.from_name(trial.name).study_resource.name
    if opcode == DELETE_TRIAL:
        name = payload.decode("utf-8")
        return resources.TrialResource.from_name(name).study_resource.name
    if opcode in (CREATE_SUGGESTION_OP, UPDATE_SUGGESTION_OP):
        op = vizier_service_pb2.Operation.FromString(payload)
        r = resources.SuggestionOperationResource.from_name(op.name)
        return resources.StudyResource(r.owner_id, r.study_id).name
    if opcode in (CREATE_EARLY_STOPPING_OP, UPDATE_EARLY_STOPPING_OP):
        op = vizier_service_pb2.EarlyStoppingOperation.FromString(payload)
        r = resources.EarlyStoppingOperationResource.from_name(op.name)
        return resources.StudyResource(r.owner_id, r.study_id).name
    if opcode == UPDATE_METADATA:
        req = vizier_service_pb2.UpdateMetadataRequest.FromString(payload)
        return req.name
    raise ValueError(f"Unknown WAL opcode: {opcode}")


class WriteAheadLog:
    """Append-only mutation log with atomic snapshot compaction."""

    def __init__(self, directory: str, *, fsync: bool = False):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()  # file handle + counters only
        self._fsync = fsync
        self._log_path = os.path.join(directory, LOG_FILE)
        self._snapshot_path = os.path.join(directory, SNAPSHOT_FILE)
        self._log = open(self._log_path, "ab")
        self._appended = 0

    # -- framing -----------------------------------------------------------

    @staticmethod
    def _frame(opcode: int, payload: bytes) -> bytes:
        if opcode not in _OPCODES:
            raise ValueError(f"Unknown WAL opcode: {opcode}")
        body = bytes((opcode,)) + payload
        return _HEADER.pack(len(body), zlib.crc32(body)) + body

    @staticmethod
    def _read_records(path: str) -> Tuple[List[Tuple[int, bytes]], bool]:
        """Records of one file; second element is True when a torn/corrupt
        tail was dropped. Reading stops at the first bad record — with one
        appender flushing sequentially, damage can only be a tail."""
        records: List[Tuple[int, bytes]] = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return records, False
        offset = 0
        while offset < len(data):
            if offset + _HEADER.size > len(data):
                return records, True  # torn header
            length, crc = _HEADER.unpack_from(data, offset)
            start = offset + _HEADER.size
            end = start + length
            if length < 1 or end > len(data):
                return records, True  # torn payload
            body = data[start:end]
            if zlib.crc32(body) != crc:
                return records, True  # corrupt tail
            records.append((body[0], body[1:]))
            offset = end
        return records, False

    # -- API ---------------------------------------------------------------

    def append(self, opcode: int, payload: bytes) -> None:
        frame = self._frame(opcode, payload)
        with self._lock:
            self._log.write(frame)
            self._log.flush()
            if self._fsync:
                os.fsync(self._log.fileno())
            self._appended += 1

    @property
    def appended_since_snapshot(self) -> int:
        with self._lock:
            return self._appended

    def load(self) -> Tuple[List[Tuple[int, bytes]], bool]:
        """Snapshot records + live log records, in apply order.

        Second element reports whether a torn/corrupt log tail was dropped
        (a crash mid-append, or — without per-append fsync — an OS crash
        that lost flushed-but-unsynced tail records).
        """
        snapshot_records, snapshot_torn = self._read_records(self._snapshot_path)
        if snapshot_torn:
            # A torn snapshot can only be a crashed *tmp* promoted by an
            # outside force; never trust it over replaying nothing.
            snapshot_records = []
        log_records, log_torn = self._read_records(self._log_path)
        return snapshot_records + log_records, log_torn or snapshot_torn

    def compact(self, records: Iterable[Tuple[int, bytes]]) -> None:
        """Atomically replaces the snapshot with ``records``, truncates the log.

        The caller must hold whatever lock serializes its mutations (the
        compaction must see a quiescent state and no append may interleave
        with the truncate).
        """
        tmp_path = self._snapshot_path + ".tmp"
        with self._lock:
            with open(tmp_path, "wb") as f:
                for opcode, payload in records:
                    f.write(self._frame(opcode, payload))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_path, self._snapshot_path)
            # Crash between replace and truncate double-applies the log
            # over the snapshot — tolerated by replay (module docstring).
            self._log.close()
            self._log = open(self._log_path, "wb")
            self._appended = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._log.close()
            except Exception:
                pass


class StoreDivergedError(RuntimeError):
    """The RAM state and the WAL no longer agree (a log write failed after
    its mutation was applied); the store fail-stops rather than serve
    state a restart would silently revert."""


class PersistentDataStore(datastore_lib.DataStore):
    """RAM datastore + snapshot/WAL durability (one writer per directory)."""

    def __init__(
        self,
        directory: str,
        *,
        snapshot_interval: Optional[int] = None,
        fsync: Optional[bool] = None,
        inner: Optional[ram_datastore.NestedDictRAMDataStore] = None,
    ):
        from vizier_tpu.distributed import config as config_lib

        env = config_lib.DistributedConfig.from_env()
        self._inner = inner or ram_datastore.NestedDictRAMDataStore()
        self._wal = WriteAheadLog(
            directory, fsync=env.wal_fsync if fsync is None else fsync
        )
        self._snapshot_interval = (
            snapshot_interval
            if snapshot_interval is not None
            else env.snapshot_interval
        )
        # Serializes apply+append so log order == apply order; nests over
        # the inner store's lock and the WAL file lock only.
        self._lock = threading.Lock()
        self._diverged: Optional[str] = None
        records, self.recovered_torn_tail = self._wal.load()
        self.recovered_records = len(records)
        for opcode, payload in records:
            apply_record(self._inner, opcode, payload)

    # -- plumbing ----------------------------------------------------------

    @property
    def wal(self) -> WriteAheadLog:
        return self._wal

    def _check_converged(self) -> None:
        if self._diverged is not None:
            raise StoreDivergedError(self._diverged)

    def _mutate(self, fn, opcode: int, payload: bytes):
        """Applies ``fn`` to the inner store, then logs it (apply-then-log:
        a rejected mutation — duplicate create, missing target — raises
        before anything reaches the log).

        A FAILED log write after the apply is a fail-stop: the RAM state
        now holds a mutation the WAL lost, so instead of serving state a
        restart would silently revert, the store poisons itself and every
        subsequent operation raises :class:`StoreDivergedError`.
        """
        with self._lock:
            self._check_converged()
            result = fn()
            try:
                self._wal.append(opcode, payload)
                if self._wal.appended_since_snapshot >= self._snapshot_interval:
                    self._wal.compact(export_records(self._inner))
            except BaseException as e:
                self._diverged = (
                    f"WAL write failed after the mutation was applied "
                    f"({type(e).__name__}: {e}); RAM and log have diverged "
                    f"— restart the replica to recover to the logged state."
                )
                raise
        return result

    def compact_now(self) -> None:
        """Forces a snapshot compaction (tests, graceful shutdown)."""
        with self._lock:
            self._check_converged()
            self._wal.compact(export_records(self._inner))

    def close(self) -> None:
        self._wal.close()

    # -- studies -----------------------------------------------------------

    def create_study(self, study):
        return self._mutate(
            lambda: self._inner.create_study(study),
            CREATE_STUDY,
            study.SerializeToString(),
        )

    def load_study(self, study_name):
        self._check_converged()
        return self._inner.load_study(study_name)

    def update_study(self, study):
        return self._mutate(
            lambda: self._inner.update_study(study),
            UPDATE_STUDY,
            study.SerializeToString(),
        )

    def delete_study(self, study_name):
        return self._mutate(
            lambda: self._inner.delete_study(study_name),
            DELETE_STUDY,
            study_name.encode("utf-8"),
        )

    def list_studies(self, owner_name):
        self._check_converged()
        return self._inner.list_studies(owner_name)

    # -- trials ------------------------------------------------------------

    def create_trial(self, trial):
        return self._mutate(
            lambda: self._inner.create_trial(trial),
            CREATE_TRIAL,
            trial.SerializeToString(),
        )

    def get_trial(self, trial_name):
        self._check_converged()
        return self._inner.get_trial(trial_name)

    def update_trial(self, trial):
        return self._mutate(
            lambda: self._inner.update_trial(trial),
            UPDATE_TRIAL,
            trial.SerializeToString(),
        )

    def delete_trial(self, trial_name):
        return self._mutate(
            lambda: self._inner.delete_trial(trial_name),
            DELETE_TRIAL,
            trial_name.encode("utf-8"),
        )

    def list_trials(self, study_name, *, states=None):
        self._check_converged()
        return self._inner.list_trials(study_name, states=states)

    def max_trial_id(self, study_name):
        self._check_converged()
        return self._inner.max_trial_id(study_name)

    # -- suggestion operations --------------------------------------------

    def create_suggestion_operation(self, operation):
        return self._mutate(
            lambda: self._inner.create_suggestion_operation(operation),
            CREATE_SUGGESTION_OP,
            operation.SerializeToString(),
        )

    def get_suggestion_operation(self, operation_name):
        self._check_converged()
        return self._inner.get_suggestion_operation(operation_name)

    def update_suggestion_operation(self, operation):
        return self._mutate(
            lambda: self._inner.update_suggestion_operation(operation),
            UPDATE_SUGGESTION_OP,
            operation.SerializeToString(),
        )

    def list_suggestion_operations(
        self, study_name, client_id, filter_fn=None, *, done=None
    ):
        self._check_converged()
        return self._inner.list_suggestion_operations(
            study_name, client_id, filter_fn, done=done
        )

    def max_suggestion_operation_number(self, study_name, client_id):
        self._check_converged()
        return self._inner.max_suggestion_operation_number(study_name, client_id)

    # -- early stopping operations ----------------------------------------

    def create_early_stopping_operation(self, operation):
        return self._mutate(
            lambda: self._inner.create_early_stopping_operation(operation),
            CREATE_EARLY_STOPPING_OP,
            operation.SerializeToString(),
        )

    def get_early_stopping_operation(self, operation_name):
        self._check_converged()
        return self._inner.get_early_stopping_operation(operation_name)

    def update_early_stopping_operation(self, operation):
        return self._mutate(
            lambda: self._inner.update_early_stopping_operation(operation),
            UPDATE_EARLY_STOPPING_OP,
            operation.SerializeToString(),
        )

    # -- metadata ----------------------------------------------------------

    def update_metadata(self, study_name, study_metadata, trial_metadata):
        # Materialize the iterables once: they are consumed both by the
        # store apply and the wire record.
        study_kvs = list(study_metadata)
        trial_kvs = [(int(tid), kv) for tid, kv in trial_metadata]
        request = vizier_service_pb2.UpdateMetadataRequest(name=study_name)
        for kv in study_kvs:
            unit = request.deltas.add()
            unit.trial_id = 0
            unit.key_value.CopyFrom(kv)
        for trial_id, kv in trial_kvs:
            unit = request.deltas.add()
            unit.trial_id = trial_id
            unit.key_value.CopyFrom(kv)
        return self._mutate(
            lambda: self._inner.update_metadata(study_name, study_kvs, trial_kvs),
            UPDATE_METADATA,
            request.SerializeToString(),
        )


# -- replay / snapshot helpers ---------------------------------------------


def export_records(
    store: ram_datastore.NestedDictRAMDataStore,
) -> List[Tuple[int, bytes]]:
    """The store's full state as a compacted record sequence.

    Replaying these records into an empty store recreates the state —
    a snapshot IS a compacted WAL, so there is exactly one on-disk format
    and one replay path.
    """
    studies, trials, ops, es_ops = store.export_protos()
    records: List[Tuple[int, bytes]] = []
    for study in studies:
        records.append((CREATE_STUDY, study.SerializeToString()))
    for trial in trials:
        records.append((CREATE_TRIAL, trial.SerializeToString()))
    for op in ops:
        records.append((CREATE_SUGGESTION_OP, op.SerializeToString()))
    for op in es_ops:
        records.append((CREATE_EARLY_STOPPING_OP, op.SerializeToString()))
    return records


def apply_record(
    store: datastore_lib.DataStore, opcode: int, payload: bytes
) -> None:
    """Applies one record to ``store``, tolerantly.

    Tolerant replay is what makes the crash windows safe: a create of an
    existing resource applies as an update (double-applied log over a
    fresh snapshot), a delete/update of a missing resource is skipped
    (the delete already happened / its study was deleted later in the
    log). Applying a record SEQUENCE in order therefore always converges
    to the state the sequence describes.
    """
    if opcode in (CREATE_STUDY, UPDATE_STUDY):
        study = study_pb2.Study.FromString(payload)
        try:
            store.create_study(study)
        except datastore_lib.AlreadyExistsError:
            store.update_study(study)
    elif opcode == DELETE_STUDY:
        try:
            store.delete_study(payload.decode("utf-8"))
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_TRIAL, UPDATE_TRIAL):
        trial = study_pb2.Trial.FromString(payload)
        try:
            try:
                store.create_trial(trial)
            except datastore_lib.AlreadyExistsError:
                store.update_trial(trial)
        except datastore_lib.NotFoundError:
            pass  # study deleted later in the log
    elif opcode == DELETE_TRIAL:
        try:
            store.delete_trial(payload.decode("utf-8"))
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_SUGGESTION_OP, UPDATE_SUGGESTION_OP):
        op = vizier_service_pb2.Operation.FromString(payload)
        try:
            try:
                store.create_suggestion_operation(op)
            except datastore_lib.AlreadyExistsError:
                store.update_suggestion_operation(op)
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_EARLY_STOPPING_OP, UPDATE_EARLY_STOPPING_OP):
        op = vizier_service_pb2.EarlyStoppingOperation.FromString(payload)
        try:
            # create doubles as upsert for early-stopping ops in the RAM
            # store, but go through update for missing-create symmetry.
            store.create_early_stopping_operation(op)
        except datastore_lib.NotFoundError:
            pass
    elif opcode == UPDATE_METADATA:
        request = vizier_service_pb2.UpdateMetadataRequest.FromString(payload)
        study_kvs = [d.key_value for d in request.deltas if d.trial_id == 0]
        trial_kvs = [
            (int(d.trial_id), d.key_value)
            for d in request.deltas
            if d.trial_id != 0
        ]
        try:
            store.update_metadata(request.name, study_kvs, trial_kvs)
        except datastore_lib.NotFoundError:
            pass
    else:
        raise ValueError(f"Unknown WAL opcode: {opcode}")


def read_directory(
    directory: str,
) -> Tuple[List[Tuple[int, bytes]], bool]:
    """Snapshot+log records of a (possibly dead) replica's WAL directory.

    Read-only: used by failover to lift a dead replica's studies into
    their successor replicas without opening the directory for append.
    """
    snapshot, _ = WriteAheadLog._read_records(
        os.path.join(directory, SNAPSHOT_FILE)
    )
    log, torn = WriteAheadLog._read_records(os.path.join(directory, LOG_FILE))
    return snapshot + log, torn
