"""Snapshot + write-ahead-log persistence for the RAM datastore.

``PersistentDataStore`` wraps a ``NestedDictRAMDataStore``: reads hit RAM
directly; every mutation is applied to RAM first and then appended to a
per-shard WAL as a proto-serialized record, so a replica restarted over
the same directory replays itself back to the exact pre-crash state
("restart warm"). Every ``snapshot_interval`` mutations the log is
compacted: the full store state is written as a *snapshot* — itself just a
compacted WAL whose records recreate the state — and the live log is
truncated.

Durability protocol (one writer per directory):

- ``wal.log``      — active log: ``[u32 length][u32 crc32][payload]``
  records, appended + flushed per mutation. A flush hands the record to
  the OS, so by default an acknowledged mutation survives a PROCESS
  crash only — an OS crash / power loss may still drop flushed-but-
  unsynced tail records. ``VIZIER_DISTRIBUTED_WAL_FSYNC=1`` (or
  ``fsync=True``) adds an fsync per append, extending the guarantee to
  OS crashes at a per-mutation disk-sync cost.
- ``snapshot.bin`` — last compaction, same record framing. Written to
  ``snapshot.bin.tmp`` + fsync + atomic rename, THEN the log is truncated
  (snapshots are always fsynced, in both modes).

Crash windows:

- mid-append: the torn tail record fails its length/CRC check and is
  dropped on replay (the mutation was never acknowledged);
- corrupt record mid-log (bit rot, injected corruption): replay recovers
  the longest valid prefix, and reopening the log **quarantines** the
  invalid suffix into a ``wal.log.corrupt`` sidecar before appending —
  without the quarantine, records appended after the damage would be
  acknowledged and then silently lost on the next replay (the reader
  stops at the first bad record). The lost suffix is recoverable from a
  replication standby log (``distributed/replication.py``) when one is
  longer — failover compares sources by mutation *sequence number*,
  which snapshots record in a leading ``SNAPSHOT_META`` record;
- mid-snapshot-write: the tmp file is ignored; old snapshot + full log
  still replay;
- after the snapshot rename but before the log truncate: replay applies
  log records already folded into the snapshot — replay is *tolerant*
  (create-of-existing applies as an update, delete-of-missing is skipped),
  and re-applying a record sequence in order is state-idempotent, so the
  double apply converges to the same state.

Lock order: ``PersistentDataStore._lock`` serializes mutate+append so the
log order equals the apply order; it nests OVER the inner RAM store's lock
and the WAL's file lock, and nothing below ever calls back up (leaf-ward
only — checked by the lock_order pass).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Tuple, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (typing only)
    from vizier_tpu.distributed.replication import AppendSink

from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import ram_datastore
from vizier_tpu.service import resources
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

# -- record vocabulary -----------------------------------------------------

CREATE_STUDY = 1
UPDATE_STUDY = 2
DELETE_STUDY = 3
CREATE_TRIAL = 4
UPDATE_TRIAL = 5
DELETE_TRIAL = 6
CREATE_SUGGESTION_OP = 7
UPDATE_SUGGESTION_OP = 8
CREATE_EARLY_STOPPING_OP = 9
UPDATE_EARLY_STOPPING_OP = 10
UPDATE_METADATA = 11
# A snapshot's first record: its payload is the origin's mutation sequence
# number at compaction time (u64). Pure bookkeeping — replay skips it; it
# is what lets a failover compare a local WAL against a replication
# standby log by *sequence number* rather than by incomparable record
# counts (a snapshot compacts history, so its record count is not its
# mutation count).
SNAPSHOT_META = 12

_OPCODES = frozenset(range(CREATE_STUDY, SNAPSHOT_META + 1))
DATA_OPCODES = frozenset(range(CREATE_STUDY, UPDATE_METADATA + 1))

_HEADER = struct.Struct("<II")  # payload length, crc32(opcode byte + payload)
_SEQ = struct.Struct("<Q")

SNAPSHOT_FILE = "snapshot.bin"
LOG_FILE = "wal.log"
CORRUPT_SUFFIX = ".corrupt"


def study_key_of(opcode: int, payload: bytes) -> str:
    """The owning study resource name of a record (failover re-placement)."""
    if opcode in (CREATE_STUDY, UPDATE_STUDY):
        study = study_pb2.Study.FromString(payload)
        return study.name
    if opcode == DELETE_STUDY:
        return payload.decode("utf-8")
    if opcode in (CREATE_TRIAL, UPDATE_TRIAL):
        trial = study_pb2.Trial.FromString(payload)
        return resources.TrialResource.from_name(trial.name).study_resource.name
    if opcode == DELETE_TRIAL:
        name = payload.decode("utf-8")
        return resources.TrialResource.from_name(name).study_resource.name
    if opcode in (CREATE_SUGGESTION_OP, UPDATE_SUGGESTION_OP):
        op = vizier_service_pb2.Operation.FromString(payload)
        r = resources.SuggestionOperationResource.from_name(op.name)
        return resources.StudyResource(r.owner_id, r.study_id).name
    if opcode in (CREATE_EARLY_STOPPING_OP, UPDATE_EARLY_STOPPING_OP):
        op = vizier_service_pb2.EarlyStoppingOperation.FromString(payload)
        r = resources.EarlyStoppingOperationResource.from_name(op.name)
        return resources.StudyResource(r.owner_id, r.study_id).name
    if opcode == UPDATE_METADATA:
        req = vizier_service_pb2.UpdateMetadataRequest.FromString(payload)
        return req.name
    raise ValueError(f"Unknown WAL opcode: {opcode}")


def split_meta(records: List[Tuple[int, bytes]]) -> Tuple[int, List[Tuple[int, bytes]]]:
    """``(base_seq, data_records)`` of a snapshot record sequence.

    A snapshot written by this version starts with a :data:`SNAPSHOT_META`
    record carrying the mutation sequence the compaction folded up to.
    Older snapshots have no meta record; their record count stands in as
    the base (each compacted record was at least one mutation) — an
    approximation that only matters for standby-vs-local comparisons, and
    pre-replication directories have no standby logs to compare against.
    """
    if records and records[0][0] == SNAPSHOT_META:
        return int(_SEQ.unpack(records[0][1])[0]), records[1:]
    return len(records), list(records)


class WriteAheadLog:
    """Append-only mutation log with atomic snapshot compaction."""

    def __init__(self, directory: str, *, fsync: bool = False):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()  # file handle + counters only
        self._fsync = fsync
        self._log_path = os.path.join(directory, LOG_FILE)
        self._snapshot_path = os.path.join(directory, SNAPSHOT_FILE)
        # Quarantine BEFORE opening for append: a log with a corrupt or
        # torn record mid-file must not be appended past it — replay stops
        # at the first bad record, so anything written after the damage
        # would be acknowledged and then silently lost on the next replay.
        # The invalid suffix moves to a ``wal.log.corrupt`` sidecar (kept
        # for forensics) and the live log truncates to its longest valid
        # prefix.
        self.quarantined_bytes = self._quarantine_invalid_suffix(
            self._log_path
        )
        self._log = open(self._log_path, "ab")
        self._appended = 0

    # -- framing -----------------------------------------------------------

    @staticmethod
    def _frame(opcode: int, payload: bytes) -> bytes:
        if opcode not in _OPCODES:
            raise ValueError(f"Unknown WAL opcode: {opcode}")
        body = bytes((opcode,)) + payload
        return _HEADER.pack(len(body), zlib.crc32(body)) + body

    @staticmethod
    def _valid_prefix_end(data: bytes) -> int:
        """Byte offset where the valid record prefix of ``data`` ends."""
        offset = 0
        while offset < len(data):
            if offset + _HEADER.size > len(data):
                return offset
            length, crc = _HEADER.unpack_from(data, offset)
            start = offset + _HEADER.size
            end = start + length
            if length < 1 or end > len(data):
                return offset
            if zlib.crc32(data[start:end]) != crc:
                return offset
            offset = end
        return offset

    @classmethod
    def _quarantine_invalid_suffix(cls, path: str) -> int:
        """Moves everything past the longest valid record prefix of
        ``path`` into ``path + '.corrupt'``; returns the bytes moved."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return 0
        end = cls._valid_prefix_end(data)
        if end >= len(data):
            return 0
        suffix = data[end:]
        with open(path + CORRUPT_SUFFIX, "ab") as sidecar:
            sidecar.write(suffix)
            sidecar.flush()
            os.fsync(sidecar.fileno())
        with open(path, "r+b") as f:
            f.truncate(end)
            f.flush()
            os.fsync(f.fileno())
        return len(suffix)

    @staticmethod
    def _read_records(path: str) -> Tuple[List[Tuple[int, bytes]], bool]:
        """Records of one file; second element is True when a torn/corrupt
        suffix was dropped. Reading stops at the first bad record: with one
        appender flushing sequentially damage is normally a tail, and a
        mid-log corruption (bit rot, an injected ``wal_corrupt`` chaos
        event) makes everything after it unreadable — the longest valid
        prefix is what this returns, and :meth:`_quarantine_invalid_suffix`
        is what keeps a reopened log from appending past the damage."""
        records: List[Tuple[int, bytes]] = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return records, False
        offset = 0
        while offset < len(data):
            if offset + _HEADER.size > len(data):
                return records, True  # torn header
            length, crc = _HEADER.unpack_from(data, offset)
            start = offset + _HEADER.size
            end = start + length
            if length < 1 or end > len(data):
                return records, True  # torn payload
            body = data[start:end]
            if zlib.crc32(body) != crc:
                return records, True  # corrupt tail
            records.append((body[0], body[1:]))
            offset = end
        return records, False

    # -- API ---------------------------------------------------------------

    def append(self, opcode: int, payload: bytes) -> None:
        frame = self._frame(opcode, payload)
        with self._lock:
            self._log.write(frame)
            self._log.flush()
            if self._fsync:
                os.fsync(self._log.fileno())
            self._appended += 1

    @property
    def appended_since_snapshot(self) -> int:
        with self._lock:
            return self._appended

    def load(self) -> Tuple[List[Tuple[int, bytes]], bool]:
        """Snapshot records + live log records, in apply order.

        Second element reports whether a torn/corrupt log tail was dropped
        (a crash mid-append, or — without per-append fsync — an OS crash
        that lost flushed-but-unsynced tail records).
        """
        records, torn, _seq = self.load_with_seq()
        return records, torn

    def load_with_seq(self) -> Tuple[List[Tuple[int, bytes]], bool, int]:
        """Like :meth:`load`, plus the mutation sequence number the loaded
        state corresponds to (snapshot meta base + live log records)."""
        snapshot_records, snapshot_torn = self._read_records(self._snapshot_path)
        if snapshot_torn:
            # A torn snapshot can only be a crashed *tmp* promoted by an
            # outside force; never trust it over replaying nothing.
            snapshot_records = []
        base_seq, snapshot_records = split_meta(snapshot_records)
        log_records, log_torn = self._read_records(self._log_path)
        log_records = [r for r in log_records if r[0] != SNAPSHOT_META]
        return (
            snapshot_records + log_records,
            log_torn or snapshot_torn,
            base_seq + len(log_records),
        )

    def compact(
        self,
        records: Iterable[Tuple[int, bytes]],
        *,
        seq: Optional[int] = None,
    ) -> None:
        """Atomically replaces the snapshot with ``records``, truncates the log.

        ``seq`` (the store's mutation sequence at compaction time) is
        recorded as the snapshot's leading :data:`SNAPSHOT_META` record so
        a later reader can place the snapshot on the origin's sequence
        axis. The caller must hold whatever lock serializes its mutations
        (the compaction must see a quiescent state and no append may
        interleave with the truncate).
        """
        tmp_path = self._snapshot_path + ".tmp"
        with self._lock:
            with open(tmp_path, "wb") as f:
                if seq is not None:
                    f.write(self._frame(SNAPSHOT_META, _SEQ.pack(int(seq))))
                for opcode, payload in records:
                    f.write(self._frame(opcode, payload))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_path, self._snapshot_path)
            # Crash between replace and truncate double-applies the log
            # over the snapshot — tolerated by replay (module docstring).
            self._log.close()
            self._log = open(self._log_path, "wb")
            self._appended = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._log.close()
            except Exception:
                pass


class StoreDivergedError(RuntimeError):
    """The RAM state and the WAL no longer agree (a log write failed after
    its mutation was applied); the store fail-stops rather than serve
    state a restart would silently revert."""


class PersistentDataStore(datastore_lib.DataStore):
    """RAM datastore + snapshot/WAL durability (one writer per directory)."""

    def __init__(
        self,
        directory: str,
        *,
        snapshot_interval: Optional[int] = None,
        fsync: Optional[bool] = None,
        inner: Optional[ram_datastore.NestedDictRAMDataStore] = None,
        on_append: Optional["AppendSink"] = None,
    ):
        from vizier_tpu.distributed import config as config_lib

        env = config_lib.DistributedConfig.from_env()
        self._inner = inner or ram_datastore.NestedDictRAMDataStore()
        self._wal = WriteAheadLog(
            directory, fsync=env.wal_fsync if fsync is None else fsync
        )
        self._snapshot_interval = (
            snapshot_interval
            if snapshot_interval is not None
            else env.snapshot_interval
        )
        # Serializes apply+append so log order == apply order; nests over
        # the inner store's lock and the WAL file lock only.
        self._lock = threading.Lock()
        self._diverged: Optional[str] = None
        # Post-append observer (the WAL replication streamer): its
        # ``submit(seq, opcode, payload)`` runs AFTER the record is
        # durably appended, still under ``self._lock`` so the observed
        # order equals the log order. Must be non-blocking and never
        # raise usefully — failures are swallowed (replication is
        # redundancy, not the write path). Annotated with the concrete
        # sink type so the lock-order pass sees the acquisition chain.
        self._on_append: Optional["AppendSink"] = on_append
        records, loaded_torn, self._seq = self._wal.load_with_seq()
        # Torn/corrupt damage now surfaces as quarantined bytes (the WAL
        # moved the invalid suffix aside before this load), but the flag
        # keeps meaning "the directory carried damage we dropped".
        self.recovered_quarantined_bytes = self._wal.quarantined_bytes
        self.recovered_torn_tail = (
            loaded_torn or self._wal.quarantined_bytes > 0
        )
        self.recovered_records = len(records)
        for opcode, payload in records:
            apply_record(self._inner, opcode, payload)

    # -- plumbing ----------------------------------------------------------

    @property
    def wal(self) -> WriteAheadLog:
        return self._wal

    @property
    def seq(self) -> int:
        """The store's monotonic mutation sequence number (replication
        stream positions and failover source comparisons key off it)."""
        with self._lock:
            return self._seq

    def export_with_seq(self) -> Tuple[int, List[Tuple[int, bytes]]]:
        """An atomic ``(seq, compacted records)`` snapshot of the store —
        the replication baseline: a successor that applies the records and
        remembers the seq holds exactly the state at that sequence."""
        with self._lock:
            self._check_converged()
            return self._seq, export_records(self._inner)

    def _check_converged(self) -> None:
        if self._diverged is not None:
            raise StoreDivergedError(self._diverged)

    def _mutate(self, fn, opcode: int, payload: bytes):
        """Applies ``fn`` to the inner store, then logs it (apply-then-log:
        a rejected mutation — duplicate create, missing target — raises
        before anything reaches the log).

        A FAILED log write after the apply is a fail-stop: the RAM state
        now holds a mutation the WAL lost, so instead of serving state a
        restart would silently revert, the store poisons itself and every
        subsequent operation raises :class:`StoreDivergedError`.
        """
        with self._lock:
            self._check_converged()
            result = fn()
            try:
                self._wal.append(opcode, payload)
                self._seq += 1
                if self._wal.appended_since_snapshot >= self._snapshot_interval:
                    self._wal.compact(
                        export_records(self._inner), seq=self._seq
                    )
            except BaseException as e:
                self._diverged = (
                    f"WAL write failed after the mutation was applied "
                    f"({type(e).__name__}: {e}); RAM and log have diverged "
                    f"— restart the replica to recover to the logged state."
                )
                raise
            if self._on_append is not None:
                try:
                    self._on_append.submit(self._seq, opcode, payload)
                except Exception:  # replication is redundancy, not the
                    pass  # write path: a streamer fault must not fail RPCs
        return result

    def compact_now(self) -> None:
        """Forces a snapshot compaction (tests, graceful shutdown)."""
        with self._lock:
            self._check_converged()
            self._wal.compact(export_records(self._inner), seq=self._seq)

    def set_append_sink(self, sink: Optional["AppendSink"]) -> None:
        """Attaches (or replaces) the post-append replication observer —
        subprocess replicas build the datastore first (the WAL replay must
        not re-stream history) and hook the streamer in afterwards."""
        with self._lock:
            self._on_append = sink

    def close(self) -> None:
        self._wal.close()

    # -- studies -----------------------------------------------------------

    def create_study(self, study):
        return self._mutate(
            lambda: self._inner.create_study(study),
            CREATE_STUDY,
            study.SerializeToString(),
        )

    def load_study(self, study_name):
        self._check_converged()
        return self._inner.load_study(study_name)

    def update_study(self, study):
        return self._mutate(
            lambda: self._inner.update_study(study),
            UPDATE_STUDY,
            study.SerializeToString(),
        )

    def delete_study(self, study_name):
        return self._mutate(
            lambda: self._inner.delete_study(study_name),
            DELETE_STUDY,
            study_name.encode("utf-8"),
        )

    def list_studies(self, owner_name):
        self._check_converged()
        return self._inner.list_studies(owner_name)

    # -- trials ------------------------------------------------------------

    def create_trial(self, trial):
        return self._mutate(
            lambda: self._inner.create_trial(trial),
            CREATE_TRIAL,
            trial.SerializeToString(),
        )

    def get_trial(self, trial_name):
        self._check_converged()
        return self._inner.get_trial(trial_name)

    def update_trial(self, trial):
        return self._mutate(
            lambda: self._inner.update_trial(trial),
            UPDATE_TRIAL,
            trial.SerializeToString(),
        )

    def delete_trial(self, trial_name):
        return self._mutate(
            lambda: self._inner.delete_trial(trial_name),
            DELETE_TRIAL,
            trial_name.encode("utf-8"),
        )

    def list_trials(self, study_name, *, states=None):
        self._check_converged()
        return self._inner.list_trials(study_name, states=states)

    def max_trial_id(self, study_name):
        self._check_converged()
        return self._inner.max_trial_id(study_name)

    # -- suggestion operations --------------------------------------------

    def create_suggestion_operation(self, operation):
        return self._mutate(
            lambda: self._inner.create_suggestion_operation(operation),
            CREATE_SUGGESTION_OP,
            operation.SerializeToString(),
        )

    def get_suggestion_operation(self, operation_name):
        self._check_converged()
        return self._inner.get_suggestion_operation(operation_name)

    def update_suggestion_operation(self, operation):
        return self._mutate(
            lambda: self._inner.update_suggestion_operation(operation),
            UPDATE_SUGGESTION_OP,
            operation.SerializeToString(),
        )

    def list_suggestion_operations(
        self, study_name, client_id, filter_fn=None, *, done=None
    ):
        self._check_converged()
        return self._inner.list_suggestion_operations(
            study_name, client_id, filter_fn, done=done
        )

    def max_suggestion_operation_number(self, study_name, client_id):
        self._check_converged()
        return self._inner.max_suggestion_operation_number(study_name, client_id)

    # -- early stopping operations ----------------------------------------

    def create_early_stopping_operation(self, operation):
        return self._mutate(
            lambda: self._inner.create_early_stopping_operation(operation),
            CREATE_EARLY_STOPPING_OP,
            operation.SerializeToString(),
        )

    def get_early_stopping_operation(self, operation_name):
        self._check_converged()
        return self._inner.get_early_stopping_operation(operation_name)

    def update_early_stopping_operation(self, operation):
        return self._mutate(
            lambda: self._inner.update_early_stopping_operation(operation),
            UPDATE_EARLY_STOPPING_OP,
            operation.SerializeToString(),
        )

    # -- metadata ----------------------------------------------------------

    def update_metadata(self, study_name, study_metadata, trial_metadata):
        # Materialize the iterables once: they are consumed both by the
        # store apply and the wire record.
        study_kvs = list(study_metadata)
        trial_kvs = [(int(tid), kv) for tid, kv in trial_metadata]
        request = vizier_service_pb2.UpdateMetadataRequest(name=study_name)
        for kv in study_kvs:
            unit = request.deltas.add()
            unit.trial_id = 0
            unit.key_value.CopyFrom(kv)
        for trial_id, kv in trial_kvs:
            unit = request.deltas.add()
            unit.trial_id = trial_id
            unit.key_value.CopyFrom(kv)
        return self._mutate(
            lambda: self._inner.update_metadata(study_name, study_kvs, trial_kvs),
            UPDATE_METADATA,
            request.SerializeToString(),
        )


# -- replay / snapshot helpers ---------------------------------------------


def export_records(
    store: ram_datastore.NestedDictRAMDataStore,
) -> List[Tuple[int, bytes]]:
    """The store's full state as a compacted record sequence.

    Replaying these records into an empty store recreates the state —
    a snapshot IS a compacted WAL, so there is exactly one on-disk format
    and one replay path.
    """
    studies, trials, ops, es_ops = store.export_protos()
    records: List[Tuple[int, bytes]] = []
    for study in studies:
        records.append((CREATE_STUDY, study.SerializeToString()))
    for trial in trials:
        records.append((CREATE_TRIAL, trial.SerializeToString()))
    for op in ops:
        records.append((CREATE_SUGGESTION_OP, op.SerializeToString()))
    for op in es_ops:
        records.append((CREATE_EARLY_STOPPING_OP, op.SerializeToString()))
    return records


def apply_record(
    store: datastore_lib.DataStore, opcode: int, payload: bytes
) -> None:
    """Applies one record to ``store``, tolerantly.

    Tolerant replay is what makes the crash windows safe: a create of an
    existing resource applies as an update (double-applied log over a
    fresh snapshot), a delete/update of a missing resource is skipped
    (the delete already happened / its study was deleted later in the
    log). Applying a record SEQUENCE in order therefore always converges
    to the state the sequence describes.
    """
    if opcode in (CREATE_STUDY, UPDATE_STUDY):
        study = study_pb2.Study.FromString(payload)
        try:
            store.create_study(study)
        except datastore_lib.AlreadyExistsError:
            store.update_study(study)
    elif opcode == DELETE_STUDY:
        try:
            store.delete_study(payload.decode("utf-8"))
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_TRIAL, UPDATE_TRIAL):
        trial = study_pb2.Trial.FromString(payload)
        try:
            try:
                store.create_trial(trial)
            except datastore_lib.AlreadyExistsError:
                store.update_trial(trial)
        except datastore_lib.NotFoundError:
            pass  # study deleted later in the log
    elif opcode == DELETE_TRIAL:
        try:
            store.delete_trial(payload.decode("utf-8"))
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_SUGGESTION_OP, UPDATE_SUGGESTION_OP):
        op = vizier_service_pb2.Operation.FromString(payload)
        try:
            try:
                store.create_suggestion_operation(op)
            except datastore_lib.AlreadyExistsError:
                store.update_suggestion_operation(op)
        except datastore_lib.NotFoundError:
            pass
    elif opcode in (CREATE_EARLY_STOPPING_OP, UPDATE_EARLY_STOPPING_OP):
        op = vizier_service_pb2.EarlyStoppingOperation.FromString(payload)
        try:
            # create doubles as upsert for early-stopping ops in the RAM
            # store, but go through update for missing-create symmetry.
            store.create_early_stopping_operation(op)
        except datastore_lib.NotFoundError:
            pass
    elif opcode == UPDATE_METADATA:
        request = vizier_service_pb2.UpdateMetadataRequest.FromString(payload)
        study_kvs = [d.key_value for d in request.deltas if d.trial_id == 0]
        trial_kvs = [
            (int(d.trial_id), d.key_value)
            for d in request.deltas
            if d.trial_id != 0
        ]
        try:
            store.update_metadata(request.name, study_kvs, trial_kvs)
        except datastore_lib.NotFoundError:
            pass
    elif opcode == SNAPSHOT_META:
        pass  # bookkeeping record: carries a sequence number, no state
    else:
        raise ValueError(f"Unknown WAL opcode: {opcode}")


def read_directory(
    directory: str,
) -> Tuple[List[Tuple[int, bytes]], bool]:
    """Snapshot+log records of a (possibly dead) replica's WAL directory.

    Read-only: used by failover to lift a dead replica's studies into
    their successor replicas without opening the directory for append.
    """
    records, torn = read_directory_with_seqs(directory)
    return [(opcode, payload) for _seq, opcode, payload in records], torn


def read_directory_with_seqs(
    directory: str,
) -> Tuple[List[Tuple[int, int, bytes]], bool]:
    """Like :func:`read_directory`, with each record's mutation sequence.

    Snapshot records all carry the snapshot's base sequence (they are a
    compaction of everything up to it); live log record *i* carries
    ``base + 1 + i``. Read-only and damage-tolerant: a corrupt or torn
    suffix in either file is excluded (the longest valid prefix is what a
    failover can trust), reported via the second element.
    """
    snapshot, snapshot_torn = WriteAheadLog._read_records(
        os.path.join(directory, SNAPSHOT_FILE)
    )
    if snapshot_torn:
        snapshot = []
    base_seq, snapshot = split_meta(snapshot)
    log, log_torn = WriteAheadLog._read_records(
        os.path.join(directory, LOG_FILE)
    )
    records = [(base_seq, opcode, payload) for opcode, payload in snapshot]
    offset = 0
    for opcode, payload in log:
        if opcode == SNAPSHOT_META:
            continue
        offset += 1
        records.append((base_seq + offset, opcode, payload))
    return records, log_torn or snapshot_torn


def group_by_study(
    records: Iterable[Tuple[int, int, bytes]],
) -> Dict[str, List[Tuple[int, int, bytes]]]:
    """``study -> [(seq, opcode, payload)]`` in record order (recovery
    source selection compares and replays per study)."""
    out: Dict[str, List[Tuple[int, int, bytes]]] = {}
    for seq, opcode, payload in records:
        if opcode == SNAPSHOT_META:
            continue
        out.setdefault(study_key_of(opcode, payload), []).append(
            (seq, opcode, payload)
        )
    return out
