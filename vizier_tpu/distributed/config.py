"""Knobs for the sharded service tier.

Everything defaults to a working single-host tier; each switch is declared
in the central registry (``vizier_tpu.analysis.registry``) and documented in
``docs/guides/running_the_service.md``:

- ``VIZIER_DISTRIBUTED=0``                 — router off-switch: every study
  routes to the first replica (a sharded deployment degrades to the
  single-server topology without touching client code);
- ``VIZIER_DISTRIBUTED_REPLICAS=N``        — replica count for tiers built
  from the environment (``ReplicaManager()`` with no explicit count);
- ``VIZIER_DISTRIBUTED_WAL_DIR=/path``     — root directory for per-replica
  snapshot+WAL persistence ('' = RAM only, no restart warmth);
- ``VIZIER_DISTRIBUTED_SNAPSHOT_INTERVAL`` — mutations per shard between
  snapshot compactions (smaller = shorter replay, more snapshot I/O);
- ``VIZIER_DISTRIBUTED_WAL_FSYNC=1``       — fsync the WAL per append:
  mutations survive OS crashes/power loss, not just process crashes, at
  the cost of a disk sync on every write (off by default);
- ``VIZIER_DISTRIBUTED_REPLICATION=0``     — WAL replication off-switch:
  appends stream to each study's rendezvous successors' standby logs so
  failover needs no shared filesystem (on by default when a WAL root is
  configured; off = the PR 12 local-disk-only failover, bit-identical);
- ``VIZIER_DISTRIBUTED_REPLICATION_FACTOR`` — standby copies per study (K
  rendezvous successors receive its records);
- ``VIZIER_DISTRIBUTED_REPLICATION_QUEUE``  — per-origin streamer queue
  bound (overflow drops + re-baselines, never blocks the write path);
- ``VIZIER_DISTRIBUTED_REPLICATION_BATCH``  — records per streamed batch;
- ``VIZIER_DISTRIBUTED_LEASE_TIMEOUT_S``   — seconds without a renewed
  heartbeat before the subprocess fleet manager declares a replica dead
  (lease-based failure detection — ``distributed.subprocess_fleet``);
- ``VIZIER_DISTRIBUTED_HEARTBEAT_INTERVAL_S`` — cadence of the manager's
  lease-renewal Heartbeat probes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# All VIZIER_* switches are declared in (and read through) the central
# registry; enforced by the env_registry analysis pass.
from vizier_tpu.analysis import registry as _registry

DEFAULT_REPLICAS = 4
DEFAULT_SNAPSHOT_INTERVAL = 256
DEFAULT_REPLICATION_FACTOR = 2
DEFAULT_REPLICATION_QUEUE = 4096
DEFAULT_REPLICATION_BATCH = 64
DEFAULT_LEASE_TIMEOUT_S = 3.0
DEFAULT_HEARTBEAT_INTERVAL_S = 1.0


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Knobs for the sharded service tier."""

    # Router on/off. Off = rendezvous ranking is ignored and every study
    # maps to the first replica; the WAL and replica plumbing still work.
    routing: bool = True
    # Replica count used when a tier is built without an explicit count.
    num_replicas: int = DEFAULT_REPLICAS
    # Snapshot+WAL root ('' / None = no persistence). Each replica owns the
    # subdirectory ``<wal_root>/<replica_id>``.
    wal_root: Optional[str] = None
    # Mutations between snapshot compactions (per shard).
    snapshot_interval: int = DEFAULT_SNAPSHOT_INTERVAL
    # fsync the WAL on every append. Off = appends are flushed to the OS
    # (durable across process crashes only); on = durable across OS
    # crashes/power loss too, at a per-mutation disk-sync cost.
    wal_fsync: bool = False
    # Deadline-bounded Pythia dispatch on in-process replicas. The router
    # already owns wedged-replica semantics (health check -> mark down ->
    # failover), so the per-suggest dispatch thread the deadline path
    # spawns is redundant overhead inside a managed tier; subprocess
    # replicas (no manager watching them) keep it on.
    replica_deadlines: bool = False
    # Shared-nothing durability: stream every WAL append to the study's
    # K rendezvous successors' standby logs, so failover needs no shared
    # filesystem. Active only when a WAL root is configured (the stream
    # IS the WAL's append feed); off = PR 12 local-disk-only failover.
    replication: bool = True
    replication_factor: int = DEFAULT_REPLICATION_FACTOR
    # Streamer bounds: a full queue drops + re-baselines (the write path
    # never blocks on replication); batches cap per-delivery work.
    replication_queue: int = DEFAULT_REPLICATION_QUEUE
    replication_batch: int = DEFAULT_REPLICATION_BATCH
    # Lease-based failure detection for SUBPROCESS replicas: the fleet
    # manager renews a per-replica lease on every successful Heartbeat
    # RPC and declares death when a lease runs out. A slow-but-alive
    # replica keeps renewing (delays shorter than the timeout never
    # trigger failover); a partitioned or crashed one expires.
    lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S
    heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        """The default config with environment overrides applied."""
        return cls(
            routing=_registry.env_on("VIZIER_DISTRIBUTED"),
            num_replicas=max(
                1,
                _registry.env_int(
                    "VIZIER_DISTRIBUTED_REPLICAS", DEFAULT_REPLICAS
                ),
            ),
            wal_root=_registry.env_str("VIZIER_DISTRIBUTED_WAL_DIR") or None,
            snapshot_interval=max(
                1,
                _registry.env_int(
                    "VIZIER_DISTRIBUTED_SNAPSHOT_INTERVAL",
                    DEFAULT_SNAPSHOT_INTERVAL,
                ),
            ),
            wal_fsync=_registry.env_on("VIZIER_DISTRIBUTED_WAL_FSYNC"),
            replication=_registry.env_on("VIZIER_DISTRIBUTED_REPLICATION"),
            replication_factor=max(
                1,
                _registry.env_int(
                    "VIZIER_DISTRIBUTED_REPLICATION_FACTOR",
                    DEFAULT_REPLICATION_FACTOR,
                ),
            ),
            replication_queue=max(
                1,
                _registry.env_int(
                    "VIZIER_DISTRIBUTED_REPLICATION_QUEUE",
                    DEFAULT_REPLICATION_QUEUE,
                ),
            ),
            replication_batch=max(
                1,
                _registry.env_int(
                    "VIZIER_DISTRIBUTED_REPLICATION_BATCH",
                    DEFAULT_REPLICATION_BATCH,
                ),
            ),
            lease_timeout_s=max(
                0.1,
                _registry.env_float(
                    "VIZIER_DISTRIBUTED_LEASE_TIMEOUT_S",
                    DEFAULT_LEASE_TIMEOUT_S,
                ),
            ),
            heartbeat_interval_s=max(
                0.01,
                _registry.env_float(
                    "VIZIER_DISTRIBUTED_HEARTBEAT_INTERVAL_S",
                    DEFAULT_HEARTBEAT_INTERVAL_S,
                ),
            ),
        )

    def as_dict(self) -> dict:
        """JSON-ready dump (evidence tools stamp this into their reports)."""
        return dataclasses.asdict(self)
