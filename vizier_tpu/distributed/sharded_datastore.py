"""``ShardedDataStore``: study-partitioned composite over per-shard stores.

Implements the ``DataStore`` ABC by routing every call to the shard that
owns the study — the same rendezvous placement the service-level
``StudyRouter`` computes, so a client-side router and a server-side
sharded store independently agree about where a study lives. Study-scoped
operations stay single-shard (the per-shard stores keep their constant-
time open/undone/max indexes and their own locking); only the owner-scoped
``list_studies`` fans out across shards.

Stateless by construction: no lock of its own, no shared mutable state —
the composite adds zero lock-order surface on top of its shards.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from vizier_tpu.distributed import routing
from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import resources


class ShardedDataStore(datastore_lib.DataStore):
    """Partitions studies across ``shards`` by rendezvous hashing."""

    def __init__(
        self,
        shards: Sequence[datastore_lib.DataStore],
        *,
        shard_ids: Optional[Sequence[str]] = None,
        router: Optional[routing.StudyRouter] = None,
    ):
        if not shards:
            raise ValueError("ShardedDataStore needs at least one shard.")
        self._shards = list(shards)
        ids = list(shard_ids or (f"shard-{i}" for i in range(len(shards))))
        if len(ids) != len(self._shards):
            raise ValueError(
                f"{len(ids)} shard ids for {len(self._shards)} shards."
            )
        self._by_id = dict(zip(ids, self._shards))
        self._router = router or routing.StudyRouter(ids)

    @property
    def router(self) -> routing.StudyRouter:
        return self._router

    @property
    def shards(self) -> List[datastore_lib.DataStore]:
        return list(self._shards)

    def shard_for(self, study_name: str) -> datastore_lib.DataStore:
        return self._by_id[self._router.replica_for(study_name)]

    def _shard_of_trial(self, trial_name: str) -> datastore_lib.DataStore:
        r = resources.TrialResource.from_name(trial_name)
        return self.shard_for(r.study_resource.name)

    def _shard_of_operation(self, operation_name: str) -> datastore_lib.DataStore:
        r = resources.SuggestionOperationResource.from_name(operation_name)
        return self.shard_for(resources.StudyResource(r.owner_id, r.study_id).name)

    def _shard_of_es_operation(
        self, operation_name: str
    ) -> datastore_lib.DataStore:
        r = resources.EarlyStoppingOperationResource.from_name(operation_name)
        return self.shard_for(resources.StudyResource(r.owner_id, r.study_id).name)

    # -- studies -----------------------------------------------------------

    def create_study(self, study):
        return self.shard_for(study.name).create_study(study)

    def load_study(self, study_name):
        return self.shard_for(study_name).load_study(study_name)

    def update_study(self, study):
        return self.shard_for(study.name).update_study(study)

    def delete_study(self, study_name):
        return self.shard_for(study_name).delete_study(study_name)

    def list_studies(self, owner_name):
        out = []
        for shard in self._shards:
            out.extend(shard.list_studies(owner_name))
        return out

    # -- trials ------------------------------------------------------------

    def create_trial(self, trial):
        return self._shard_of_trial(trial.name).create_trial(trial)

    def get_trial(self, trial_name):
        return self._shard_of_trial(trial_name).get_trial(trial_name)

    def update_trial(self, trial):
        return self._shard_of_trial(trial.name).update_trial(trial)

    def delete_trial(self, trial_name):
        return self._shard_of_trial(trial_name).delete_trial(trial_name)

    def list_trials(self, study_name, *, states=None):
        return self.shard_for(study_name).list_trials(study_name, states=states)

    def max_trial_id(self, study_name):
        return self.shard_for(study_name).max_trial_id(study_name)

    # -- suggestion operations --------------------------------------------

    def create_suggestion_operation(self, operation):
        return self._shard_of_operation(operation.name).create_suggestion_operation(
            operation
        )

    def get_suggestion_operation(self, operation_name):
        return self._shard_of_operation(operation_name).get_suggestion_operation(
            operation_name
        )

    def update_suggestion_operation(self, operation):
        return self._shard_of_operation(operation.name).update_suggestion_operation(
            operation
        )

    def list_suggestion_operations(
        self, study_name, client_id, filter_fn=None, *, done=None
    ):
        return self.shard_for(study_name).list_suggestion_operations(
            study_name, client_id, filter_fn, done=done
        )

    def max_suggestion_operation_number(self, study_name, client_id):
        return self.shard_for(study_name).max_suggestion_operation_number(
            study_name, client_id
        )

    # -- early stopping operations ----------------------------------------

    def create_early_stopping_operation(self, operation):
        return self._shard_of_es_operation(
            operation.name
        ).create_early_stopping_operation(operation)

    def get_early_stopping_operation(self, operation_name):
        return self._shard_of_es_operation(
            operation_name
        ).get_early_stopping_operation(operation_name)

    def update_early_stopping_operation(self, operation):
        return self._shard_of_es_operation(
            operation.name
        ).update_early_stopping_operation(operation)

    # -- metadata ----------------------------------------------------------

    def update_metadata(self, study_name, study_metadata, trial_metadata):
        return self.shard_for(study_name).update_metadata(
            study_name, study_metadata, trial_metadata
        )
