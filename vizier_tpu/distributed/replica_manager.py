"""``ReplicaManager``: an in-process sharded Vizier tier with failover.

N ``VizierServicer`` replicas — each owning its shard of the study
population and (optionally) a per-replica snapshot+WAL directory — behind
one :class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub`. All
replicas feed ONE shared ``PythiaServicer``: the designer cache, request
coalescer, and cross-study batch executor are fleet-wide, so suggestion
compute batches across replicas exactly as it batches across studies on a
single server. The shared Pythia reads trials back through the router too,
so its view follows failover automatically.

Failure model:

- A dead replica (``kill_replica`` in chaos runs, a crashed process in
  real life) surfaces as transport errors on its RPCs. The routed stub
  reports them to :meth:`_on_endpoint_failure`; the manager verifies the
  replica is really dead (a chaos-injected fault on a live replica is NOT
  a failover trigger — the client retry handles it), marks it down, and
  **lifts the dead replica's studies onto their rendezvous successors** by
  replaying its WAL directory into the successors' datastores (which
  re-logs every record — the handoff itself is durable). The failing RPC
  then re-raises; the caller's reliability retries land on the successor.
- ``revive_replica`` rebuilds a replica from its own WAL (restart warm);
  if its studies were failed over meanwhile, they are copied back from
  the successors before the replica is marked up.

Lock order: ``ReplicaManager._lock`` guards the replica/failover tables
only; WAL replay and datastore writes run OUTSIDE it (the failover path
serializes on ``_failover_lock`` instead, which never nests inside
``_lock``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from vizier_tpu.distributed import config as config_lib
from vizier_tpu.distributed import router_stub
from vizier_tpu.distributed import routing
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.observability import fleet as fleet_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.service import ram_datastore

_logger = logging.getLogger(__name__)


class ReplicaDownError(ConnectionError):
    """RPC reached a dead replica (transport-shaped, classified transient)."""


class _ReplicaEndpoint:
    """The callable surface of one replica; raises when the replica is dead."""

    def __init__(self, replica: "Replica"):
        self._replica = replica

    def __getattr__(self, name: str):
        attr = getattr(self._replica.servicer, name)
        if not callable(attr) or name.startswith("_"):
            return attr

        def call(*args, **kwargs):
            self._replica.enter()
            try:
                return attr(*args, **kwargs)
            finally:
                self._replica.leave()

        return call


class Replica:
    """One shard: servicer + datastore (+ WAL directory when persistent)."""

    def __init__(self, replica_id: str, servicer, datastore, wal_dir: Optional[str]):
        self.replica_id = replica_id
        self.servicer = servicer
        self.datastore = datastore
        self.wal_dir = wal_dir
        self.alive = True
        self.endpoint = _ReplicaEndpoint(self)
        # Manager-shared per-thread RPC depth (set by the manager): lets
        # the failover barrier exempt threads already inside an endpoint
        # call (their nested routed reads must not wait on a drain that is
        # waiting on them).
        self.thread_depth = threading.local()
        # In-flight RPC accounting, per thread: failover drains these
        # before reading the WAL (a dead replica's in-flight RPCs keep
        # appending until they return — replaying before they finish
        # silently drops writes the client already observed).
        self._inflight_cond = threading.Condition()
        self._inflight: Dict[int, int] = {}
        # Set by fail_over: called (outside the condition) whenever an
        # in-flight RPC leaves a dead replica, so writes it appended after
        # the failover replay (it was admitted alive and kept executing —
        # including the self-triggered-failover edge where a dispatch
        # inside the RPC tripped the failover itself) are caught up onto
        # the successors before the RPC's response reaches the client.
        self.on_drained = None

    def enter(self) -> None:
        """Admits one RPC (liveness check + in-flight count, atomically)."""
        tid = threading.get_ident()
        with self._inflight_cond:
            if not self.alive:
                raise ReplicaDownError(f"replica {self.replica_id} is down")
            self._inflight[tid] = self._inflight.get(tid, 0) + 1
        self.thread_depth.n = getattr(self.thread_depth, "n", 0) + 1

    def leave(self) -> None:
        tid = threading.get_ident()
        self.thread_depth.n = getattr(self.thread_depth, "n", 1) - 1
        with self._inflight_cond:
            count = self._inflight.get(tid, 0) - 1
            if count <= 0:
                self._inflight.pop(tid, None)
            else:
                self._inflight[tid] = count
            self._inflight_cond.notify_all()
            callback = self.on_drained if not self.alive else None
        if callback is not None:
            callback()

    def wait_quiesced(self, timeout_secs: float) -> bool:
        """Blocks until no OTHER thread has an RPC in flight (the calling
        thread's own nested RPC must not deadlock its own failover — a
        self-triggered failover from inside a dispatch is the rare edge
        the timeout also backstops). Returns False on timeout."""
        deadline = time.monotonic() + timeout_secs
        me = threading.get_ident()
        with self._inflight_cond:
            while any(tid != me for tid in self._inflight):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True


class ReplicaManager:
    """Builds, health-checks, and fails over an in-process replica fleet."""

    def __init__(
        self,
        num_replicas: Optional[int] = None,
        *,
        config: Optional[config_lib.DistributedConfig] = None,
        wal_root: Optional[str] = None,
        policy_factory=None,
        serving_config=None,
        reliability_config=None,
    ):
        import dataclasses

        from vizier_tpu.reliability import config as reliability_config_lib
        from vizier_tpu.service import pythia_service, vizier_service

        self.config = config or config_lib.DistributedConfig.from_env()
        self._num_replicas = max(1, num_replicas or self.config.num_replicas)
        self._wal_root = wal_root if wal_root is not None else self.config.wal_root
        replica_ids = [f"replica-{i}" for i in range(self._num_replicas)]
        self.router = routing.StudyRouter(
            replica_ids, routing=self.config.routing
        )

        # In-process replicas run the synchronous Pythia dispatch: the
        # manager (not a per-request watchdog thread) owns wedged-replica
        # semantics here, and the thread-per-suggest the deadline path
        # spawns is measurable overhead at tier throughput. Everything
        # else (retries, breaker, fallback) keeps its configured state.
        base_reliability = (
            reliability_config or reliability_config_lib.ReliabilityConfig.from_env()
        )
        replica_reliability = dataclasses.replace(
            base_reliability, deadlines=self.config.replica_deadlines
        )

        # One Pythia for the whole fleet; its trial reads route like any
        # other client so failover moves its view too. Constructed first
        # (the replicas need it), connected to the router stub below.
        self._pythia = pythia_service.PythiaServicer(
            None,
            policy_factory,
            serving_config=serving_config,
            reliability_config=base_reliability,
        )
        registry = self._pythia.serving_runtime.stats.registry
        self._failovers = registry.counter(
            "vizier_replica_failovers", help="Replica failovers performed."
        )
        self._restored = registry.counter(
            "vizier_replica_restored_studies",
            help="Studies lifted onto successors during failover.",
        )

        self._lock = threading.Lock()  # replica + failover bookkeeping only
        # One per-thread RPC-depth record shared by every replica: the
        # failover barrier exempts threads already inside an endpoint call.
        self._thread_depth = threading.local()
        # Topology transitions in progress (failover replay / revive
        # copy-back): fresh RPCs park on the barrier until zero.
        self._transition_cond = threading.Condition()
        self._transitions = 0
        self._replicas: Dict[str, Replica] = {}
        for rid in replica_ids:
            self._replicas[rid] = self._build_replica(
                rid, vizier_service, replica_reliability
            )

        self._stub = router_stub.RoutedVizierStub(
            {rid: r.endpoint for rid, r in self._replicas.items()},
            router=self.router,
            on_failure=self._on_endpoint_failure,
            registry=registry,
            retry_sink=self._record_retries,
            barrier=self.failover_barrier,
        )
        self._pythia.connect_to_vizier(self._stub)

        # Failover serialization (never nests inside self._lock).
        self._failover_lock = threading.Lock()
        self._failed_over: set = set()
        # replica_id -> WAL records already replayed onto successors
        # (late-write catch-up baseline; see _catch_up_late_writes).
        self._replayed_records: Dict[str, int] = {}
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    # -- construction helpers ---------------------------------------------

    def _build_replica(self, replica_id, vizier_service_mod, reliability):
        wal_dir = None
        if self._wal_root:
            wal_dir = os.path.join(self._wal_root, replica_id)
            datastore = wal_lib.PersistentDataStore(
                wal_dir,
                snapshot_interval=self.config.snapshot_interval,
                fsync=self.config.wal_fsync,
            )
        else:
            datastore = ram_datastore.NestedDictRAMDataStore()
        servicer = vizier_service_mod.VizierServicer(
            datastore=datastore, reliability_config=reliability
        )
        # Tag the replica's request spans so a fleet dump can split one
        # process's span ring back into per-replica files.
        servicer.replica_id = replica_id
        servicer.set_pythia(self._pythia)
        replica = Replica(replica_id, servicer, datastore, wal_dir)
        replica.thread_depth = self._thread_depth
        return replica

    def _record_retries(self, amount: int) -> None:
        self._pythia.serving_runtime.stats.increment("retries", amount)

    # -- public surface ----------------------------------------------------

    @property
    def stub(self) -> router_stub.RoutedVizierStub:
        """The drop-in service stub clients (and the shared Pythia) use."""
        return self._stub

    @property
    def pythia(self):
        return self._pythia

    def replica(self, replica_id: str) -> Replica:
        with self._lock:
            return self._replicas[replica_id]

    def replica_ids(self) -> List[str]:
        return list(self.router.replica_ids)

    def serving_stats(self) -> dict:
        """Fleet stats: shared-Pythia counters + router + per-replica."""
        stats = dict(self._pythia.serving_stats())
        stats["router"] = self.router.snapshot()
        stats["replicas"] = self._stub.stats()["replicas"]
        stats["failovers"] = int(
            sum(
                self._failovers.value(**dict(key))
                for key in self._failovers.label_keys()
            )
        )
        stats["restored_studies"] = int(self._restored.value())
        return stats

    def prometheus_text(self) -> str:
        return self._pythia.prometheus_text()

    def dump_observability(self, out_dir: str) -> Dict[str, List[str]]:
        """Writes the fleet's observability dumps into ``out_dir``.

        The in-process tier shares one span ring; this splits it back into
        per-replica ``<replica>-spans.jsonl`` files (request spans carry a
        ``replica`` attribute) plus a ``client-spans.jsonl`` for
        unattributed spans, and writes the shared registry snapshot and
        the flight-recorder event list — the exact file layout subprocess
        replicas produce via ``replica_main --obs-dump-dir``, so
        ``observability.fleet`` (and ``tools/obs_report.py --fleet``)
        merges either deployment the same way.
        """
        tracer = tracing_lib.get_tracer()
        by_source: Dict[str, List[dict]] = {}
        for span in tracer.finished_spans():
            data = span.to_dict()
            source = (data.get("attributes") or {}).get("replica") or "client"
            by_source.setdefault(source, []).append(data)
        written: Dict[str, List[str]] = {"spans": [], "other": []}
        for source, spans in sorted(by_source.items()):
            written["spans"].append(
                fleet_lib.write_spans(out_dir, source, spans)
            )
        paths = fleet_lib.dump_process(
            out_dir,
            "fleet",
            registry=self._pythia.serving_runtime.metrics,
            recorder=recorder_lib.get_recorder(),
        )
        written["other"] = sorted(paths.values())
        return written

    def shutdown(self) -> None:
        self.stop_health_loop()
        self._pythia.shutdown()
        with self._lock:
            replicas = list(self._replicas.values())
        for replica in replicas:
            close = getattr(replica.datastore, "close", None)
            if close is not None:
                close()

    # -- topology-transition barrier ---------------------------------------

    def failover_barrier(self, timeout_secs: float = 30.0) -> None:
        """Routed-stub hook: parks fresh RPCs while a failover replay or
        revive copy-back is mid-flight, so no request can land on a
        successor the replay has not populated yet (NotFound there reads
        as "study deleted" — no retry fixes it). Threads already inside an
        endpoint call pass straight through: the failover drain is waiting
        on exactly those threads, and parking their nested reads would
        deadlock the drain. Bounded: after ``timeout_secs`` the request
        proceeds and at worst degrades through the reliability layer."""
        if getattr(self._thread_depth, "n", 0) > 0:
            return
        deadline = time.monotonic() + timeout_secs
        with self._transition_cond:
            while self._transitions > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._transition_cond.wait(remaining)

    def _begin_transition(self) -> None:
        with self._transition_cond:
            self._transitions += 1

    def _end_transition(self) -> None:
        with self._transition_cond:
            self._transitions -= 1
            self._transition_cond.notify_all()

    # -- chaos / lifecycle -------------------------------------------------

    def kill_replica(self, replica_id: str) -> None:
        """Simulates a replica crash: every subsequent RPC to it fails.

        Detection and failover happen through the normal channels (a
        failed RPC's failure hook, or the health loop) — exactly as they
        would for a crashed process.
        """
        self.replica(replica_id).alive = False
        recorder_lib.get_recorder().record(
            None, "replica_killed", replica=replica_id
        )

    def fail_over(self, replica_id: str) -> int:
        """Marks a dead replica down and lifts its studies onto successors.

        Returns the number of studies restored. Idempotent; a no-op for
        replicas that already failed over.
        """
        # Fast path WITHOUT the failover lock: an RPC thread whose nested
        # router read trips over the dead replica mid-failover must return
        # immediately, not queue behind the in-progress failover that is
        # draining it (the drain below waits for exactly such threads).
        with self._lock:
            if replica_id in self._failed_over:
                return 0
        with self._failover_lock:
            with self._lock:
                if replica_id in self._failed_over:
                    return 0
                replica = self._replicas[replica_id]
                if replica.alive:
                    # Either caller misuse (no kill first) or, under load,
                    # a concurrent revive won the failover lock between
                    # this caller observing the replica dead and getting
                    # here — the replica is serving again, nothing to do.
                    return 0
                self._failed_over.add(replica_id)
            self._begin_transition()  # fresh RPCs park until replay lands
            try:
                self.router.mark_down(replica_id)
                # Late-write catch-up hook first (any leave() from here on
                # serializes behind this failover via _failover_lock), then
                # drain in-flight RPCs before reading the WAL: an RPC
                # admitted while the replica was alive may still be
                # appending; replaying a prefix would hand successors a
                # store missing writes the client already saw (NotFound on
                # the very next CompleteTrial).
                replica.on_drained = (
                    lambda: self._catch_up_late_writes(replica)
                )
                if not replica.wait_quiesced(30.0):
                    _logger.warning(
                        "Failing over %s with RPCs still in flight after "
                        "30s; their writes catch up when they drain.",
                        replica.replica_id,
                    )
                restored, successors, replayed = self._restore_from_wal(
                    replica
                )
                with self._lock:
                    self._replayed_records[replica_id] = replayed
                if replica.wal_dir:
                    # Its studies now live on successors: a live-replica
                    # ListStudies fan-out is complete again. RAM-only
                    # replicas stay unaccounted — their studies are gone,
                    # and listings keep failing loudly rather than
                    # silently shrinking.
                    self._stub.note_failed_over(replica_id)
            finally:
                self._end_transition()
        # Counter updates (and the recorder append) outside the failover
        # lock: metric locks must not nest under tier mutexes
        # (serving-stack convention, enforced by the chaos soak's runtime
        # lock-order cross-check).
        self._failovers.inc(replica=replica_id)
        self._restored.inc(restored)
        # Structured failover event: with just the vizier_replica_*
        # counters, the fleet's topology history was gone the moment the
        # numbers were read — the recorder keeps who died, when, which
        # successors took its studies, and how many moved.
        recorder_lib.get_recorder().record(
            None,
            "replica_failover",
            replica=replica_id,
            successors=sorted(successors),
            restored_studies=restored,
        )
        return restored

    def _restore_from_wal(self, replica: Replica) -> Tuple[int, set, int]:
        """Replays a dead replica's WAL into its successors' datastores.

        Returns ``(studies_restored, successor_ids, records_replayed)``.
        """
        if not replica.wal_dir:
            # RAM-only replica: its studies are lost until recreated.
            return 0, set(), 0
        records, torn = wal_lib.read_directory(replica.wal_dir)
        if torn:
            _logger.warning(
                "Dropped a torn WAL tail while failing over %s.",
                replica.replica_id,
            )
        studies: set = set()
        successors: set = set()
        for opcode, payload in records:
            study_key = wal_lib.study_key_of(opcode, payload)
            successor_id = self.router.replica_for(study_key)
            successor = self.replica(successor_id)
            # Applying through the successor's datastore re-logs each
            # record into the successor's own WAL: the handoff is durable.
            wal_lib.apply_record(successor.datastore, opcode, payload)
            studies.add(study_key)
            successors.add(successor_id)
        return len(studies), successors, len(records)

    def _catch_up_late_writes(self, replica: Replica) -> None:
        """Replays WAL records a dead replica appended AFTER its failover.

        The self-triggered-failover edge: an RPC in flight on the dying
        replica can itself trip the failover (a nested routed read hits
        the corpse) and then keep executing — its writes land in the dead
        WAL after the replay read. ``Replica.leave`` calls this when the
        last such RPC drains, so the tail reaches the successors before
        the RPC's response reaches the client. Idempotent and serialized
        with failover/revive via ``_failover_lock``.
        """
        with self._failover_lock:
            with self._lock:
                start = self._replayed_records.get(replica.replica_id)
            if start is None or not replica.wal_dir:
                return  # failover incomplete or RAM-only: nothing to do
            records, _torn = wal_lib.read_directory(replica.wal_dir)
            tail = records[start:]
            if not tail:
                return
            for opcode, payload in tail:
                study_key = wal_lib.study_key_of(opcode, payload)
                successor = self.replica(self.router.replica_for(study_key))
                wal_lib.apply_record(successor.datastore, opcode, payload)
            with self._lock:
                self._replayed_records[replica.replica_id] = len(records)
        recorder_lib.get_recorder().record(
            None,
            "replica_failover_catchup",
            replica=replica.replica_id,
            records=len(tail),
        )

    def revive_replica(self, replica_id: str) -> None:
        """Restarts a replica warm from its WAL and routes its studies back.

        Studies that failed over while it was down are copied back from
        their interim successors (and deleted there so the owner is unique
        again); studies DELETED while it was down exist on no successor
        and are deleted from the rebuilt store too, not resurrected from
        its stale WAL. Assumes quiesced traffic for the handback window —
        the copy-back is not a transactional migration.
        """
        from vizier_tpu.reliability import config as reliability_config_lib
        from vizier_tpu.service import vizier_service
        import dataclasses

        # Serialize with fail_over (and the late-write catch-up): a revive
        # racing an in-flight failover would copy back from successors the
        # WAL replay is still populating — partial state marked up, the
        # rest of the replay stranded on the successors.
        with self._failover_lock:
            with self._lock:
                old = self._replicas[replica_id]
                was_failed_over = replica_id in self._failed_over
            if old.alive:
                return
            self._begin_transition()  # fresh RPCs park during copy-back
            try:
                close = getattr(old.datastore, "close", None)
                if close is not None:
                    close()
                reliability = dataclasses.replace(
                    reliability_config_lib.ReliabilityConfig.from_env(),
                    deadlines=self.config.replica_deadlines,
                )
                fresh = self._build_replica(
                    replica_id, vizier_service, reliability
                )
                if was_failed_over:
                    self._copy_back_from_successors(fresh)
                with self._lock:
                    self._replicas[replica_id] = fresh
                    self._failed_over.discard(replica_id)
                    self._replayed_records.pop(replica_id, None)
                # _ReplicaEndpoint objects are bound per Replica; repoint
                # the stub.
                self._stub.set_endpoint(replica_id, fresh.endpoint)
                self.router.mark_up(replica_id)
            finally:
                self._end_transition()
        recorder_lib.get_recorder().record(
            None,
            "replica_revive",
            replica=replica_id,
            was_failed_over=was_failed_over,
        )

    def _copy_back_from_successors(self, fresh: Replica) -> None:
        """Moves studies the revived replica will own back from successors.

        Successor CURRENT state, not WAL history, is what comes back — so
        after the copy, any study the revived replica rebuilt from its own
        (stale) WAL that exists on NO live successor was deleted while the
        replica was down, and is deleted from the fresh store too rather
        than resurrected.
        """
        revived_id = fresh.replica_id
        with self._lock:
            others = [
                r
                for rid, r in self._replicas.items()
                if rid != revived_id and r.alive
            ]
        on_successors: set = set()
        for successor in others:
            inner = getattr(successor.datastore, "_inner", successor.datastore)
            moved: set = set()
            for opcode, payload in wal_lib.export_records(inner):
                study_key = wal_lib.study_key_of(opcode, payload)
                on_successors.add(study_key)
                # Full ranking (liveness-blind): will this study route to
                # the revived replica once it is marked up again?
                if self.router.ranking(study_key)[0] != revived_id:
                    continue
                wal_lib.apply_record(fresh.datastore, opcode, payload)
                moved.add(study_key)
            for study_key in moved:
                try:
                    successor.datastore.delete_study(study_key)
                except Exception:  # already gone / never fully copied
                    pass
        fresh_inner = getattr(fresh.datastore, "_inner", fresh.datastore)
        for opcode, payload in wal_lib.export_records(fresh_inner):
            if opcode != wal_lib.CREATE_STUDY:
                continue
            study_key = wal_lib.study_key_of(opcode, payload)
            if (
                study_key in on_successors
                or self.router.ranking(study_key)[0] != revived_id
            ):
                continue
            try:
                fresh.datastore.delete_study(study_key)
            except Exception:  # pragma: no cover - already gone
                pass

    # -- failure detection -------------------------------------------------

    def _on_endpoint_failure(self, replica_id: str, error: BaseException) -> None:
        """Routed-stub failure hook. Verifies the replica is actually dead
        before failing over: a chaos-injected transport fault on a LIVE
        replica is the retry layer's job, not a topology change."""
        del error
        replica = self.replica(replica_id)
        if replica.alive:
            return
        self.fail_over(replica_id)

    def check_health(self) -> Dict[str, str]:
        """One health sweep: fails over dead replicas, returns the map."""
        with self._lock:
            replicas = list(self._replicas.values())
            failed_over = set(self._failed_over)
        for replica in replicas:
            if not replica.alive and replica.replica_id not in failed_over:
                self.fail_over(replica.replica_id)
        return self.router.snapshot()

    def start_health_loop(self, interval_secs: float = 1.0) -> None:
        """Background health sweeps (idempotent start)."""
        with self._lock:
            if self._health_thread is not None:
                return
            self._health_stop.clear()
            self._health_thread = threading.Thread(
                target=self._health_loop,
                args=(interval_secs,),
                daemon=True,
                name="vizier-replica-health",
            )
            self._health_thread.start()

    def stop_health_loop(self) -> None:
        with self._lock:
            thread = self._health_thread
            self._health_thread = None
        if thread is not None:
            self._health_stop.set()
            thread.join(timeout=5)

    def _health_loop(self, interval_secs: float) -> None:
        while not self._health_stop.wait(interval_secs):
            try:
                self.check_health()
            except Exception as e:  # sweep must never kill the loop
                _logger.warning("Health sweep failed: %s", e)
