"""``ReplicaManager``: an in-process sharded Vizier tier with failover.

N ``VizierServicer`` replicas — each owning its shard of the study
population and (optionally) a per-replica snapshot+WAL directory — behind
one :class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub`. All
replicas feed ONE shared ``PythiaServicer``: the designer cache, request
coalescer, and cross-study batch executor are fleet-wide, so suggestion
compute batches across replicas exactly as it batches across studies on a
single server. The shared Pythia reads trials back through the router too,
so its view follows failover automatically.

Failure model:

- A dead replica (``kill_replica`` in chaos runs, a crashed process in
  real life) surfaces as transport errors on its RPCs. The routed stub
  reports them to :meth:`_on_endpoint_failure`; the manager verifies the
  replica is really dead (a chaos-injected fault on a live replica is NOT
  a failover trigger — the client retry handles it), marks it down, and
  **lifts the dead replica's studies onto their rendezvous successors**.
  With WAL replication armed (``VIZIER_DISTRIBUTED_REPLICATION``, the
  default on a WAL-backed tier) the records come from the successors'
  own **standby logs** (``distributed/replication.py``) — no shared
  filesystem needed; the dead replica's local WAL is consulted only as a
  fallback and wins only when strictly longer (longest-valid-prefix by
  sequence number, per study). Without replication the PR 6 local-disk
  replay runs unchanged. Applying through the successors' datastores
  re-logs (and re-replicates) every record — the handoff itself is
  durable. The failing RPC then re-raises; the caller's reliability
  retries land on the successor.
- **Concurrent multi-replica failure**: one ``fail_over`` call sweeps
  EVERY currently-dead replica — all of them are marked down in the
  router first (so no successor choice can land on another corpse), then
  each is restored in deterministic id order with routing re-resolved
  between steps, all under one topology transition (fresh RPCs park on
  the barrier for the whole sweep).
- ``revive_replica`` rebuilds a replica from its own WAL (restart warm,
  corruption-quarantined); if its studies were failed over meanwhile,
  they are copied back from the successors before the replica is marked
  up. With replication the handback is safe under live traffic: the
  cutover is **epoch-fenced** (every standby store rejects appends from
  the dead generation's streamer before the copy-back starts), fresh
  RPCs drain through the existing failover barrier, and in-flight RPCs
  on the live successors are drained before their state is exported.

Lock order: ``ReplicaManager._lock`` guards the replica/failover tables
only; WAL replay and datastore writes run OUTSIDE it (the failover path
serializes on ``_failover_lock`` instead, which never nests inside
``_lock``). The replication plane's streamer/standby locks are leaves
below both (see ``replication.py``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from vizier_tpu.distributed import config as config_lib
from vizier_tpu.distributed import replication as replication_lib
from vizier_tpu.distributed import router_stub
from vizier_tpu.distributed import routing
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.observability import fleet as fleet_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.service import ram_datastore

_logger = logging.getLogger(__name__)


class ReplicaDownError(ConnectionError):
    """RPC reached a dead replica (transport-shaped, classified transient)."""


class _TransitionGate:
    """The tier's topology-transition latch, shared by the manager (which
    raises/lowers it around failover replay and revive copy-back), the
    routed stub's ``failover_barrier``, and every replica's ``enter()``.

    Admission checks the gate UNDER its condition and registers the RPC
    in-flight before releasing it, so there is no window where a request
    has passed the barrier but is not yet visible to a drain — the race
    that let a write land on a study copy mid-handback.
    """

    def __init__(self):
        self.cond = threading.Condition()
        self.count = 0  # transitions in progress


class _ReplicaEndpoint:
    """The callable surface of one replica; raises when the replica is dead."""

    def __init__(self, replica: "Replica"):
        self._replica = replica

    def __getattr__(self, name: str):
        attr = getattr(self._replica.servicer, name)
        if not callable(attr) or name.startswith("_"):
            return attr

        def call(*args, **kwargs):
            self._replica.enter()
            try:
                return attr(*args, **kwargs)
            finally:
                self._replica.leave()

        return call


class Replica:
    """One shard: servicer + datastore (+ WAL directory when persistent)."""

    def __init__(
        self,
        replica_id: str,
        servicer,
        datastore,
        wal_dir: Optional[str],
        standby: Optional[replication_lib.StandbyStore] = None,
    ):
        self.replica_id = replica_id
        self.servicer = servicer
        self.datastore = datastore
        self.wal_dir = wal_dir
        # Receiver side of WAL replication: the standby logs this replica
        # holds for the origins it is a rendezvous successor of.
        self.standby: Optional[replication_lib.StandbyStore] = standby
        self.alive = True
        self.endpoint = _ReplicaEndpoint(self)
        # Manager-shared per-thread RPC depth (set by the manager): lets
        # the failover barrier exempt threads already inside an endpoint
        # call (their nested routed reads must not wait on a drain that is
        # waiting on them).
        self.thread_depth = threading.local()
        # In-flight RPC accounting, per thread: failover drains these
        # before reading the WAL (a dead replica's in-flight RPCs keep
        # appending until they return — replaying before they finish
        # silently drops writes the client already observed).
        self._inflight_cond = threading.Condition()
        self._inflight: Dict[int, int] = {}
        # The tier's transition gate (set by the manager): admission
        # parks while a failover replay / revive copy-back is mid-flight
        # and registers in-flight atomically with the gate check.
        self.gate: Optional[_TransitionGate] = None
        # Set by fail_over: called (outside the condition) whenever an
        # in-flight RPC leaves a dead replica, so writes it appended after
        # the failover replay (it was admitted alive and kept executing —
        # including the self-triggered-failover edge where a dispatch
        # inside the RPC tripped the failover itself) are caught up onto
        # the successors before the RPC's response reaches the client.
        self.on_drained = None

    def enter(self, timeout_secs: float = 30.0) -> None:
        """Admits one RPC (liveness check + in-flight count, atomically).

        Fresh RPCs (thread depth 0) first wait out any topology
        transition UNDER the gate's condition and register in-flight
        before releasing it — a request can never slip between "passed
        the barrier" and "visible to a drain". Threads already inside an
        endpoint call pass straight through (the drain is waiting on
        exactly those threads; parking their nested reads would deadlock
        it). Bounded: after ``timeout_secs`` the request proceeds and at
        worst degrades through the reliability layer.
        """
        tid = threading.get_ident()
        gate = self.gate
        if gate is not None and getattr(self.thread_depth, "n", 0) == 0:
            deadline = time.monotonic() + timeout_secs
            with gate.cond:
                while gate.count > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    gate.cond.wait(remaining)
                self._admit(tid)
        else:
            self._admit(tid)
        self.thread_depth.n = getattr(self.thread_depth, "n", 0) + 1

    def _admit(self, tid: int) -> None:
        with self._inflight_cond:
            if not self.alive:
                raise ReplicaDownError(f"replica {self.replica_id} is down")
            self._inflight[tid] = self._inflight.get(tid, 0) + 1

    def leave(self) -> None:
        tid = threading.get_ident()
        self.thread_depth.n = getattr(self.thread_depth, "n", 1) - 1
        with self._inflight_cond:
            count = self._inflight.get(tid, 0) - 1
            if count <= 0:
                self._inflight.pop(tid, None)
            else:
                self._inflight[tid] = count
            self._inflight_cond.notify_all()
            callback = self.on_drained if not self.alive else None
        if callback is not None:
            callback()

    def wait_quiesced(self, timeout_secs: float) -> bool:
        """Blocks until no OTHER thread has an RPC in flight (the calling
        thread's own nested RPC must not deadlock its own failover — a
        self-triggered failover from inside a dispatch is the rare edge
        the timeout also backstops). Returns False on timeout."""
        deadline = time.monotonic() + timeout_secs
        me = threading.get_ident()
        with self._inflight_cond:
            while any(tid != me for tid in self._inflight):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True


class ReplicaManager:
    """Builds, health-checks, and fails over an in-process replica fleet."""

    def __init__(
        self,
        num_replicas: Optional[int] = None,
        *,
        config: Optional[config_lib.DistributedConfig] = None,
        wal_root: Optional[str] = None,
        policy_factory=None,
        serving_config=None,
        reliability_config=None,
    ):
        import dataclasses

        from vizier_tpu.reliability import config as reliability_config_lib
        from vizier_tpu.service import pythia_service, vizier_service

        self.config = config or config_lib.DistributedConfig.from_env()
        self._num_replicas = max(1, num_replicas or self.config.num_replicas)
        self._wal_root = wal_root if wal_root is not None else self.config.wal_root
        replica_ids = [f"replica-{i}" for i in range(self._num_replicas)]
        self.router = routing.StudyRouter(
            replica_ids, routing=self.config.routing
        )

        # In-process replicas run the synchronous Pythia dispatch: the
        # manager (not a per-request watchdog thread) owns wedged-replica
        # semantics here, and the thread-per-suggest the deadline path
        # spawns is measurable overhead at tier throughput. Everything
        # else (retries, breaker, fallback) keeps its configured state.
        base_reliability = (
            reliability_config or reliability_config_lib.ReliabilityConfig.from_env()
        )
        replica_reliability = dataclasses.replace(
            base_reliability, deadlines=self.config.replica_deadlines
        )

        # One Pythia for the whole fleet; its trial reads route like any
        # other client so failover moves its view too. Constructed first
        # (the replicas need it), connected to the router stub below.
        self._pythia = pythia_service.PythiaServicer(
            None,
            policy_factory,
            serving_config=serving_config,
            reliability_config=base_reliability,
        )
        registry = self._pythia.serving_runtime.stats.registry
        self._failovers = registry.counter(
            "vizier_replica_failovers", help="Replica failovers performed."
        )
        self._restored = registry.counter(
            "vizier_replica_restored_studies",
            help="Studies lifted onto successors during failover.",
        )
        self._recovery_source = registry.counter(
            "vizier_replica_recovery_source",
            help="Failover recovery sources chosen, per study "
            "(standby log vs local WAL).",
        )

        self._lock = threading.Lock()  # replica + failover bookkeeping only
        # One per-thread RPC-depth record shared by every replica: the
        # failover barrier exempts threads already inside an endpoint call.
        self._thread_depth = threading.local()
        # Topology transitions in progress (failover replay / revive
        # copy-back): fresh RPCs park on the gate until zero — checked
        # both at the routed stub (failover_barrier) and atomically at
        # replica admission (Replica.enter).
        self._gate = _TransitionGate()
        # Shared-nothing WAL replication: active on multi-replica
        # WAL-backed tiers unless switched off. The plane owns the
        # per-origin streamers; standby stores hang off each Replica.
        self._replication: Optional[replication_lib.ReplicationPlane] = None
        if (
            self._wal_root
            and self.config.replication
            and self._num_replicas > 1
        ):
            self._replication = replication_lib.ReplicationPlane(
                factor=self.config.replication_factor,
                queue_size=self.config.replication_queue,
                batch_max=self.config.replication_batch,
                router=self.router,
                get_replica=self._replica_or_none,
                registry=registry,
            )

        self._replicas: Dict[str, Replica] = {}
        for rid in replica_ids:
            self._replicas[rid] = self._build_replica(
                rid, vizier_service, replica_reliability
            )
        if self._replication is not None:
            # Streamers start AFTER every replica exists: their initial
            # baseline sync reads peers through self._replicas.
            for rid in replica_ids:
                self._replication.start_streamer(rid)

        self._stub = router_stub.RoutedVizierStub(
            {rid: r.endpoint for rid, r in self._replicas.items()},
            router=self.router,
            on_failure=self._on_endpoint_failure,
            registry=registry,
            retry_sink=self._record_retries,
            barrier=self.failover_barrier,
        )
        self._pythia.connect_to_vizier(self._stub)

        # Failover serialization (never nests inside self._lock).
        self._failover_lock = threading.Lock()
        self._failed_over: set = set()
        # replica_id -> WAL records already replayed onto successors
        # (late-write catch-up baseline; see _catch_up_late_writes).
        self._replayed_records: Dict[str, int] = {}
        # replica_id -> highest mutation seq replayed onto successors
        # (the replication path's catch-up watermark).
        self._replayed_seq: Dict[str, int] = {}
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    # -- construction helpers ---------------------------------------------

    def _build_replica(self, replica_id, vizier_service_mod, reliability):
        wal_dir = None
        standby = None
        if self._wal_root:
            wal_dir = os.path.join(self._wal_root, replica_id)
            on_append = None
            if self._replication is not None:
                # The typed sink resolves the origin's CURRENT streamer
                # per call, so revives swap streamers without rebuilding
                # the datastore hook.
                on_append = replication_lib.AppendSink(
                    replica_id, self._replication
                )
                # Receiver side: reload whatever standby logs this
                # replica already holds for its peers (restart warm).
                standby = replication_lib.StandbyStore(wal_dir)
            datastore = wal_lib.PersistentDataStore(
                wal_dir,
                snapshot_interval=self.config.snapshot_interval,
                fsync=self.config.wal_fsync,
                on_append=on_append,
            )
        else:
            datastore = ram_datastore.NestedDictRAMDataStore()
        servicer = vizier_service_mod.VizierServicer(
            datastore=datastore, reliability_config=reliability
        )
        # Tag the replica's request spans so a fleet dump can split one
        # process's span ring back into per-replica files.
        servicer.replica_id = replica_id
        servicer.set_pythia(self._pythia)
        replica = Replica(
            replica_id, servicer, datastore, wal_dir, standby=standby
        )
        replica.thread_depth = self._thread_depth
        replica.gate = self._gate
        return replica

    def _replica_or_none(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(replica_id)

    def _record_retries(self, amount: int) -> None:
        self._pythia.serving_runtime.stats.increment("retries", amount)

    # -- public surface ----------------------------------------------------

    @property
    def stub(self) -> router_stub.RoutedVizierStub:
        """The drop-in service stub clients (and the shared Pythia) use."""
        return self._stub

    @property
    def pythia(self):
        return self._pythia

    def replica(self, replica_id: str) -> Replica:
        with self._lock:
            return self._replicas[replica_id]

    def replica_ids(self) -> List[str]:
        return list(self.router.replica_ids)

    def serving_stats(self) -> dict:
        """Fleet stats: shared-Pythia counters + router + per-replica."""
        stats = dict(self._pythia.serving_stats())
        stats["router"] = self.router.snapshot()
        stats["replicas"] = self._stub.stats()["replicas"]
        stats["failovers"] = int(
            sum(
                self._failovers.value(**dict(key))
                for key in self._failovers.label_keys()
            )
        )
        stats["restored_studies"] = int(self._restored.value())
        stats["recovery_sources"] = {
            dict(key).get("source", ""): int(
                self._recovery_source.value(**dict(key))
            )
            for key in self._recovery_source.label_keys()
        }
        if self._replication is not None:
            stats["replication"] = self.replication_stats()
        return stats

    @property
    def replication_active(self) -> bool:
        """True when WAL appends stream to standby logs (shared-nothing
        failover + epoch-fenced revive are in force)."""
        return self._replication is not None

    def flush_replication(self, replica_id: str, timeout_secs: float = 10.0) -> bool:
        """Drains a replica's replication streamer (chaos harnesses call
        this before destroying its disk, modelling the acked-replication
        durability point). No-op without replication."""
        if self._replication is None:
            return True
        return self._replication.flush_origin(replica_id, timeout_secs)

    def _standby_views_for(
        self, origin: str
    ) -> Tuple[List[str], List[replication_lib.StandbyView]]:
        """Every LIVE replica's standby view for ``origin`` (+ holders)."""
        holders: List[str] = []
        views: List[replication_lib.StandbyView] = []
        for rid in self.router.replica_ids:
            if rid == origin:
                continue
            replica = self._replica_or_none(rid)
            if replica is None or replica.standby is None or not replica.alive:
                continue
            view = replica.standby.view_for(origin)
            if view is not None:
                holders.append(rid)
                views.append(view)
        return holders, views

    def recovery_plan(
        self, origin: str, wal_dir: Optional[str], *, min_seq: int = 0
    ) -> replication_lib.RecoveryPlan:
        """The per-study recovery-source selection for a dead origin:
        live standby logs vs its local WAL, longest-valid-prefix by
        sequence number (``replication.plan_recovery``)."""
        local_records: List[Tuple[int, int, bytes]] = []
        local_torn = False
        if wal_dir:
            local_records, local_torn = wal_lib.read_directory_with_seqs(
                wal_dir
            )
        holders, views = self._standby_views_for(origin)
        plane = self._replication
        return replication_lib.plan_recovery(
            origin,
            local_records,
            local_torn,
            views,
            min_seq=min_seq,
            successors_fn=lambda study: plane.successors_for(study, origin),
            holders=holders,
        )

    def _fence_standby(self, origin: str, epoch: int) -> None:
        """Revive cutover: every live replica's standby store rejects
        deliveries from streamer epochs below ``epoch`` from now on."""
        for rid in self.router.replica_ids:
            replica = self._replica_or_none(rid)
            if replica is not None and replica.standby is not None and replica.alive:
                replica.standby.fence(origin, epoch)

    def replication_stats(self) -> dict:
        """Replication-plane observability: factor, per-holder standby
        depths, per-origin streamer lag/resync/drop counters."""
        plane = self._replication
        if plane is None:
            return {}
        return {
            "factor": plane.factor,
            "standby_depths": plane.record_depths(),
            "origins": plane.streamer_stats(),
        }

    def prometheus_text(self) -> str:
        return self._pythia.prometheus_text()

    def dump_observability(self, out_dir: str) -> Dict[str, List[str]]:
        """Writes the fleet's observability dumps into ``out_dir``.

        The in-process tier shares one span ring; this splits it back into
        per-replica ``<replica>-spans.jsonl`` files (request spans carry a
        ``replica`` attribute) plus a ``client-spans.jsonl`` for
        unattributed spans, and writes the shared registry snapshot and
        the flight-recorder event list — the exact file layout subprocess
        replicas produce via ``replica_main --obs-dump-dir``, so
        ``observability.fleet`` (and ``tools/obs_report.py --fleet``)
        merges either deployment the same way.
        """
        tracer = tracing_lib.get_tracer()
        by_source: Dict[str, List[dict]] = {}
        for span in tracer.finished_spans():
            data = span.to_dict()
            source = (data.get("attributes") or {}).get("replica") or "client"
            by_source.setdefault(source, []).append(data)
        written: Dict[str, List[str]] = {"spans": [], "other": []}
        for source, spans in sorted(by_source.items()):
            written["spans"].append(
                fleet_lib.write_spans(out_dir, source, spans)
            )
        paths = fleet_lib.dump_process(
            out_dir,
            "fleet",
            registry=self._pythia.serving_runtime.metrics,
            recorder=recorder_lib.get_recorder(),
        )
        written["other"] = sorted(paths.values())
        return written

    def shutdown(self) -> None:
        self.stop_health_loop()
        if self._replication is not None:
            self._replication.close()
        self._pythia.shutdown()
        with self._lock:
            replicas = list(self._replicas.values())
        for replica in replicas:
            close = getattr(replica.datastore, "close", None)
            if close is not None:
                close()
            if replica.standby is not None:
                replica.standby.close()

    # -- topology-transition barrier ---------------------------------------

    def failover_barrier(self, timeout_secs: float = 30.0) -> None:
        """Routed-stub hook: parks fresh RPCs while a failover replay or
        revive copy-back is mid-flight, so no request can land on a
        successor the replay has not populated yet (NotFound there reads
        as "study deleted" — no retry fixes it). Threads already inside an
        endpoint call pass straight through: the failover drain is waiting
        on exactly those threads, and parking their nested reads would
        deadlock the drain. Bounded: after ``timeout_secs`` the request
        proceeds and at worst degrades through the reliability layer."""
        if getattr(self._thread_depth, "n", 0) > 0:
            return
        deadline = time.monotonic() + timeout_secs
        with self._gate.cond:
            while self._gate.count > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._gate.cond.wait(remaining)

    def _begin_transition(self) -> None:
        with self._gate.cond:
            self._gate.count += 1

    def _end_transition(self) -> None:
        with self._gate.cond:
            self._gate.count -= 1
            self._gate.cond.notify_all()

    # -- chaos / lifecycle -------------------------------------------------

    def kill_replica(self, replica_id: str) -> None:
        """Simulates a replica crash: every subsequent RPC to it fails.

        Detection and failover happen through the normal channels (a
        failed RPC's failure hook, or the health loop) — exactly as they
        would for a crashed process.
        """
        self.replica(replica_id).alive = False
        recorder_lib.get_recorder().record(
            None, "replica_killed", replica=replica_id
        )

    def fail_over(self, replica_id: str) -> int:
        """Marks dead replicas down and lifts their studies onto successors.

        One call sweeps EVERY currently-dead, not-yet-failed-over replica
        (concurrent multi-replica failure): all corpses are marked down in
        the router FIRST — a successor choice must never land on another
        dead replica — then each is restored in deterministic id order,
        with routing re-resolved between steps, under one topology
        transition. Returns the number of studies restored across the
        sweep. Idempotent; a no-op for replicas that already failed over.
        """
        # Fast path WITHOUT the failover lock: an RPC thread whose nested
        # router read trips over the dead replica mid-failover must return
        # immediately, not queue behind the in-progress failover that is
        # draining it (the drain below waits for exactly such threads).
        with self._lock:
            if replica_id in self._failed_over:
                return 0
        completed: List[dict] = []
        total_restored = 0
        with self._failover_lock:
            with self._lock:
                if replica_id in self._failed_over:
                    return 0
                if self._replicas[replica_id].alive:
                    # Either caller misuse (no kill first) or, under load,
                    # a concurrent revive won the failover lock between
                    # this caller observing the replica dead and getting
                    # here — the replica is serving again, nothing to do.
                    return 0
                dead = sorted(
                    rid
                    for rid, r in self._replicas.items()
                    if not r.alive and rid not in self._failed_over
                )
                self._failed_over.update(dead)
            for rid in dead:
                self.router.mark_down(rid)
            self._begin_transition()  # fresh RPCs park until replay lands
            try:
                for rid in dead:
                    with self._lock:
                        replica = self._replicas[rid]
                    # Late-write catch-up hook first (any leave() from
                    # here on serializes behind this failover via
                    # _failover_lock), then drain in-flight RPCs before
                    # reading the logs: an RPC admitted while the replica
                    # was alive may still be appending; replaying a
                    # prefix would hand successors a store missing writes
                    # the client already saw (NotFound on the very next
                    # CompleteTrial).
                    replica.on_drained = (
                        lambda r=replica: self._catch_up_late_writes(r)
                    )
                    if not replica.wait_quiesced(30.0):
                        _logger.warning(
                            "Failing over %s with RPCs still in flight "
                            "after 30s; their writes catch up when they "
                            "drain.",
                            replica.replica_id,
                        )
                    restored, successors, sources, rearm = (
                        self._restore_replica(replica)
                    )
                    if replica.wal_dir:
                        # Its studies now live on successors: a
                        # live-replica ListStudies fan-out is complete
                        # again. RAM-only replicas stay unaccounted —
                        # their studies are gone, and listings keep
                        # failing loudly rather than silently shrinking.
                        self._stub.note_failed_over(rid)
                    total_restored += restored
                    completed.append(
                        {
                            "replica": rid,
                            "restored": restored,
                            "successors": sorted(successors),
                            "sources": sources,
                            "rearm": rearm,
                        }
                    )
            finally:
                self._end_transition()
        # Counter updates (and the recorder append) outside the failover
        # lock: metric locks must not nest under tier mutexes
        # (serving-stack convention, enforced by the chaos soak's runtime
        # lock-order cross-check).
        for entry in completed:
            self._failovers.inc(replica=entry["replica"])
            self._restored.inc(entry["restored"])
            for source, count in entry["sources"].items():
                self._recovery_source.inc(count, source=source)
            # Structured failover event: with just the vizier_replica_*
            # counters, the fleet's topology history was gone the moment
            # the numbers were read — the recorder keeps who died, when,
            # which successors took its studies, how many moved, and
            # which recovery source (standby log vs local WAL) won.
            recorder_lib.get_recorder().record(
                None,
                "replica_failover",
                replica=entry["replica"],
                successors=entry["successors"],
                restored_studies=entry["restored"],
                recovery_sources=entry["sources"],
            )
        self._rearm_speculation(
            [study for entry in completed for study in entry["rearm"]]
        )
        return total_restored

    def _restore_replica(self, replica: Replica):
        """Dispatches to the standby-log or local-WAL restore path.

        Returns ``(restored, successor_ids, source_counts,
        rearm_studies)`` where ``rearm_studies`` are restored studies
        with >= 1 completed trial (speculative re-arm candidates).
        """
        if self._replication is not None:
            return self._restore_from_standby(replica)
        studies, successors, replayed = self._restore_from_wal(replica)
        with self._lock:
            self._replayed_records[replica.replica_id] = replayed
        sources = {"local": len(studies)} if studies else {}
        rearm = [
            study
            for study in sorted(studies)
            if self._has_completed_trials(
                self.replica(self.router.replica_for(study)), study
            )
        ]
        return len(studies), successors, sources, rearm

    def _restore_from_standby(self, replica: Replica):
        """Replays a dead replica's studies from the best available
        source per study: its successors' standby logs, or its local WAL
        when that is present and strictly longer (shared-nothing
        failover — the local disk is an optimization, not a dependency).
        """
        plane = self._replication
        # Drain the origin's streamer first: in-process, everything its
        # in-flight RPCs appended before the quiesce is still in the
        # bounded queue — what a real fleet would have acked already.
        plane.flush_origin(replica.replica_id)
        plan = self.recovery_plan(replica.replica_id, replica.wal_dir)
        if plan.local_torn:
            _logger.warning(
                "Local WAL of %s carried a torn/corrupt suffix; recovery "
                "compares its valid prefix against the standby logs.",
                replica.replica_id,
            )
        successors: set = set()
        rearm: List[str] = []
        for item in plan.studies:
            successor = self.replica(self.router.replica_for(item.study))
            # Applying through the successor's datastore re-logs (and
            # re-replicates) each record: the handoff is durable and the
            # standby copies follow the new owner.
            for opcode, payload in item.records:
                wal_lib.apply_record(successor.datastore, opcode, payload)
            successors.add(successor.replica_id)
            if self._has_completed_trials(successor, item.study):
                rearm.append(item.study)
        with self._lock:
            self._replayed_seq[replica.replica_id] = plan.max_seq
        return len(plan.studies), successors, plan.source_counts(), rearm

    def _restore_from_wal(self, replica: Replica) -> Tuple[set, set, int]:
        """Replays a dead replica's WAL into its successors' datastores
        (the pre-replication shared-filesystem path).

        Returns ``(studies, successor_ids, records_replayed)``.
        """
        if not replica.wal_dir:
            # RAM-only replica: its studies are lost until recreated.
            return set(), set(), 0
        records, torn = wal_lib.read_directory(replica.wal_dir)
        if torn:
            _logger.warning(
                "Dropped a torn WAL tail while failing over %s.",
                replica.replica_id,
            )
        # Studies whose history net-resolves to deletion contribute
        # nothing: replaying a revive-handback tombstone onto the study's
        # live copy elsewhere would destroy it (see plan_recovery).
        final_delete: Dict[str, bool] = {}
        for opcode, payload in records:
            study_key = wal_lib.study_key_of(opcode, payload)
            final_delete[study_key] = opcode == wal_lib.DELETE_STUDY
        studies: set = set()
        successors: set = set()
        for opcode, payload in records:
            study_key = wal_lib.study_key_of(opcode, payload)
            if final_delete[study_key]:
                continue
            successor_id = self.router.replica_for(study_key)
            successor = self.replica(successor_id)
            # Applying through the successor's datastore re-logs each
            # record into the successor's own WAL: the handoff is durable.
            wal_lib.apply_record(successor.datastore, opcode, payload)
            studies.add(study_key)
            successors.add(successor_id)
        return studies, successors, len(records)

    @staticmethod
    def _has_completed_trials(successor: Replica, study: str) -> bool:
        """True when the restored study has >= 1 completed trial on its
        new owner (it exists and is worth a speculative pre-compute)."""
        from vizier_tpu.service.protos import study_pb2

        try:
            states = successor.datastore.trial_states(study)
        except Exception:
            return False  # deleted study (tombstone replayed) or racing
        return any(
            state == study_pb2.Trial.SUCCEEDED for _tid, state in states
        )

    def _rearm_speculation(self, studies: List[str]) -> None:
        """Re-arms the speculative trigger on the successors: one
        pre-compute per restored study with completed trials, so a
        replica loss does not zero the PR 8 hit rate until organic
        completions rebuild it. Runs OUTSIDE the failover lock (the
        engine enqueue takes serving-side locks)."""
        engine = getattr(
            self._pythia.serving_runtime, "speculative_engine", None
        )
        if engine is None or not engine.bound or not studies:
            return
        stats = self._pythia.serving_runtime.stats
        for study in studies:
            try:
                self._pythia.notify_trial_event(study)
                stats.increment("speculative_rearms")
            except Exception as e:  # re-arm is best-effort
                _logger.debug("Speculative re-arm of %s failed: %s", study, e)

    def _catch_up_late_writes(self, replica: Replica) -> None:
        """Replays WAL records a dead replica appended AFTER its failover.

        The self-triggered-failover edge: an RPC in flight on the dying
        replica can itself trip the failover (a nested routed read hits
        the corpse) and then keep executing — its writes land in the dead
        WAL after the replay read. ``Replica.leave`` calls this when the
        last such RPC drains, so the tail reaches the successors before
        the RPC's response reaches the client. Idempotent and serialized
        with failover/revive via ``_failover_lock``.
        """
        with self._failover_lock:
            if self._replication is not None:
                caught_up = self._catch_up_from_standby(replica)
            else:
                caught_up = self._catch_up_from_wal(replica)
        if caught_up:
            recorder_lib.get_recorder().record(
                None,
                "replica_failover_catchup",
                replica=replica.replica_id,
                records=caught_up,
            )

    def _catch_up_from_wal(self, replica: Replica) -> int:
        """Local-WAL late-write tail (record-count watermark)."""
        with self._lock:
            start = self._replayed_records.get(replica.replica_id)
        if start is None or not replica.wal_dir:
            return 0  # failover incomplete or RAM-only: nothing to do
        records, _torn = wal_lib.read_directory(replica.wal_dir)
        tail = records[start:]
        if not tail:
            return 0
        for opcode, payload in tail:
            study_key = wal_lib.study_key_of(opcode, payload)
            successor = self.replica(self.router.replica_for(study_key))
            wal_lib.apply_record(successor.datastore, opcode, payload)
        with self._lock:
            self._replayed_records[replica.replica_id] = len(records)
        return len(tail)

    def _catch_up_from_standby(self, replica: Replica) -> int:
        """Standby-log late-write tail (sequence-number watermark): a
        late write streamed through the dead replica's still-current
        streamer epoch, so the standby logs already hold it — replay
        just the records past the failover's watermark onto the current
        owners."""
        with self._lock:
            watermark = self._replayed_seq.get(replica.replica_id)
        if watermark is None:
            return 0  # failover incomplete: the replay will include it
        plane = self._replication
        plane.flush_origin(replica.replica_id)
        plan = self.recovery_plan(
            replica.replica_id, replica.wal_dir, min_seq=watermark
        )
        caught_up = 0
        for item in plan.studies:
            successor = self.replica(self.router.replica_for(item.study))
            for opcode, payload in item.records:
                wal_lib.apply_record(successor.datastore, opcode, payload)
            caught_up += len(item.records)
        if caught_up:
            with self._lock:
                self._replayed_seq[replica.replica_id] = max(
                    watermark, plan.max_seq
                )
        return caught_up

    def revive_replica(self, replica_id: str) -> None:
        """Restarts a replica warm from its WAL and routes its studies back.

        Studies that failed over while it was down are copied back from
        their interim successors (and deleted there so the owner is unique
        again); studies DELETED while it was down exist on no successor
        and are deleted from the rebuilt store too, not resurrected from
        its stale WAL.

        With replication armed the handback is an **epoch-fenced cutover**
        that is safe under live traffic: (1) every live standby store is
        fenced to the new origin epoch, so a stale streamer — an RPC that
        outlived the dead generation — can no longer scribble over the
        handed-back state; (2) fresh RPCs drain through the existing
        failover barrier for the duration; (3) in-flight RPCs on the live
        successors are drained before their state is exported, so the
        copy-back sees a quiescent snapshot. Without replication the
        pre-existing contract stands: the caller quiesces traffic for the
        handback window.
        """
        from vizier_tpu.reliability import config as reliability_config_lib
        from vizier_tpu.service import vizier_service
        import dataclasses

        # Serialize with fail_over (and the late-write catch-up): a revive
        # racing an in-flight failover would copy back from successors the
        # WAL replay is still populating — partial state marked up, the
        # rest of the replay stranded on the successors.
        with self._failover_lock:
            with self._lock:
                old = self._replicas[replica_id]
                was_failed_over = replica_id in self._failed_over
            if old.alive:
                return
            self._begin_transition()  # fresh RPCs park during copy-back
            try:
                if self._replication is not None:
                    # Fence first: from here on, deliveries from the dead
                    # generation's streamer are rejected everywhere, even
                    # before the fresh streamer announces the new epoch.
                    new_epoch = self._replication.epoch_of(replica_id) + 1
                    self._fence_standby(replica_id, new_epoch)
                    self._replication.close_origin(replica_id)
                    # Live-traffic drain: fresh RPCs are parked on the
                    # barrier; wait out the in-flight ones on the live
                    # successors so the copy-back exports quiescent state.
                    with self._lock:
                        live = [
                            r
                            for rid, r in self._replicas.items()
                            if rid != replica_id and r.alive
                        ]
                    for other in live:
                        if not other.wait_quiesced(10.0):
                            _logger.warning(
                                "Reviving %s with RPCs still in flight on "
                                "%s after 10s.",
                                replica_id,
                                other.replica_id,
                            )
                close = getattr(old.datastore, "close", None)
                if close is not None:
                    close()
                standby_close = getattr(old.standby, "close", None)
                if standby_close is not None:
                    standby_close()
                reliability = dataclasses.replace(
                    reliability_config_lib.ReliabilityConfig.from_env(),
                    deadlines=self.config.replica_deadlines,
                )
                fresh = self._build_replica(
                    replica_id, vizier_service, reliability
                )
                if was_failed_over:
                    self._copy_back_from_successors(fresh)
                with self._lock:
                    self._replicas[replica_id] = fresh
                    self._failed_over.discard(replica_id)
                    self._replayed_records.pop(replica_id, None)
                    self._replayed_seq.pop(replica_id, None)
                # _ReplicaEndpoint objects are bound per Replica; repoint
                # the stub.
                self._stub.set_endpoint(replica_id, fresh.endpoint)
                self.router.mark_up(replica_id)
                if self._replication is not None:
                    # The fresh streamer (epoch == the fence) baselines
                    # its successors from the handed-back state — and the
                    # other origins proactively re-baseline the revived
                    # replica's standby logs, which went stale (or were
                    # lost with its disk) while it was down.
                    self._replication.start_streamer(replica_id)
                    self._replication.resync_into(replica_id)
            finally:
                self._end_transition()
        recorder_lib.get_recorder().record(
            None,
            "replica_revive",
            replica=replica_id,
            was_failed_over=was_failed_over,
            epoch_fenced=self._replication is not None,
        )

    def _copy_back_from_successors(self, fresh: Replica) -> None:
        """Moves studies the revived replica will own back from successors.

        Successor CURRENT state, not WAL history, is what comes back — so
        after the copy, any study the revived replica rebuilt from its own
        (stale) WAL that exists on NO live successor was deleted while the
        replica was down, and is deleted from the fresh store too rather
        than resurrected.

        Routing is LIVENESS-AWARE as of the post-revive world (live
        replicas plus the one coming up): with several replicas down at
        once, a study whose liveness-blind first choice is still dead
        must come back to the revived replica when that is where live
        traffic will route it — leaving it on the interim successor would
        strand it unreachable until the true owner returns.
        """
        revived_id = fresh.replica_id
        with self._lock:
            others = [
                r
                for rid, r in self._replicas.items()
                if rid != revived_id and r.alive
            ]
        reachable = {revived_id} | {r.replica_id for r in others}

        def routes_to_revived(study_key: str) -> bool:
            for rid in self.router.ranking(study_key):
                if rid in reachable:
                    return rid == revived_id
            return False

        on_successors: set = set()
        for successor in others:
            inner = getattr(successor.datastore, "_inner", successor.datastore)
            moved: set = set()
            for opcode, payload in wal_lib.export_records(inner):
                study_key = wal_lib.study_key_of(opcode, payload)
                on_successors.add(study_key)
                if not routes_to_revived(study_key):
                    continue
                wal_lib.apply_record(fresh.datastore, opcode, payload)
                moved.add(study_key)
            for study_key in moved:
                try:
                    successor.datastore.delete_study(study_key)
                except Exception:  # already gone / never fully copied
                    pass
        fresh_inner = getattr(fresh.datastore, "_inner", fresh.datastore)
        for opcode, payload in wal_lib.export_records(fresh_inner):
            if opcode != wal_lib.CREATE_STUDY:
                continue
            study_key = wal_lib.study_key_of(opcode, payload)
            if study_key in on_successors or not routes_to_revived(
                study_key
            ):
                continue
            try:
                fresh.datastore.delete_study(study_key)
            except Exception:  # pragma: no cover - already gone
                pass

    # -- failure detection -------------------------------------------------

    def _on_endpoint_failure(self, replica_id: str, error: BaseException) -> None:
        """Routed-stub failure hook. Verifies the replica is actually dead
        before failing over: a chaos-injected transport fault on a LIVE
        replica is the retry layer's job, not a topology change."""
        del error
        replica = self.replica(replica_id)
        if replica.alive:
            return
        self.fail_over(replica_id)

    def check_health(self) -> Dict[str, str]:
        """One health sweep: fails over dead replicas, returns the map."""
        with self._lock:
            replicas = list(self._replicas.values())
            failed_over = set(self._failed_over)
        for replica in replicas:
            if not replica.alive and replica.replica_id not in failed_over:
                self.fail_over(replica.replica_id)
        return self.router.snapshot()

    def start_health_loop(self, interval_secs: float = 1.0) -> None:
        """Background health sweeps (idempotent start)."""
        with self._lock:
            if self._health_thread is not None:
                return
            self._health_stop.clear()
            self._health_thread = threading.Thread(
                target=self._health_loop,
                args=(interval_secs,),
                daemon=True,
                name="vizier-replica-health",
            )
            self._health_thread.start()

    def stop_health_loop(self) -> None:
        with self._lock:
            thread = self._health_thread
            self._health_thread = None
        if thread is not None:
            self._health_stop.set()
            thread.join(timeout=5)

    def _health_loop(self, interval_secs: float) -> None:
        while not self._health_stop.wait(interval_secs):
            try:
                self.check_health()
            except Exception as e:  # sweep must never kill the loop
                _logger.warning("Health sweep failed: %s", e)
