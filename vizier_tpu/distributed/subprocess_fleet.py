"""Lease-based management of a SUBPROCESS replica fleet.

``ReplicaManager`` (replica_manager.py) health-checks replicas by poking
objects in its own address space — which proves nothing about the failure
modes a real fleet has: crashed processes, partitions, slow links. This
module manages replicas that are real OS processes (``replica_main``)
reached only over gRPC, with the failure-detection and recovery planes
crossing the process/network boundary:

- **Lease-based failure detection.** The manager polls each replica's
  ``Heartbeat`` RPC (the ``ReplicationService`` surface) every
  ``heartbeat_interval_s``; a success renews that replica's lease. A
  replica whose lease runs out — crashed, wedged, or partitioned away —
  is declared dead and failed over. A *slow* replica keeps renewing:
  delays shorter than ``lease_timeout_s`` never trigger failover. A
  replica whose PROCESS is observed dead (a transport failure plus a
  reaped pid) is declared immediately, matching the in-process manager's
  verify-then-failover contract.
- **Fence-first failover over the wire.** Failover bumps the dead
  origin's epoch and ``Fence``\\ s every reachable replica BEFORE reading
  any standby log, so a partitioned-but-alive origin (a "zombie") whose
  in-flight appends arrive after the cutover is rejected by the fenced
  standby stores — no split-brain write wins, and the rejections are
  observable (``HeartbeatResponse.fenced_rejections``). Recovery then
  reuses the PR 13 planner verbatim: ``ExportStandby`` collects every
  live holder's view, :func:`replication.plan_recovery` picks the
  longest-valid-prefix source per study (the corpse's local WAL is
  consulted only when its process is dead and its directory readable),
  and ``ApplyRecords`` applies each study's records through the new
  owner's datastore — re-logged and re-replicated, so the handoff is
  durable the moment the RPC returns.
- **Revive = fenced process restart + copy-back.** The old generation is
  fenced out everywhere, the process restarts warm over its own WAL
  directory ON ITS OLD PORT (peer endpoint strings stay valid; gRPC
  channels reconnect) with ``--replication-epoch`` = the fence, studies
  that failed over meanwhile are copied back through
  ``ExportState``/``ApplyRecords`` and deleted from their interim
  owners, studies deleted while it was down are not resurrected from its
  stale WAL, and every other origin's streamer re-baselines the revived
  replica's standby logs (``Resync``).
- **Network fault injection.** An optional ``testing.netchaos.NetChaos``
  schedule wraps the manager's control links and the routed client
  links, so partitions/drops/delays between driver and fleet travel the
  exact production failure path (``ConnectionError``-shaped → reliability
  retries → routed-stub failure hook). Inter-replica links can be fault-
  injected inside each replica via ``VIZIER_NETCHAOS``.

Lock order: ``_lock`` guards the replica/lease/failover tables only;
all RPCs and WAL reads run outside it (failover serializes on
``_failover_lock``, which never nests inside ``_lock``). The lease
table's lock is a leaf.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from vizier_tpu.distributed import config as config_lib
from vizier_tpu.distributed import replication as replication_lib
from vizier_tpu.distributed import replication_service as repl_service
from vizier_tpu.distributed import router_stub
from vizier_tpu.distributed import routing
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.service.protos import replication_service_pb2 as _pb

_logger = logging.getLogger(__name__)

# Fleet-member id of the shared compute server (disaggregated compute
# tier). One per fleet: the whole point is fleet-wide batch fusion.
COMPUTE_ID = "compute-0"


def _pick_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LeaseTable:
    """Per-replica heartbeat leases (leaf lock: dict bookkeeping only).

    A lease is granted/renewed with the wall-free monotonic clock and
    expires ``timeout_s`` later. Expiry is a *statement about silence*,
    not about the process: a partitioned-but-alive replica expires too —
    which is exactly when fencing must keep its late writes out.
    """

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._expiry: Dict[str, float] = {}

    def renew(self, replica_id: str, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._expiry[replica_id] = now + self.timeout_s

    def drop(self, replica_id: str) -> None:
        with self._lock:
            self._expiry.pop(replica_id, None)

    def remaining(self, replica_id: str) -> float:
        with self._lock:
            expiry = self._expiry.get(replica_id)
        if expiry is None:
            return 0.0
        return max(0.0, expiry - time.monotonic())

    def expired(self, replica_id: str, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            expiry = self._expiry.get(replica_id)
        return expiry is not None and now >= expiry

    def snapshot(self) -> Dict[str, float]:
        """replica -> seconds of lease remaining (observability)."""
        now = time.monotonic()
        with self._lock:
            return {
                rid: round(max(0.0, expiry - now), 3)
                for rid, expiry in sorted(self._expiry.items())
            }


class StaleRouteError(ConnectionError):
    """A topology transition completed while this RPC was parked: its
    pre-transition route may be stale (the study may have moved), so the
    call fails transport-shaped and the client's retry re-routes through
    the fresh topology."""


class _ClientGate:
    """Driver-side topology-transition gate with in-flight accounting.

    The cross-process sibling of the in-process ``_TransitionGate`` +
    ``Replica.enter`` pair: every outbound RPC registers in-flight
    ATOMICALLY with the open-gate check (no window where a request has
    passed the barrier but is invisible to a drain), and a transition
    (failover replay, revive copy-back) first waits out the in-flight
    set before touching fleet state. An RPC that had to PARK on the gate
    raises :class:`StaleRouteError` instead of proceeding — its route was
    resolved against the pre-transition topology.
    """

    def __init__(self):
        self.cond = threading.Condition()
        self.transitions = 0
        self.inflight = 0

    def admit(self, timeout_secs: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_secs
        with self.cond:
            if self.transitions == 0:
                self.inflight += 1
                return
            while self.transitions > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self.cond.wait(remaining)
            raise StaleRouteError(
                "topology transition completed while this RPC was parked; "
                "retry to re-route"
            )

    def leave(self) -> None:
        with self.cond:
            self.inflight -= 1
            self.cond.notify_all()

    def begin(self) -> None:
        with self.cond:
            self.transitions += 1

    def wait_drained(self, timeout_secs: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_secs
        with self.cond:
            while self.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.cond.wait(remaining)
        return True

    def end(self) -> None:
        with self.cond:
            self.transitions -= 1
            self.cond.notify_all()

    def wait_open(self, timeout_secs: float) -> None:
        deadline = time.monotonic() + timeout_secs
        with self.cond:
            while self.transitions > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self.cond.wait(remaining)


class _GatedEndpoint:
    """Endpoint proxy registering every RPC with the client gate."""

    def __init__(self, inner, gate: _ClientGate):
        self._inner = inner
        self._gate = gate

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr):
            return attr
        gate = self._gate

        def call(*args, **kwargs):
            gate.admit()
            try:
                return attr(*args, **kwargs)
            finally:
                gate.leave()

        return call


class _ReplicaProcess:
    """One spawned ``replica_main`` and its addressing."""

    def __init__(self, replica_id: str, port: int, wal_dir: str):
        self.replica_id = replica_id
        self.port = port
        self.wal_dir = wal_dir
        self.endpoint = f"localhost:{port}"
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = os.path.join(
            os.path.dirname(wal_dir), f"{replica_id}.log"
        )

    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class SubprocessReplicaManager:
    """Spawns, leases, fails over, and revives a ``replica_main`` fleet."""

    def __init__(
        self,
        num_replicas: Optional[int] = None,
        *,
        config: Optional[config_lib.DistributedConfig] = None,
        wal_root: str,
        netchaos=None,
        lease_timeout_s: Optional[float] = None,
        heartbeat_interval_s: Optional[float] = None,
        child_env: Optional[Dict[str, str]] = None,
        obs_dump_dir: str = "",
        start_health_loop: bool = True,
        spawn_timeout_s: float = 60.0,
        compute_tier: bool = False,
    ):
        self.config = config or config_lib.DistributedConfig.from_env()
        self._num_replicas = max(2, num_replicas or self.config.num_replicas)
        self._wal_root = wal_root
        self._netchaos = netchaos
        self._child_env = dict(child_env or {})
        self._obs_dump_dir = obs_dump_dir
        self._spawn_timeout_s = spawn_timeout_s
        self.lease = LeaseTable(
            lease_timeout_s
            if lease_timeout_s is not None
            else self.config.lease_timeout_s
        )
        self._heartbeat_interval_s = (
            heartbeat_interval_s
            if heartbeat_interval_s is not None
            else self.config.heartbeat_interval_s
        )

        replica_ids = [f"replica-{i}" for i in range(self._num_replicas)]
        self.router = routing.StudyRouter(replica_ids, routing=self.config.routing)

        # Replica/lease/failover bookkeeping only; RPCs never run under it.
        self._lock = threading.Lock()
        self._failover_lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaProcess] = {}
        self._declared_dead: set = set()
        self._failed_over: set = set()
        self._epochs: Dict[str, int] = {rid: 1 for rid in replica_ids}
        self._failovers = 0
        self._restored_studies = 0
        self._recovery_sources: Dict[str, int] = {}
        self._heartbeat_stats: Dict[str, Dict[str, int]] = {}

        # Barrier + in-flight accounting: fresh client RPCs park while a
        # failover replay / revive copy-back is mid-flight, register
        # in-flight atomically with the gate check, and transitions drain
        # the in-flight set before touching fleet state (the PR 13
        # passed-barrier-but-invisible-to-drain race, client-side).
        self._gate = _ClientGate()

        ports = [_pick_port() for _ in replica_ids]
        for rid, port in zip(replica_ids, ports):
            self._replicas[rid] = _ReplicaProcess(
                rid, port, os.path.join(wal_root, rid)
            )
        self._peers_arg = ",".join(
            f"{rid}={rec.endpoint}" for rid, rec in self._replicas.items()
        )

        # Disaggregated compute tier: one shared Pythia compute server the
        # whole fleet dispatches to (distributed.compute_tier). A fleet
        # member for leasing/failover purposes, but it owns no studies —
        # its "failover" is just a respawn, with frontends riding their
        # local-Pythia fallback through the gap.
        self._compute: Optional[_ReplicaProcess] = None
        self._compute_restarts = 0
        if compute_tier:
            self._compute = _ReplicaProcess(
                COMPUTE_ID, _pick_port(), os.path.join(wal_root, COMPUTE_ID)
            )

        # Control plane: the replication surface of every replica (plus
        # the compute server's Heartbeat-only surface), with bounded
        # transport retries and the netchaos manager-side links.
        control_endpoints = {
            rid: rec.endpoint for rid, rec in self._replicas.items()
        }
        if self._compute is not None:
            control_endpoints[COMPUTE_ID] = self._compute.endpoint
        self._control = repl_service.GrpcReplicationLink(
            control_endpoints,
            src_id="manager",
            netchaos=netchaos,
            connect_timeout_secs=5.0,
        )

        if self._compute is not None:
            self._spawn_compute(self._compute)
        for rid in replica_ids:
            self._spawn(self._replicas[rid], epoch=1)
        records = list(self._replicas.values())
        if self._compute is not None:
            records.append(self._compute)
        self._await_ready(records)
        for rid in replica_ids:
            self.lease.renew(rid)
        if self._compute is not None:
            self.lease.renew(COMPUTE_ID)

        self._stub = router_stub.RoutedVizierStub(
            {
                rid: self._endpoint_factory(rid)
                for rid in replica_ids
            },
            router=self.router,
            on_failure=self._on_endpoint_failure,
            barrier=self.failover_barrier,
        )

        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if start_health_loop:
            self.start_health_loop()

    # -- spawning ------------------------------------------------------------

    def _endpoint_factory(self, replica_id: str):
        def factory():
            from vizier_tpu.service import grpc_stubs

            with self._lock:
                endpoint = self._replicas[replica_id].endpoint
            stub = grpc_stubs.create_vizier_stub(endpoint)
            if self._netchaos is not None:
                stub = self._netchaos.wrap_stub(stub, "client", replica_id)
            return _GatedEndpoint(stub, self._gate)

        return factory

    def _spawn(self, rec: _ReplicaProcess, *, epoch: int) -> None:
        args = [
            sys.executable,
            "-m",
            "vizier_tpu.distributed.replica_main",
            "--replica-id",
            rec.replica_id,
            "--port",
            str(rec.port),
            "--wal-dir",
            rec.wal_dir,
            "--peers",
            self._peers_arg,
            "--replication-factor",
            str(self.config.replication_factor),
            "--replication-epoch",
            str(epoch),
        ]
        if self._compute is not None:
            args += ["--compute-endpoint", self._compute.endpoint]
        if self._obs_dump_dir:
            args += ["--obs-dump-dir", self._obs_dump_dir]
        self._popen(rec, args)

    def _spawn_compute(self, rec: _ReplicaProcess) -> None:
        args = [
            sys.executable,
            "-m",
            "vizier_tpu.distributed.pythia_server_main",
            "--server-id",
            rec.replica_id,
            "--port",
            str(rec.port),
            "--frontends",
            self._peers_arg,
        ]
        if self._obs_dump_dir:
            args += ["--obs-dump-dir", self._obs_dump_dir]
        self._popen(rec, args)

    def _popen(self, rec: _ReplicaProcess, args: List[str]) -> None:
        os.makedirs(self._wal_root, exist_ok=True)
        log = open(rec.log_path, "ab")
        try:
            rec.proc = subprocess.Popen(
                args,
                stdout=subprocess.PIPE,
                stderr=log,
                text=True,
                env={
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    **self._child_env,
                },
            )
        finally:
            log.close()

    def _await_ready(self, records: Sequence[_ReplicaProcess]) -> None:
        deadline = time.monotonic() + self._spawn_timeout_s
        for rec in records:
            line = ""
            while time.monotonic() < deadline:
                line = rec.proc.stdout.readline().strip()
                if line:
                    break
            if not line.startswith("READY "):
                raise RuntimeError(
                    f"{rec.replica_id} failed to start (got {line!r}); "
                    f"see {rec.log_path}"
                )
            endpoint = line.split(" ", 1)[1]
            if endpoint != rec.endpoint:  # pragma: no cover - port pinned
                rec.endpoint = endpoint

    # -- public surface ------------------------------------------------------

    @property
    def stub(self) -> router_stub.RoutedVizierStub:
        return self._stub

    def replica_ids(self) -> List[str]:
        return list(self.router.replica_ids)

    def endpoint_of(self, replica_id: str) -> str:
        with self._lock:
            return self._replicas[replica_id].endpoint

    def owner_of(self, study_name: str) -> str:
        return self.router.replica_for(study_name)

    def is_alive(self, replica_id: str) -> bool:
        with self._lock:
            rec = self._replicas[replica_id]
            declared = replica_id in self._declared_dead
        return rec.running() and not declared

    @property
    def replication_active(self) -> bool:
        return True  # subprocess tiers always stream (peers + WAL dirs)

    def serving_stats(self) -> dict:
        with self._lock:
            stats = {
                "failovers": self._failovers,
                "restored_studies": self._restored_studies,
                "recovery_sources": dict(self._recovery_sources),
                "replication": {
                    "factor": self.config.replication_factor,
                    "fenced_rejections": sum(
                        s.get("fenced_rejections", 0)
                        for s in self._heartbeat_stats.values()
                    ),
                    "resyncs": sum(
                        s.get("resyncs", 0)
                        for s in self._heartbeat_stats.values()
                    ),
                    "heartbeats": {
                        rid: dict(s)
                        for rid, s in sorted(self._heartbeat_stats.items())
                    },
                },
            }
        stats["router"] = self.router.snapshot()
        stats["replicas"] = self._stub.stats()["replicas"]
        stats["leases"] = self.lease.snapshot()
        if self._compute is not None:
            with self._lock:
                restarts = self._compute_restarts
            stats["compute_tier"] = {
                "endpoint": self._compute.endpoint,
                "alive": self._compute.running(),
                "restarts": restarts,
            }
        return stats

    def shutdown(self, grace_s: float = 10.0) -> None:
        self.stop_health_loop()
        with self._lock:
            records = list(self._replicas.values())
        if self._compute is not None:
            records.append(self._compute)
        for rec in records:
            if rec.running():
                rec.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + grace_s
        for rec in records:
            if rec.proc is None:
                continue
            try:
                rec.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                rec.proc.kill()
                rec.proc.wait(timeout=5)
        from vizier_tpu.service import grpc_stubs

        for rec in records:
            grpc_stubs.close_channel(rec.endpoint)

    # -- failure detection ---------------------------------------------------

    def start_health_loop(self) -> None:
        with self._lock:
            if self._health_thread is not None:
                return
            self._health_stop.clear()
            self._health_thread = threading.Thread(
                target=self._health_loop,
                daemon=True,
                name="vizier-subprocess-health",
            )
            self._health_thread.start()

    def stop_health_loop(self) -> None:
        with self._lock:
            thread = self._health_thread
            self._health_thread = None
        if thread is not None:
            self._health_stop.set()
            thread.join(timeout=5)

    def _health_loop(self) -> None:
        while not self._health_stop.wait(self._heartbeat_interval_s):
            try:
                self.check_health()
            except Exception as e:  # a sweep must never kill the loop
                _logger.warning("Subprocess health sweep failed: %s", e)

    def check_health(self) -> Dict[str, str]:
        """One heartbeat sweep: renew leases, fail over expired ones."""
        with self._lock:
            candidates = [
                rid
                for rid in self.router.replica_ids
                if rid not in self._declared_dead
            ]
        for rid in candidates:
            try:
                response = self._control.call_once(
                    rid, "Heartbeat", _pb.HeartbeatRequest(sender="manager")
                )
            except Exception:
                continue  # no renewal; the lease keeps draining
            self.lease.renew(rid)
            with self._lock:
                self._heartbeat_stats[rid] = {
                    "seq": int(response.seq),
                    "fenced_rejections": int(response.fenced_rejections),
                    "resyncs": int(response.resyncs),
                }
        now = time.monotonic()
        for rid in candidates:
            if self.lease.expired(rid, now):
                self._declare_dead(rid, reason="lease_expired")
        self._check_compute_health()
        return self.router.snapshot()

    def _check_compute_health(self) -> None:
        """Compute-server arm of the sweep: renew its lease, and respawn
        it on expiry. No studies live there, so its failover IS the
        respawn — frontends serve from their local fallback in between."""
        if self._compute is None:
            return
        try:
            self._control.call_once(
                COMPUTE_ID, "Heartbeat", _pb.HeartbeatRequest(sender="manager")
            )
        except Exception:
            pass  # no renewal; the lease keeps draining
        else:
            self.lease.renew(COMPUTE_ID)
            return
        if self.lease.expired(COMPUTE_ID):
            recorder_lib.get_recorder().record(
                None,
                "replica_declared_dead",
                replica=COMPUTE_ID,
                reason="lease_expired",
            )
            try:
                self.revive_compute_server()
            except Exception as e:  # next sweep retries
                _logger.warning("Compute-server respawn failed: %s", e)

    def _on_endpoint_failure(self, replica_id: str, error: BaseException) -> None:
        """Routed-stub failure hook. A transport fault alone is NOT death
        (it may be a partition or a chaos drop — the lease decides);
        only an actually-exited process is declared immediately."""
        del error
        with self._lock:
            rec = self._replicas[replica_id]
            declared = replica_id in self._declared_dead
        if declared:
            return
        if rec.proc is not None and rec.proc.poll() is not None:
            self._declare_dead(replica_id, reason="process_exited")

    def _declare_dead(self, replica_id: str, *, reason: str) -> None:
        with self._lock:
            if replica_id in self._declared_dead:
                return
            self._declared_dead.add(replica_id)
        self.lease.drop(replica_id)
        recorder_lib.get_recorder().record(
            None, "replica_declared_dead", replica=replica_id, reason=reason
        )
        self.fail_over(replica_id)

    # -- topology-transition barrier -----------------------------------------

    def failover_barrier(self, timeout_secs: float = 30.0) -> None:
        """Routed-stub hook: routes are only resolved against an open
        gate (the endpoint proxy re-checks atomically at call time)."""
        self._gate.wait_open(timeout_secs)

    def _begin_transition(self, drain_timeout_s: float = 10.0) -> None:
        self._gate.begin()
        if not self._gate.wait_drained(drain_timeout_s):
            _logger.warning(
                "Topology transition proceeding with client RPCs still "
                "in flight after %.1fs.",
                drain_timeout_s,
            )

    def _end_transition(self) -> None:
        self._gate.end()

    # -- chaos / lifecycle ---------------------------------------------------

    def kill_replica(self, replica_id: str, *, flush: bool = True) -> None:
        """SIGKILLs a replica process (a real crash, not a graceful stop).

        ``flush`` first drains its replication streamer — the acked-
        replication durability point (PR 13's in-process chaos runs model
        the same point): replication is asynchronous, so an append acked
        microseconds before an arbitrary SIGKILL may legitimately be in
        flight; the flush pins the kill to the instant where everything
        the client observed is on the successors.
        """
        with self._lock:
            rec = self._replicas[replica_id]
        if flush and rec.running():
            try:
                self._control.call_once(
                    replica_id,
                    "FlushStream",
                    _pb.FlushStreamRequest(timeout_secs=5.0),
                )
            except Exception:
                pass  # dying anyway; recovery plans around the gap
        if rec.running():
            rec.proc.kill()
            try:
                rec.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        recorder_lib.get_recorder().record(
            None, "replica_killed", replica=replica_id
        )

    def has_compute_tier(self) -> bool:
        return self._compute is not None

    def compute_endpoint(self) -> str:
        if self._compute is None:
            raise RuntimeError("This fleet has no compute tier.")
        return self._compute.endpoint

    def compute_is_alive(self) -> bool:
        return self._compute is not None and self._compute.running()

    def kill_compute_server(self) -> None:
        """SIGKILLs the shared compute server (a real crash). Frontends
        degrade to their local Pythia; the health loop (or an explicit
        :meth:`revive_compute_server`) brings the tier back."""
        if self._compute is None:
            raise RuntimeError("This fleet has no compute tier.")
        rec = self._compute
        if rec.running():
            rec.proc.kill()
            try:
                rec.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        recorder_lib.get_recorder().record(
            None, "replica_killed", replica=COMPUTE_ID
        )

    def revive_compute_server(self) -> None:
        """Respawns the compute server on its old port (idempotent: a
        running server is left alone). No fencing and no copy-back — the
        tier is stateless from the fleet's point of view; the shared
        designer cache simply re-warms."""
        if self._compute is None:
            raise RuntimeError("This fleet has no compute tier.")
        rec = self._compute
        with self._failover_lock:
            if rec.running():
                return
            self._spawn_compute(rec)
            self._await_ready([rec])
            # Evict the manager-side channel stuck in reconnect backoff;
            # each FRONTEND evicts its own channel via the RemotePythiaStub
            # cooldown/reconnect path — close_channel here only fixes this
            # process's cache.
            from vizier_tpu.service import grpc_stubs

            grpc_stubs.close_channel(rec.endpoint)
            self._control.set_endpoint(COMPUTE_ID, rec.endpoint)
            self.lease.renew(COMPUTE_ID)
            with self._lock:
                self._compute_restarts += 1
        recorder_lib.get_recorder().record(
            None, "replica_revive", replica=COMPUTE_ID, was_failed_over=False
        )

    def partition_replica(self, replica_id: str) -> None:
        """Severs every driver-side link to ``replica_id`` (netchaos):
        heartbeats stop renewing its lease and client RPCs fail transport-
        shaped — the replica itself keeps running (the zombie regime)."""
        if self._netchaos is None:
            raise RuntimeError("partition_replica needs a NetChaos schedule.")
        self._netchaos.partition(replica_id)
        recorder_lib.get_recorder().record(
            None, "replica_partitioned", replica=replica_id
        )

    def heal_partition(self, replica_id: str) -> None:
        if self._netchaos is None:
            return
        self._netchaos.heal(replica_id)
        recorder_lib.get_recorder().record(
            None, "replica_partition_healed", replica=replica_id
        )

    def corrupt_wal(self, replica_id: str) -> Dict[str, object]:
        """Flips 16 bytes at the midpoint of the replica's live wal.log
        (the ``wal_corrupt`` severity event, manager-side)."""
        with self._lock:
            rec = self._replicas[replica_id]
        path = os.path.join(rec.wal_dir, wal_lib.LOG_FILE)
        try:
            size = os.path.getsize(path)
        except OSError:
            return {"skipped": "no wal.log"}
        if size < 64:
            return {"skipped": f"log too small ({size} bytes)"}
        offset = size // 2
        with open(path, "r+b") as f:
            f.seek(offset)
            f.write(b"\xff" * 16)
        return {"log_bytes": size, "corrupted_at": offset}

    # -- failover -------------------------------------------------------------

    def _live_ids(self) -> List[str]:
        with self._lock:
            return [
                rid
                for rid in self.router.replica_ids
                if rid not in self._declared_dead
            ]

    def _next_epoch(self, origin: str) -> int:
        with self._lock:
            self._epochs[origin] = self._epochs.get(origin, 1) + 1
            return self._epochs[origin]

    def fail_over(self, replica_id: str) -> int:
        """Marks declared-dead replicas down and lifts their studies onto
        successors from the fleet's standby logs, over the wire.

        One call sweeps EVERY declared-dead, not-yet-failed-over replica
        in deterministic id order under one topology transition, exactly
        like the in-process sweep. Idempotent.
        """
        # An EXITED process counts as detected, whether or not a lease
        # has expired yet (the scripted kill→fail_over path, and the
        # "every currently-dead replica" sweep contract: simultaneous
        # multi-kill victims must ALL be corpses to the sweep, or a
        # successor choice — or a standby export — could land on one).
        # Running (possibly partitioned) replicas still wait for their
        # lease to expire.
        newly_declared: List[str] = []
        with self._lock:
            if replica_id in self._failed_over:
                return 0
            for rid, rec in self._replicas.items():
                if (
                    rid not in self._declared_dead
                    and rec.proc is not None
                    and rec.proc.poll() is not None
                ):
                    self._declared_dead.add(rid)
                    newly_declared.append(rid)
        for rid in newly_declared:
            self.lease.drop(rid)
        completed: List[dict] = []
        total_restored = 0
        with self._failover_lock:
            with self._lock:
                if (
                    replica_id in self._failed_over
                    or replica_id not in self._declared_dead
                ):
                    return 0
                dead = sorted(
                    rid
                    for rid in self._declared_dead
                    if rid not in self._failed_over
                )
                self._failed_over.update(dead)
            for rid in dead:
                self.router.mark_down(rid)
            self._begin_transition()
            try:
                for rid in dead:
                    restored, successors, sources = self._restore(rid)
                    self._stub.note_failed_over(rid)
                    total_restored += restored
                    completed.append(
                        {
                            "replica": rid,
                            "restored": restored,
                            "successors": sorted(successors),
                            "sources": sources,
                        }
                    )
            finally:
                self._end_transition()
        with self._lock:
            for entry in completed:
                self._failovers += 1
                self._restored_studies += entry["restored"]
                for source, count in entry["sources"].items():
                    self._recovery_sources[source] = (
                        self._recovery_sources.get(source, 0) + count
                    )
        for entry in completed:
            recorder_lib.get_recorder().record(
                None,
                "replica_failover",
                replica=entry["replica"],
                successors=entry["successors"],
                restored_studies=entry["restored"],
                recovery_sources=entry["sources"],
            )
        return total_restored

    def _restore(self, origin: str) -> Tuple[int, set, Dict[str, int]]:
        """Fence → collect standby views → plan → apply, all over gRPC."""
        live = [rid for rid in self._live_ids() if rid != origin]
        # FENCE FIRST: after this, nothing the origin's stale generation
        # streams can enter any live standby log — the views exported
        # below are final, and a zombie's post-partition appends are
        # rejected (and counted) rather than racing the replay.
        new_epoch = self._next_epoch(origin)
        for rid in live:
            try:
                self._control.call(
                    rid,
                    "Fence",
                    _pb.FenceRequest(origin=origin, epoch=new_epoch),
                )
            except Exception as e:
                _logger.warning("Fence of %s on %s failed: %s", origin, rid, e)
        holders: List[str] = []
        views: List[replication_lib.StandbyView] = []
        for rid in live:
            try:
                response = self._control.call(
                    rid, "ExportStandby", _pb.ExportStandbyRequest(origin=origin)
                )
            except Exception as e:
                _logger.warning(
                    "ExportStandby(%s) from %s failed: %s", origin, rid, e
                )
                continue
            if response.present:
                holders.append(rid)
                views.append(
                    replication_lib.StandbyView(
                        baseline_seq=int(response.baseline_seq),
                        records=repl_service.records_from_proto(
                            response.records
                        ),
                    )
                )
        # The corpse's local WAL is an optimization, not a dependency —
        # and reading the live disk of a PARTITIONED (still-running)
        # origin would be a shared-filesystem cheat, so only an exited
        # process's directory is consulted.
        local_records: List[Tuple[int, int, bytes]] = []
        local_torn = False
        with self._lock:
            rec = self._replicas[origin]
        if not rec.running() and os.path.isdir(rec.wal_dir):
            local_records, local_torn = wal_lib.read_directory_with_seqs(
                rec.wal_dir
            )
        plan = replication_lib.plan_recovery(
            origin,
            local_records,
            local_torn,
            views,
            successors_fn=lambda study: self.router.successors(
                study, origin, self.config.replication_factor
            ),
            holders=holders,
        )
        successors: set = set()
        per_owner: Dict[str, List[replication_lib.Record]] = {}
        for item in plan.studies:
            owner = self.router.replica_for(item.study)
            per_owner.setdefault(owner, []).extend(
                (item.seq, opcode, payload)
                for opcode, payload in item.records
            )
            successors.add(owner)
        for owner, records in sorted(per_owner.items()):
            request = _pb.ApplyRecordsRequest()
            repl_service.records_to_proto(records, request.records)
            self._control.call(owner, "ApplyRecords", request)
        return len(plan.studies), successors, plan.source_counts()

    # -- revive ---------------------------------------------------------------

    def revive_replica(self, replica_id: str) -> None:
        """Fenced process restart + copy-back (safe under live traffic).

        The zombie (if the process still runs — the healed-partition
        case) is killed first: its generation is already fenced out and
        two processes must not share one WAL directory.
        """
        with self._failover_lock:
            with self._lock:
                rec = self._replicas[replica_id]
                was_failed_over = replica_id in self._failed_over
                declared = replica_id in self._declared_dead
            if not declared and rec.running():
                return  # never declared dead: nothing to revive
            if rec.running():
                rec.proc.kill()
                try:
                    rec.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            new_epoch = self._next_epoch(replica_id)
            for rid in self._live_ids():
                if rid == replica_id:
                    continue
                try:
                    self._control.call(
                        rid,
                        "Fence",
                        _pb.FenceRequest(origin=replica_id, epoch=new_epoch),
                    )
                except Exception:
                    pass
            self._spawn(rec, epoch=new_epoch)
            self._await_ready([rec])
            # The shared channel to this endpoint is sitting in gRPC's
            # TRANSIENT_FAILURE reconnect backoff (every RPC fails fast
            # with the cached refusal until the backoff expires): evict
            # it so the copy-back and fresh client traffic connect to the
            # restarted server immediately.
            from vizier_tpu.service import grpc_stubs

            grpc_stubs.close_channel(rec.endpoint)
            self._control.set_endpoint(replica_id, rec.endpoint)
            self._begin_transition()
            try:
                if was_failed_over:
                    self._copy_back(replica_id)
                with self._lock:
                    self._declared_dead.discard(replica_id)
                    self._failed_over.discard(replica_id)
                self._stub.set_endpoint(
                    replica_id, self._endpoint_factory(replica_id)
                )
                self.router.mark_up(replica_id)
                self.lease.renew(replica_id)
            finally:
                self._end_transition()
            # Every other origin re-baselines the revived replica's
            # standby logs, which went stale while it was down.
            for rid in self._live_ids():
                if rid == replica_id:
                    continue
                try:
                    self._control.call(
                        rid, "Resync", _pb.ResyncRequest(successor=replica_id)
                    )
                except Exception:
                    pass
        recorder_lib.get_recorder().record(
            None,
            "replica_revive",
            replica=replica_id,
            was_failed_over=was_failed_over,
            epoch_fenced=True,
        )

    def _copy_back(self, revived_id: str) -> None:
        """Moves studies the revived replica owns back from their interim
        successors, and deletes net-deleted studies its stale WAL
        resurrected — the in-process ``_copy_back_from_successors``
        contract, executed over ``ExportState``/``ApplyRecords``."""
        live = [rid for rid in self._live_ids() if rid != revived_id]
        reachable = set(live) | {revived_id}

        def routes_to_revived(study_key: str) -> bool:
            for rid in self.router.ranking(study_key):
                if rid in reachable:
                    return rid == revived_id
            return False

        from vizier_tpu.service import grpc_stubs
        from vizier_tpu.service.protos import vizier_service_pb2

        on_successors: set = set()
        for successor in live:
            try:
                state = self._control.call(
                    successor, "ExportState", _pb.ExportStateRequest()
                )
            except Exception as e:
                _logger.warning(
                    "ExportState from %s failed during revive of %s: %s",
                    successor,
                    revived_id,
                    e,
                )
                continue
            moved_records = _pb.ApplyRecordsRequest()
            moved_studies: set = set()
            for record in state.records:
                study_key = wal_lib.study_key_of(record.opcode, record.payload)
                on_successors.add(study_key)
                if not routes_to_revived(study_key):
                    continue
                moved_records.records.add(
                    seq=record.seq, opcode=record.opcode, payload=record.payload
                )
                moved_studies.add(study_key)
            if moved_studies:
                self._control.call(revived_id, "ApplyRecords", moved_records)
                # Delete from the interim owner DIRECTLY (not routed: the
                # router already maps these studies to the revived
                # replica).
                with self._lock:
                    endpoint = self._replicas[successor].endpoint
                vstub = grpc_stubs.create_vizier_stub(endpoint)
                for study_key in sorted(moved_studies):
                    try:
                        vstub.DeleteStudy(
                            vizier_service_pb2.DeleteStudyRequest(
                                name=study_key
                            )
                        )
                    except Exception:
                        pass  # already gone / never fully copied
        # Studies the revived replica rebuilt from its own (stale) WAL
        # that exist on NO live successor were deleted while it was down:
        # delete them rather than resurrect.
        try:
            state = self._control.call(
                revived_id, "ExportState", _pb.ExportStateRequest()
            )
        except Exception:
            return
        with self._lock:
            endpoint = self._replicas[revived_id].endpoint
        vstub = grpc_stubs.create_vizier_stub(endpoint)
        for record in state.records:
            if record.opcode != wal_lib.CREATE_STUDY:
                continue
            study_key = wal_lib.study_key_of(record.opcode, record.payload)
            if study_key in on_successors or not routes_to_revived(study_key):
                continue
            try:
                vstub.DeleteStudy(
                    vizier_service_pb2.DeleteStudyRequest(name=study_key)
                )
            except Exception:  # pragma: no cover - already gone
                pass
