"""Disaggregated compute tier: frontends share ONE remote Pythia server.

The source architecture separates the Pythia algorithm service from the
Vizier DB service so algorithm compute scales independently of traffic
("The Vizier Gaussian Process Bandit Algorithm", arXiv:2408.11527 §4; the
reference's ``DistributedPythiaVizierServer`` topology). The subprocess
fleet gives every ``replica_main`` its OWN in-process Pythia, so the
cross-study batch executor, designer cache, and speculative engine
amortize only within one process. This module is the other topology: N
frontend replicas dispatch Pythia work over the EXISTING ``PythiaService``
gRPC surface to one standalone compute server
(``distributed.pythia_server_main``) hosting one shared
:class:`~vizier_tpu.service.pythia_service.PythiaServicer` — one designer
cache, one batch executor whose shape buckets fuse concurrent suggests
from the WHOLE fleet into single vmapped flushes (occupancy ≈ N frontends
instead of N singleton flushes).

:class:`RemotePythiaStub` is the frontend half: a duck-typed drop-in for
``VizierServicer.set_pythia`` that forwards ``Suggest``/``EarlyStop`` to
the tier under the reliability plane's :class:`RetryPolicy` and the
request's propagated deadline budget, and **degrades gracefully** — when
the tier is unreachable it serves from the frontend's local minimal
Pythia (``fallback="local"``), enters a cooldown so the hot path never
re-blocks on a dead endpoint, and re-probes after
``health_interval_s``. ``trace_context`` is re-stamped across the hop
with a ``compute_tier.remote_suggest`` span carrying
``frontend=<replica_id>``, so a merged fleet dump stitches
frontend→compute-tier traces (``tools/obs_report.py --fleet``).

Off-switch semantics: with ``VIZIER_COMPUTE_TIER=0`` (the default) no
stub is constructed anywhere and the self-contained path is bit-identical
to the pre-tier tree (see PARITY.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Optional

from vizier_tpu.analysis import registry as env_registry
from vizier_tpu.reliability import deadline as deadline_lib
from vizier_tpu.reliability import errors as errors_lib
from vizier_tpu.reliability import retry as retry_lib

try:  # grpc is present in the service image; keep importable without it.
    import grpc
except ImportError:  # pragma: no cover - service extras absent
    grpc = None  # type: ignore[assignment]

# Seconds a connect attempt (channel-ready wait) may block a probing
# request. Deliberately short: the only caller that pays it is the first
# request after a cooldown expires, and the local fallback is one
# exception away.
CONNECT_TIMEOUT_S = 2.0


@dataclasses.dataclass(frozen=True)
class ComputeTierConfig:
    """The frontend-side compute-tier switches (VIZIER_COMPUTE_TIER*)."""

    enabled: bool = False
    # host:port of the shared Pythia compute server. Empty with the tier
    # enabled behaves as "tier down": every request takes the fallback.
    endpoint: str = ""
    # "local" — serve from the frontend's own minimal Pythia when the
    # tier is unreachable; "fail" — surface the transport error.
    fallback: str = "local"
    # Cooldown after a tier failure before the next remote re-probe.
    health_interval_s: float = 1.0

    def __post_init__(self):
        if self.fallback not in ("local", "fail"):
            raise ValueError(
                f"ComputeTierConfig.fallback must be 'local' or 'fail', "
                f"got {self.fallback!r}."
            )

    @classmethod
    def from_env(cls) -> "ComputeTierConfig":
        return cls(
            enabled=env_registry.env_on("VIZIER_COMPUTE_TIER"),
            endpoint=env_registry.env_str("VIZIER_COMPUTE_TIER_ENDPOINT"),
            fallback=env_registry.env_str(
                "VIZIER_COMPUTE_TIER_FALLBACK", "local"
            ),
            health_interval_s=env_registry.env_float(
                "VIZIER_COMPUTE_TIER_HEALTH_INTERVAL_S", 1.0
            ),
        )


def _is_tier_unreachable(error: BaseException) -> bool:
    """Transport-level failures that mean "the tier, not the request".

    Semantic errors (NotFoundError, ValueError — already translated by the
    stub layer) and designer failures that the COMPUTE SERVER handled (it
    has its own breaker/fallback plane) must propagate unchanged; only the
    hop itself failing engages the frontend's degradation path.
    """
    if isinstance(error, (ConnectionError, TimeoutError)):
        return True
    if isinstance(error, ValueError) and "closed channel" in str(error):
        # A concurrent request's failure path evicted the shared channel
        # (``close_channel`` in ``_note_tier_down``) while this call was
        # in flight: grpcio surfaces that as ``ValueError: Cannot invoke
        # RPC on closed channel!`` — the tier is down, not the request.
        return True
    if grpc is None:  # pragma: no cover - service extras absent
        return False
    if isinstance(error, grpc.FutureTimeoutError):
        return True  # channel never became ready (server down at connect)
    if isinstance(error, grpc.RpcError):
        code = error.code() if hasattr(error, "code") else None
        return code in (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
            grpc.StatusCode.CANCELLED,
        )
    return False


class RemotePythiaStub:
    """Frontend-side Pythia endpoint that dispatches to the compute tier.

    Duck-typed drop-in for ``VizierServicer.set_pythia``: the servicer
    surface (``Suggest``/``EarlyStop``/``Ping``) goes remote; the
    state-management surface (``invalidate_study``, ``notify_trial_event``,
    ``serving_runtime``, ``serving_stats``) stays LOCAL — the shared tier
    has no invalidation RPC, so it detects config turnover itself by
    keying its caches on ``(study_name, config_hash)`` (see
    ``PythiaServicer._parsed_study_config``).

    Lock order: ``_lock`` is a LEAF — counter/cooldown bookkeeping only;
    stub construction and every RPC run outside it (enforced by the
    lock_order static-analysis pass).
    """

    def __init__(
        self,
        endpoint: str,
        *,
        local: Any = None,
        replica_id: str = "",
        config: Optional[ComputeTierConfig] = None,
        retry_policy: Optional[retry_lib.RetryPolicy] = None,
        stub_factory: Optional[Callable[[], Any]] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self._endpoint = endpoint
        self._local = local
        self._replica_id = replica_id
        self._config = config or ComputeTierConfig(
            enabled=True, endpoint=endpoint
        )
        # Tight retry budget: the tier hop sits INSIDE the service's own
        # dispatch deadline, and the local fallback is the real second
        # attempt. One quick in-hop retry absorbs connection blips.
        self._retry = retry_policy or retry_lib.RetryPolicy(
            max_attempts=2, base_delay_secs=0.05, max_delay_secs=0.25
        )
        self._stub_factory = stub_factory or self._default_stub_factory
        self._time = time_fn
        self._lock = threading.Lock()  # LEAF: bookkeeping only, no RPC.
        self._remote: Any = None
        self._down_until = 0.0
        self._remote_calls = 0
        self._remote_failures = 0
        self._fallback_serves = 0
        self._reconnects = 0

    # -- remote plumbing ---------------------------------------------------

    def _default_stub_factory(self):
        from vizier_tpu.service import grpc_stubs

        return grpc_stubs.create_pythia_stub(
            self._endpoint, timeout=CONNECT_TIMEOUT_S
        )

    def _remote_stub(self):
        """The cached Pythia stub; (re)built OUTSIDE the leaf lock —
        ``create_pythia_stub`` blocks on channel readiness."""
        with self._lock:
            remote = self._remote
        if remote is not None:
            return remote
        built = self._stub_factory()
        with self._lock:
            if self._remote is None:
                self._remote = built
                self._reconnects += 1
            return self._remote

    def _cooling_down(self) -> bool:
        if not self._endpoint:
            return True  # no endpoint configured: permanently "down"
        now = self._time()
        with self._lock:
            return now < self._down_until

    def _note_tier_down(self, error: BaseException) -> None:
        """Failure bookkeeping + channel eviction + cooldown arm."""
        from vizier_tpu.observability import flight_recorder as recorder_lib
        from vizier_tpu.service import grpc_stubs

        if self._endpoint:
            # The shared channel may be wedged on a dead server; evict so
            # the post-cooldown probe reconnects instead of re-timing-out.
            grpc_stubs.close_channel(self._endpoint)
        with self._lock:
            self._remote = None
            self._remote_failures += 1
            self._down_until = self._time() + max(
                0.0, self._config.health_interval_s
            )
        recorder_lib.get_recorder().record(
            None,
            "compute_tier_down",
            frontend=self._replica_id,
            endpoint=self._endpoint,
            error=errors_lib.format_op_error(error),
        )

    def _fallback(self, method: str, request, error: Optional[BaseException]):
        from vizier_tpu.observability import tracing as tracing_lib

        if self._config.fallback != "local" or self._local is None:
            if error is not None:
                raise error
            raise errors_lib.TransientError(
                errors_lib.mark_transient(
                    f"Compute tier {self._endpoint or '(unset)'} unavailable "
                    f"and fallback={self._config.fallback!r}."
                )
            )
        with self._lock:
            self._fallback_serves += 1
        tracing_lib.add_current_event(
            "compute_tier.fallback",
            method=method,
            endpoint=self._endpoint,
            frontend=self._replica_id,
        )
        return getattr(self._local, method)(request)

    def _dispatch(self, method: str, request, span_name: str):
        from vizier_tpu.observability import tracing as tracing_lib

        tracer = tracing_lib.get_tracer()
        parent = tracing_lib.parse_context(
            getattr(request, "trace_context", "")
        )
        with tracer.span(
            span_name,
            parent=parent,
            frontend=self._replica_id,
            endpoint=self._endpoint,
            study=getattr(request, "study_name", ""),
        ) as span:
            # Re-stamp the wire context so the compute server's spans
            # parent under THIS frontend-attributed hop span — that is
            # what lets the fleet merge compute per-frontend fan-in.
            if hasattr(request, "trace_context"):
                request.trace_context = tracing_lib.format_context(
                    span.context()
                )
            if self._cooling_down():
                span.set_attribute("fallback", True)
                return self._fallback(method, request, None)
            deadline = deadline_lib.Deadline.from_wire(
                getattr(request, "deadline_secs", 0.0)
            )
            try:
                remote = self._remote_stub()
                response = self._retry.call(
                    lambda: getattr(remote, method)(request),
                    deadline=deadline if deadline.is_set else None,
                )
            except Exception as e:  # noqa: BLE001 - classified below
                if not _is_tier_unreachable(e):
                    raise
                self._note_tier_down(e)
                span.set_attribute("fallback", True)
                return self._fallback(method, request, e)
            with self._lock:
                self._remote_calls += 1
            return response

    # -- the PythiaService surface ----------------------------------------

    def Suggest(self, request, context=None):
        del context
        return self._dispatch("Suggest", request, "compute_tier.remote_suggest")

    def EarlyStop(self, request, context=None):
        del context
        return self._dispatch(
            "EarlyStop", request, "compute_tier.remote_early_stop"
        )

    def Ping(self, request, context=None):
        del context
        if not self._cooling_down():
            try:
                return self._remote_stub().Ping(request)
            except Exception as e:  # noqa: BLE001 - classified below
                if not _is_tier_unreachable(e):
                    raise
                self._note_tier_down(e)
        return self._fallback("Ping", request, None)

    # -- local state-management surface (duck-typed by VizierServicer) -----

    @property
    def serving_runtime(self):
        return getattr(self._local, "serving_runtime", None)

    def invalidate_study(self, study_name: str) -> None:
        invalidate = getattr(self._local, "invalidate_study", None)
        if invalidate is not None:
            invalidate(study_name)

    def notify_trial_event(self, *args, **kwargs) -> None:
        notify = getattr(self._local, "notify_trial_event", None)
        if notify is not None:
            notify(*args, **kwargs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "endpoint": self._endpoint,
                "remote_calls": self._remote_calls,
                "remote_failures": self._remote_failures,
                "fallback_serves": self._fallback_serves,
                "reconnects": self._reconnects,
                "cooling_down": self._time() < self._down_until,
            }

    def serving_stats(self) -> dict:
        base = {}
        local_stats = getattr(self._local, "serving_stats", None)
        if local_stats is not None:
            base = dict(local_stats())
        base["compute_tier"] = self.stats()
        return base

    def shutdown(self) -> None:
        from vizier_tpu.service import grpc_stubs

        local_shutdown = getattr(self._local, "shutdown", None)
        if local_shutdown is not None:
            local_shutdown()
        if self._endpoint:
            grpc_stubs.close_channel(self._endpoint)


def maybe_wrap_pythia(
    local_pythia,
    *,
    replica_id: str = "",
    endpoint: str = "",
    config: Optional[ComputeTierConfig] = None,
) -> Any:
    """``local_pythia`` unchanged when the tier is off (the bit-identical
    default), else a :class:`RemotePythiaStub` fronting it.

    ``endpoint`` (e.g. from ``replica_main --compute-endpoint``) overrides
    the config's; a non-empty explicit endpoint also implies enablement so
    the fleet manager can arm frontends by flag alone.
    """
    cfg = config or ComputeTierConfig.from_env()
    target = endpoint or cfg.endpoint
    if not (cfg.enabled or endpoint) or not target:
        return local_pythia
    if cfg.endpoint != target or not cfg.enabled:
        cfg = dataclasses.replace(cfg, enabled=True, endpoint=target)
    return RemotePythiaStub(
        target, local=local_pythia, replica_id=replica_id, config=cfg
    )
