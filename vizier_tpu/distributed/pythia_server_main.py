"""The disaggregated compute tier as a standalone gRPC server process.

``python -m vizier_tpu.distributed.pythia_server_main --server-id
compute-0 --port 28190 --frontends replica-0=host:port,...`` starts ONE
shared :class:`~vizier_tpu.service.pythia_service.PythiaServicer` behind
a gRPC server — one designer cache, one batch executor whose shape
buckets fuse concurrent suggests from EVERY frontend into single vmapped
flushes, one speculative engine, mesh placements spanning this process's
whole visible device pool. N ``replica_main`` frontends running with
``--compute-endpoint`` dispatch their Pythia work here over the existing
``PythiaService`` surface (``distributed.compute_tier.RemotePythiaStub``).

The servicer reads trials back through a
:class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub` over the
``--frontends`` endpoints — the same rendezvous placement the fleet's
clients use, so each study's read-back lands on the frontend that owns
it. Connections are lazy: the tier may start before, after, or between
frontend (re)starts.

Unlike ``replica_main``, this process does NOT default
``JAX_PLATFORMS=cpu`` — the compute tier is the process that is SUPPOSED
to own the accelerators. Test/CI spawners pin cpu through the child
environment instead (``SubprocessReplicaManager`` does).

The ``ReplicationService`` surface is served solely for its ``Heartbeat``
method: the fleet manager health-checks the compute server with the same
lease probes it sends replicas, and a missed lease triggers a respawn
(frontends ride their local-Pythia fallback during the gap — no studies
live here, so there is nothing to restore).

Prints ``READY <endpoint>`` on stdout once serving; SIGTERM drains
in-flight RPCs through the grace window, shuts the serving runtime down,
and writes the ``--obs-dump-dir`` observability dump so the fleet merge
(``tools/obs_report.py --fleet``) can stitch frontend→compute-tier traces
and read this process's batch-occupancy histograms.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from concurrent import futures


def _parse_frontends(spec: str):
    """``rid=host:port,...`` -> ordered dict of frontend endpoints."""
    frontends = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        rid, _, endpoint = entry.partition("=")
        if not rid or not endpoint:
            raise SystemExit(f"Bad --frontends entry: {entry!r}")
        frontends[rid] = endpoint
    return frontends


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--server-id", default="compute-0")
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    parser.add_argument(
        "--frontends",
        default="",
        help="frontend replicas as 'rid=host:port,...'; the shared "
        "servicer reads trials back through a routed stub over these "
        "(required for GP algorithms; '' only serves stateless policies)",
    )
    parser.add_argument(
        "--max-workers",
        type=int,
        default=30,
        help="gRPC handler threads; keep >= the frontend count so "
        "concurrent same-bucket suggests can actually meet in one "
        "batch-executor flush window",
    )
    parser.add_argument(
        "--shutdown-grace",
        type=float,
        default=5.0,
        help="seconds SIGTERM waits for in-flight RPCs to drain",
    )
    parser.add_argument(
        "--obs-dump-dir",
        default=None,
        help="write <server-id>-{spans.jsonl,metrics.json,recorder.json} "
        "here on shutdown for fleet merging (obs_report --fleet); "
        "default: $VIZIER_OBS_DUMP_DIR ('' = no dump)",
    )
    args = parser.parse_args(argv)

    import grpc

    from vizier_tpu.analysis import registry as env_registry
    from vizier_tpu.distributed import config as config_lib
    from vizier_tpu.distributed import replication as replication_lib
    from vizier_tpu.distributed import replication_service as repl_service
    from vizier_tpu.distributed import router_stub, routing
    from vizier_tpu.service import grpc_stubs, pythia_service
    from vizier_tpu.service.vizier_server import _pick_port

    obs_dump_dir = args.obs_dump_dir
    if obs_dump_dir is None:
        obs_dump_dir = env_registry.env_str("VIZIER_OBS_DUMP_DIR")

    frontends = _parse_frontends(args.frontends)

    vizier_backend = None
    if frontends:
        dist_config = config_lib.DistributedConfig.from_env()
        # Lazy endpoint factories: a frontend that is not up yet (or is
        # mid-revive) costs nothing until a study routed to it is read.
        endpoints = {
            rid: (lambda ep=endpoint: grpc_stubs.create_vizier_stub(ep))
            for rid, endpoint in frontends.items()
        }
        vizier_backend = router_stub.RoutedVizierStub(
            endpoints,
            router=routing.StudyRouter(
                list(frontends), routing=dist_config.routing
            ),
        )

    pythia = pythia_service.PythiaServicer(vizier_backend)

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=args.max_workers))
    grpc_stubs.add_pythia_servicer_to_server(pythia, server)
    # Heartbeat-only replication surface: the fleet manager's lease plane
    # probes the compute server exactly like any replica.
    replication_servicer = repl_service.ReplicationServicer(
        args.server_id, replication_lib.StandbyStore()
    )
    grpc_stubs.add_replication_servicer_to_server(replication_servicer, server)

    endpoint = f"{args.host}:{args.port or _pick_port()}"
    server.add_insecure_port(endpoint)
    server.start()

    print(f"READY {endpoint}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()

    # Drain in-flight suggests through the grace window, then stop the
    # serving runtime's background planes (speculative workers, batch
    # executor threads), then dump observability — the dump reflects every
    # flush the process actually served.
    server.stop(args.shutdown_grace).wait()
    runtime = pythia.serving_runtime
    if runtime is not None:
        runtime.shutdown()
    grpc_stubs.close_channel(endpoint)
    if obs_dump_dir:
        from vizier_tpu.observability import fleet as fleet_lib
        from vizier_tpu.observability import flight_recorder as recorder_lib
        from vizier_tpu.observability import tracing as tracing_lib

        registry = runtime.metrics if runtime is not None else None
        written = fleet_lib.dump_process(
            obs_dump_dir,
            args.server_id,
            tracer=tracing_lib.get_tracer(),
            registry=registry,
            recorder=recorder_lib.get_recorder(),
        )
        print(
            f"[{args.server_id}] observability dump: "
            f"{', '.join(sorted(written.values()))}",
            file=sys.stderr,
            flush=True,
        )


if __name__ == "__main__":
    main()
