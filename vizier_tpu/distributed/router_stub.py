"""``RoutedVizierStub``: client-side study-affinity routing, stub-shaped.

Exposes exactly the ``VizierServiceStub`` method surface and routes every
RPC to the replica that owns the request's study (rendezvous placement via
:class:`~vizier_tpu.distributed.routing.StudyRouter`), so it drops into
every place a stub or in-process servicer already goes — ``VizierClient``,
``clients.Study``, the Pythia supporter — with zero caller changes.

Per-method routing keys come from the request protos themselves (study
``name``/``parent`` fields, trial and operation names parsed back to their
study), so the router needs no out-of-band placement metadata. The one
owner-scoped RPC, ``ListStudies``, fans out across live replicas and
merges — and is LOUD about partiality: when a replica is down and nothing
has declared its studies failed over to successors
(:meth:`RoutedVizierStub.note_failed_over`, called by the manager after a
WAL-restore), the fan-out raises a transport-shaped error instead of
silently returning a subset.

Failure handling: transport-shaped errors (``ConnectionError``, gRPC
``UNAVAILABLE``) are reported to the failure hook — a
:class:`~vizier_tpu.distributed.replica_manager.ReplicaManager` verifies
the replica is really dead, marks it down, and lifts its studies onto
their successors — and then re-raised unchanged. The caller's existing
retry machinery (``vizier_tpu.reliability``) absorbs the transition: the
retried RPC routes to the successor. Without a hook, the stub marks a
replica down itself after ``failure_threshold`` consecutive transport
failures.

Observability: ``vizier_replica_requests_total{replica,method}`` /
``vizier_replica_failures_total{replica,method}`` counters plus a
``router.route`` event (replica + method) on the active span.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Mapping, Optional, Union

from vizier_tpu.distributed import routing
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.service import resources
from vizier_tpu.service.protos import vizier_service_pb2


def _study_of_trial(name: str) -> str:
    return resources.TrialResource.from_name(name).study_resource.name


def _study_of_operation(name: str) -> str:
    r = resources.SuggestionOperationResource.from_name(name)
    return resources.StudyResource(r.owner_id, r.study_id).name


def _create_study_key(request) -> str:
    # VizierClient always names the study before CreateStudy; an unnamed
    # create routes by owner so create_or_load of the same id stays on one
    # replica.
    return request.study.name or request.parent


# method -> study-key extractor. ListStudies is the fan-out special case.
ROUTING_KEYS: Dict[str, Callable[[Any], str]] = {
    "CreateStudy": _create_study_key,
    "GetStudy": lambda r: r.name,
    "DeleteStudy": lambda r: r.name,
    "SetStudyState": lambda r: r.name,
    "SuggestTrials": lambda r: r.parent,
    "GetOperation": lambda r: _study_of_operation(r.name),
    "CreateTrial": lambda r: r.parent,
    "GetTrial": lambda r: _study_of_trial(r.name),
    "ListTrials": lambda r: r.parent,
    "AddTrialMeasurement": lambda r: _study_of_trial(r.trial_name),
    "CompleteTrial": lambda r: _study_of_trial(r.name),
    "DeleteTrial": lambda r: _study_of_trial(r.name),
    "CheckTrialEarlyStoppingState": lambda r: _study_of_trial(r.trial_name),
    "StopTrial": lambda r: _study_of_trial(r.name),
    "ListOptimalTrials": lambda r: r.parent,
    "UpdateMetadata": lambda r: r.name,
}

# Transport-shaped failures that implicate the REPLICA rather than the
# request. Deadline/timeout errors are deliberately absent: a slow
# computation must not down a healthy replica.
def _is_transport_failure(error: BaseException) -> bool:
    if isinstance(error, ConnectionError):
        return True
    code = getattr(error, "code", None)
    if callable(code):
        try:
            import grpc

            if isinstance(error, grpc.RpcError):
                return code() == grpc.StatusCode.UNAVAILABLE
        except Exception:
            return False
    return False


EndpointLike = Union[Any, Callable[[], Any]]


class RoutedVizierStub:
    """Routes the Vizier RPC surface across replica endpoints."""

    def __init__(
        self,
        endpoints: Mapping[str, EndpointLike],
        *,
        router: Optional[routing.StudyRouter] = None,
        routing_enabled: bool = True,
        on_failure: Optional[Callable[[str, BaseException], None]] = None,
        failure_threshold: int = 2,
        registry: Optional[metrics_lib.MetricsRegistry] = None,
        retry_sink: Optional[Callable[[int], None]] = None,
        barrier: Optional[Callable[[], None]] = None,
    ):
        if not endpoints:
            raise ValueError("RoutedVizierStub needs at least one endpoint.")
        self._endpoint_spec = dict(endpoints)
        self.router = router or routing.StudyRouter(
            list(self._endpoint_spec), routing=routing_enabled
        )
        self._on_failure = on_failure
        self._failure_threshold = max(1, failure_threshold)
        self._retry_sink = retry_sink
        # Topology-transition barrier (ReplicaManager.failover_barrier):
        # called before resolving a route, it briefly parks fresh RPCs
        # while a failover/revive is mid-replay, so requests cannot land
        # on a successor the WAL replay has not populated yet (a NotFound
        # there would read as "study deleted", which no retry fixes).
        self._barrier = barrier
        self._lock = threading.Lock()  # resolved-endpoint + failure tables
        self._resolved: Dict[str, Any] = {}
        self._consecutive_failures: Dict[str, int] = {}
        # Down replicas whose studies ARE served elsewhere (WAL-restored
        # onto successors): a ListStudies fan-out over the live set is
        # still complete with these down.
        self._failed_over: set = set()
        reg = registry or metrics_lib.MetricsRegistry()
        self._requests = reg.counter(
            "vizier_replica_requests", help="RPCs routed per replica."
        )
        self._failures = reg.counter(
            "vizier_replica_failures",
            help="Transport failures observed per replica.",
        )
        self.registry = reg
        for name in ROUTING_KEYS:
            setattr(self, name, self._bind(name))
        # ListStudies is owner-scoped: fan out + merge.
        setattr(self, "ListStudies", self._list_studies)

    # -- endpoint plumbing -------------------------------------------------

    def _endpoint(self, replica_id: str):
        with self._lock:
            resolved = self._resolved.get(replica_id)
        if resolved is not None:
            return resolved
        spec = self._endpoint_spec[replica_id]
        # A zero-arg factory (lazy gRPC connect) vs an already-built
        # stub/servicer: duck-typed on the RPC surface.
        resolved = spec if hasattr(spec, "SuggestTrials") else spec()
        with self._lock:
            self._resolved[replica_id] = resolved
        return resolved

    def invalidate_endpoint(self, replica_id: str) -> None:
        """Drops the cached endpoint (a revived replica reconnects fresh)."""
        with self._lock:
            self._resolved.pop(replica_id, None)
            self._consecutive_failures.pop(replica_id, None)

    def set_endpoint(self, replica_id: str, endpoint: EndpointLike) -> None:
        """Repoints a replica id at a new endpoint (replica restart)."""
        if replica_id not in self._endpoint_spec:
            raise KeyError(f"Unknown replica id: {replica_id!r}")
        with self._lock:
            self._endpoint_spec[replica_id] = endpoint
            self._resolved.pop(replica_id, None)
            self._consecutive_failures.pop(replica_id, None)
            # A restarted replica owns its studies again.
            self._failed_over.discard(replica_id)

    def note_failed_over(self, replica_id: str) -> None:
        """Declares a down replica's studies restored onto successors, so
        a live-replica ``ListStudies`` fan-out counts as complete."""
        with self._lock:
            self._failed_over.add(replica_id)

    def _note_success(self, replica_id: str) -> None:
        with self._lock:
            self._consecutive_failures.pop(replica_id, None)

    def _note_failure(self, replica_id: str, error: BaseException) -> None:
        self._failures.inc(replica=replica_id)
        if self._on_failure is not None:
            # The manager decides (verifies the replica is really dead,
            # marks down, runs failover restore) — synchronously, so the
            # caller's retry already sees the post-failover routing.
            self._on_failure(replica_id, error)
            return
        with self._lock:
            count = self._consecutive_failures.get(replica_id, 0) + 1
            self._consecutive_failures[replica_id] = count
        if count >= self._failure_threshold:
            self.router.mark_down(replica_id)

    # -- RPC surface -------------------------------------------------------

    def _bind(self, method_name: str):
        extract = ROUTING_KEYS[method_name]

        def call(request):
            if self._barrier is not None:
                self._barrier()
            study_key = extract(request)
            replica_id = self.router.replica_for(study_key)
            self._requests.inc(replica=replica_id, method=method_name)
            tracing_lib.add_current_event(
                "router.route", replica=replica_id, method=method_name
            )
            endpoint = self._endpoint(replica_id)
            try:
                response = getattr(endpoint, method_name)(request)
            except BaseException as e:
                if _is_transport_failure(e):
                    self._note_failure(replica_id, e)
                raise
            self._note_success(replica_id)
            return response

        return call

    def _list_studies(self, request):
        if self._barrier is not None:
            # The fan-out honors the topology-transition barrier like any
            # routed RPC: listing mid-replay would observe a half-restored
            # successor (or raise on a corpse the sweep is about to
            # account for) when waiting out the transition returns a
            # complete listing.
            self._barrier()
        live = self.router.live_replicas()
        with self._lock:
            failed_over = set(self._failed_over)
        unaccounted = [
            rid
            for rid in self.router.replica_ids
            if rid not in live and rid not in failed_over
        ]
        if unaccounted:
            # A silent subset would read as "those studies don't exist";
            # fail transport-shaped instead so the caller's retry machinery
            # re-lists once failover has restored the studies (or surfaces
            # a loud error when nothing will).
            raise ConnectionError(
                "ListStudies would be partial: replica(s) "
                f"{', '.join(unaccounted)} are down and their studies have "
                "not been failed over to successors."
            )
        response = vizier_service_pb2.ListStudiesResponse()
        for replica_id in live:
            self._requests.inc(replica=replica_id, method="ListStudies")
            endpoint = self._endpoint(replica_id)
            try:
                part = endpoint.ListStudies(request)
            except BaseException as e:
                if _is_transport_failure(e):
                    self._note_failure(replica_id, e)
                raise
            response.studies.extend(part.studies)
        return response

    # -- best-effort accounting hooks (duck-typed like the servicer) -------

    def record_client_retry(self, amount: int = 1) -> None:
        """Forwards client retry accounting to the tier's stats sink."""
        if self._retry_sink is not None:
            try:
                self._retry_sink(amount)
            except Exception:
                pass

    def stats(self) -> Dict[str, Any]:
        """Router + per-replica request/failure counters (JSON-ready)."""
        per_replica: Dict[str, Dict[str, float]] = {}
        with self._lock:
            failed_over = set(self._failed_over)
        for rid in self.router.replica_ids:
            requests = sum(
                self._requests.value(replica=rid, method=m)
                for m in list(ROUTING_KEYS) + ["ListStudies"]
            )
            per_replica[rid] = {
                "requests": requests,
                "failures": self._failures.value(replica=rid),
                "state": self.router.snapshot()[rid],
                "failed_over": rid in failed_over,
            }
        return {"replicas": per_replica}
