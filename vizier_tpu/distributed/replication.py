"""Shared-nothing WAL replication: streamed standby logs + recovery plans.

The PR 6 failover replayed a dead replica's WAL *files* into its
successors — which silently assumed every replica can read every other
replica's disk. A real multi-host fleet has no shared filesystem, so this
module makes durability shared-nothing: every ``PersistentDataStore``
append is **asynchronously streamed** to the owning study's K rendezvous
successors, which keep **per-origin standby logs**; failover replays from
the standby logs and falls back to the origin's local WAL only when that
WAL is present *and longer* (longest-valid-prefix wins, compared by the
mutation sequence numbers ``wal.py`` assigns).

Pieces, origin side → successor side:

- :class:`ReplicationStreamer` — one per live replica. The store's
  ``on_append`` hook drops ``(seq, opcode, payload)`` into a bounded
  queue (non-blocking: the write path never waits on replication); a
  worker thread drains in batches, routes each record to the study's
  successors (``StudyRouter.successors`` — liveness-blind, so the sets
  are stable), and delivers with ack tracking. A successor whose ack
  does not match what was sent (it restarted, its disk was wiped, the
  queue overflowed) is **resynced** with a *baseline*: an atomic
  ``(seq, compacted records)`` export of the origin store filtered to
  the studies that successor stands by for, which replaces its standby
  log for this origin.
- :class:`StandbyStore` — one per replica, holding the standby logs of
  every origin it is a successor for, disk-backed under
  ``<wal_dir>/standby/<origin>/`` (same crash tolerance as the WAL:
  framed records, longest-valid-prefix reads) or in-memory when the
  tier runs without persistence. Appends are **epoch-fenced**: a revive
  bumps the origin's epoch and fences all standby stores, so a stale
  streamer (an RPC that outlived its own replica's revive) cannot
  scribble over the handed-back state.
- :func:`plan_recovery` — the pure recovery-source selector: given the
  origin's local WAL records (possibly truncated by corruption
  quarantine, possibly missing entirely) and every live standby log,
  choose per study the source whose records reach the highest sequence
  number. Local wins only when strictly longer; ties go to the standby
  (the shared-nothing posture: prefer the source that exists on a live
  host).

Lock order: the streamer's queue condition is a leaf under
``PersistentDataStore._lock`` (the ``on_append`` hook only appends to a
deque and notifies); the worker thread never holds it while delivering or
exporting a baseline. ``StandbyStore._lock`` is a leaf guarding its maps
and file handles. Nothing here calls back into router/replica locks while
holding either.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.observability import flight_recorder as recorder_lib

_logger = logging.getLogger(__name__)

# Standby record framing: [u32 payload len][u32 crc][u64 seq][u8 opcode]
# [payload]; crc covers seq+opcode+payload. Opcode 0 is the epoch marker
# (seq field = epoch, empty payload) written as the first record of each
# standby log generation; data records use the wal.py opcodes (1..11).
_HEADER = struct.Struct("<IIQB")
EPOCH_MARKER = 0

STANDBY_DIR = "standby"
STANDBY_LOG = "standby.log"

Record = Tuple[int, int, bytes]  # (seq, opcode, payload)


def _frame(seq: int, opcode: int, payload: bytes) -> bytes:
    body = _HEADER.pack(
        len(payload),
        zlib.crc32(struct.pack("<QB", seq, opcode) + payload),
        seq,
        opcode,
    )
    return body + payload


def _read_standby_file(path: str) -> List[Record]:
    """Valid-prefix read of one standby log (damage drops the suffix —
    standby logs are redundancy; a shorter one just loses the seq race)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return []
    records: List[Record] = []
    offset = 0
    while offset + _HEADER.size <= len(data):
        length, crc, seq, opcode = _HEADER.unpack_from(data, offset)
        start = offset + _HEADER.size
        end = start + length
        if end > len(data):
            break
        payload = data[start:end]
        if zlib.crc32(struct.pack("<QB", seq, opcode) + payload) != crc:
            break
        records.append((seq, opcode, payload))
        offset = end
    return records


class _OriginStandby:
    """One origin's standby log at one successor."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.epoch = 0
        self.records: List[Record] = []
        self.last_seq = 0
        # The seq of the last baseline this log was reset to. A baseline
        # is a statement about the origin's WHOLE (successor-filtered)
        # state: a study ABSENT from this log with baseline_seq > its
        # seq elsewhere was absent from the origin at that point — which
        # is how a stale local WAL prefix (e.g. one whose handback
        # tombstone fell into a quarantined corrupt suffix) loses to the
        # standby's authoritative absence.
        self.baseline_seq = 0
        self._file = None
        if path is not None and os.path.exists(path):
            loaded = _read_standby_file(path)
            for seq, opcode, payload in loaded:
                if opcode == EPOCH_MARKER:
                    self.epoch = seq
                    if len(payload) == 8:
                        self.baseline_seq = int(
                            struct.unpack("<Q", payload)[0]
                        )
                else:
                    self.records.append((seq, opcode, payload))
                    self.last_seq = max(self.last_seq, seq)

    def _open(self, truncate: bool):
        if self.path is None:
            return None
        if self._file is None or truncate:
            if self._file is not None:
                self._file.close()
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._file = open(self.path, "wb" if truncate else "ab")
        return self._file

    def reset(self, epoch: int, baseline_seq: int = 0) -> None:
        self.epoch = epoch
        self.records = []
        self.last_seq = baseline_seq
        self.baseline_seq = baseline_seq
        f = self._open(truncate=True)
        if f is not None:
            f.write(
                _frame(
                    epoch, EPOCH_MARKER, struct.pack("<Q", baseline_seq)
                )
            )
            f.flush()

    def append(self, records: Sequence[Record]) -> None:
        f = self._open(truncate=False)
        for seq, opcode, payload in records:
            self.records.append((seq, opcode, payload))
            self.last_seq = max(self.last_seq, seq)
            if f is not None:
                f.write(_frame(seq, opcode, payload))
        if f is not None:
            f.flush()

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except Exception:
                pass
            self._file = None


class StandbyStore:
    """A replica's receiver side: per-origin, epoch-fenced standby logs."""

    def __init__(self, directory: Optional[str] = None):
        self._directory = (
            os.path.join(directory, STANDBY_DIR) if directory else None
        )
        self._lock = threading.Lock()  # leaf: maps + file handles only
        self._origins: Dict[str, _OriginStandby] = {}
        if self._directory is not None and os.path.isdir(self._directory):
            for origin in sorted(os.listdir(self._directory)):
                path = os.path.join(self._directory, origin, STANDBY_LOG)
                if os.path.exists(path):
                    self._origins[origin] = _OriginStandby(path)

    def _origin(self, origin: str) -> _OriginStandby:
        standby = self._origins.get(origin)
        if standby is None:
            path = None
            if self._directory is not None:
                path = os.path.join(self._directory, origin, STANDBY_LOG)
            standby = self._origins[origin] = _OriginStandby(path)
        return standby

    def append_batch(
        self,
        origin: str,
        epoch: int,
        records: Sequence[Record],
        *,
        reset: bool = False,
        baseline_seq: int = 0,
    ) -> Tuple[bool, int]:
        """Appends one delivered batch; ``reset=True`` replaces the log
        (a baseline taken at ``baseline_seq``). Returns ``(accepted,
        value)`` — on acceptance the value is the log's last sequence
        number (the ack the streamer verifies); on a stale-epoch
        rejection it is the fenced epoch.
        """
        with self._lock:
            standby = self._origin(origin)
            if epoch < standby.epoch:
                return False, standby.epoch  # fenced: stale origin epoch
            if epoch > standby.epoch and not reset:
                # A new epoch must introduce itself with a baseline; a
                # bare append across an epoch boundary means this store
                # missed the handoff.
                return False, standby.epoch
            if reset:
                standby.reset(epoch, baseline_seq)
            else:
                # Replay applies records in log order, so a record OLDER
                # than what the log already holds must never be appended
                # behind it (it would regress state on replay). Baselines
                # are exempt: all their records share the baseline seq.
                records = [r for r in records if r[0] > standby.last_seq]
            standby.append(records)
            return True, standby.last_seq

    def fence(self, origin: str, epoch: int) -> None:
        """Raises the origin's known epoch WITHOUT data (revive cutover):
        deliveries from streamers of earlier epochs are rejected from now
        on, even before the new streamer's first baseline arrives."""
        with self._lock:
            standby = self._origin(origin)
            if epoch > standby.epoch:
                standby.epoch = epoch

    def last_seq(self, origin: str) -> int:
        with self._lock:
            standby = self._origins.get(origin)
            return standby.last_seq if standby is not None else 0

    def epoch(self, origin: str) -> int:
        with self._lock:
            standby = self._origins.get(origin)
            return standby.epoch if standby is not None else 0

    def records_for(self, origin: str) -> List[Record]:
        with self._lock:
            standby = self._origins.get(origin)
            return list(standby.records) if standby is not None else []

    def view_for(self, origin: str) -> Optional["StandbyView"]:
        """The recovery-plan input: records plus the baseline seq (the
        'absent studies were absent as of here' claim)."""
        with self._lock:
            standby = self._origins.get(origin)
            if standby is None:
                return None
            return StandbyView(
                baseline_seq=standby.baseline_seq,
                records=list(standby.records),
            )

    def depths(self) -> Dict[str, int]:
        """origin -> standby record count (the standby-depth gauge)."""
        with self._lock:
            return {
                origin: len(standby.records)
                for origin, standby in sorted(self._origins.items())
            }

    def close(self) -> None:
        with self._lock:
            for standby in self._origins.values():
                standby.close()


# -- origin-side streaming ---------------------------------------------------


@dataclasses.dataclass
class StandbyView:
    """One holder's standby log for an origin, as recovery-plan input.

    ``baseline_seq`` is the log's absence claim: a study with no records
    here was absent from the origin's (successor-filtered) state at that
    sequence number.
    """

    baseline_seq: int
    records: List[Record]


@dataclasses.dataclass
class _SuccessorState:
    """Worker-thread-private per-successor tracking (no lock needed: only
    the worker reads or writes it)."""

    synced: bool = False
    acked_seq: int = 0
    # Why the NEXT resync of this successor is needed — kept alongside the
    # unsynced flag so the ``vizier_replication_resyncs{reason}`` counter
    # attributes each baseline to what actually broke the stream:
    # "initial" (first contact), "overflow" (queue drop), "transport"
    # (delivery failed / link died), "epoch_behind" (receiver restarted
    # with an old epoch), "ack_regressed" (standby log wiped underneath
    # us), "requested" (a revive's proactive re-baseline).
    reason: str = "initial"

    def desync(self, reason: str) -> None:
        self.synced = False
        self.reason = reason


class StreamerFencedError(RuntimeError):
    """A successor rejected this streamer's epoch: a newer generation of
    the origin exists; this streamer must stop streaming."""


class ReplicationStreamer:
    """Streams one origin's WAL appends to per-study rendezvous successors.

    ``submit`` is the store's ``on_append`` hook: non-blocking, called
    under the store lock so the queue order equals the log order. On
    queue overflow records are DROPPED and every successor is marked
    unsynced — the next drain re-baselines them from the store itself, so
    overflow costs a resync, never correctness.
    """

    def __init__(
        self,
        origin: str,
        epoch: int,
        *,
        successors_fn: Callable[[str], Sequence[str]],
        deliver_fn: Callable[
            [str, str, int, Sequence[Record], bool, int],
            Optional[Tuple[bool, int]],
        ],
        baseline_fn: Callable[[str], Tuple[int, List[Record]]],
        queue_size: int = 4096,
        batch_max: int = 64,
        repair_interval_secs: float = 0.5,
        on_lag: Optional[Callable[[str, int], None]] = None,
        on_resync: Optional[Callable[[str, str, str], None]] = None,
    ):
        self.origin = origin
        self.epoch = epoch
        self._successors_fn = successors_fn
        self._deliver_fn = deliver_fn
        self._baseline_fn = baseline_fn
        self._queue_size = max(1, queue_size)
        self._batch_max = max(1, batch_max)
        # Self-healing cadence: a successor left unsynced by a failed
        # delivery (the link died, the peer restarted) is re-baselined on
        # this throttle even with NO new traffic — quiet studies must not
        # stay unprotected until the next organic mutation.
        self._repair_interval = max(0.05, repair_interval_secs)
        self._next_repair = 0.0
        self._on_lag = on_lag
        # (origin, successor, reason) observer — the plane's labeled
        # ``vizier_replication_resyncs`` counter.
        self._on_resync = on_resync
        self._cond = threading.Condition()
        self._queue: "collections.deque[Record]" = collections.deque()
        # successor -> reason of the queued proactive resync.
        self._pending_resync: Dict[str, str] = {}
        self._overflowed = False
        self._closed = False
        self._fenced = False
        self._inflight = 0  # records drained but not yet delivered
        self._submitted_seq = 0
        self._states: Dict[str, _SuccessorState] = {}
        self.resyncs = 0
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, name=f"vizier-wal-repl-{origin}", daemon=True
        )
        self._thread.start()

    # -- producer side ------------------------------------------------------

    def submit(self, seq: int, opcode: int, payload: bytes) -> None:
        """The store's post-append hook. Never blocks, never raises."""
        with self._cond:
            if self._closed or self._fenced:
                return
            self._submitted_seq = max(self._submitted_seq, seq)
            if len(self._queue) >= self._queue_size:
                # Dropping breaks per-successor continuity; the worker
                # re-baselines everyone on the next drain.
                self._overflowed = True
                self.dropped += 1
                return
            self._queue.append((seq, opcode, payload))
            self._cond.notify()

    def request_resync(self, successor: str, reason: str = "requested") -> None:
        """Queues a proactive baseline for ``successor`` (a revived
        replica's standby logs are stale until someone re-baselines them;
        waiting for the next organic record would leave a window where
        the origin's death loses the quiet studies)."""
        with self._cond:
            if self._closed or self._fenced:
                return
            self._pending_resync[successor] = reason
            self._cond.notify()

    def flush(self, timeout_secs: float = 10.0) -> bool:
        """Blocks until the queue has fully drained AND delivered (or the
        timeout passes). Failover calls this on the dead origin's streamer
        so everything its in-flight RPCs appended is on the successors
        before the standby logs are read."""
        import time

        deadline = time.monotonic() + timeout_secs
        with self._cond:
            self._cond.notify_all()
            while self._queue or self._inflight or self._pending_resync:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.05))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)

    @property
    def fenced(self) -> bool:
        with self._cond:
            return self._fenced

    def lag(self) -> int:
        """Records submitted but not yet acked by the slowest successor."""
        with self._cond:
            submitted = self._submitted_seq
            states = [s for s in self._states.values() if s.synced]
        if not states:
            return 0
        return max(0, submitted - min(s.acked_seq for s in states))

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        # First action: baseline every successor that currently stands by
        # for one of the origin's studies, so a restart-warm replica is
        # protected before its first new mutation.
        try:
            self._initial_sync()
        except StreamerFencedError:
            with self._cond:
                self._fenced = True
                self._queue.clear()
                self._cond.notify_all()
            return
        except Exception as e:  # pragma: no cover - defensive
            _logger.warning("Initial replication sync failed: %s", e)
        while True:
            with self._cond:
                while (
                    not self._queue
                    and not self._pending_resync
                    and not self._closed
                ):
                    if (
                        self._has_unsynced()
                        and time.monotonic() >= self._next_repair
                    ):
                        break  # idle repair pass: re-baseline dead links
                    self._cond.wait(0.2)
                if self._closed and not self._queue:
                    return
                batch: List[Record] = []
                while self._queue and len(batch) < self._batch_max:
                    batch.append(self._queue.popleft())
                resyncs = sorted(self._pending_resync.items())
                self._pending_resync.clear()
                overflowed, self._overflowed = self._overflowed, False
                self._inflight = len(batch) + len(resyncs)
            try:
                for successor, reason in resyncs:
                    self._state(successor).desync(reason)
                    self._resync(successor)
                self._deliver_batch(batch, overflowed)
                self._repair_unsynced()
            except StreamerFencedError:
                with self._cond:
                    self._fenced = True
                    self._queue.clear()
                    self._inflight = 0
                    self._cond.notify_all()
                return
            except Exception as e:  # pragma: no cover - defensive
                _logger.warning(
                    "Replication delivery from %s failed: %s", self.origin, e
                )
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()

    def _initial_sync(self) -> None:
        _seq, records = self._baseline_all()
        targets: Dict[str, None] = {}
        for seq, opcode, payload in records:
            for successor in self._successors_fn(
                wal_lib.study_key_of(opcode, payload)
            ):
                targets[successor] = None
        for successor in targets:
            self._resync(successor)

    def _baseline_all(self) -> Tuple[int, List[Record]]:
        seq, flat = self._baseline_fn("")
        return seq, flat

    def _state(self, successor: str) -> _SuccessorState:
        state = self._states.get(successor)
        if state is None:
            state = self._states[successor] = _SuccessorState()
        return state

    def _has_unsynced(self) -> bool:
        """Worker-private: any known successor currently off-stream?"""
        return any(not state.synced for state in self._states.values())

    def _repair_unsynced(self) -> None:
        """Throttled self-healing: retry the baseline of every unsynced
        successor. Called from the worker after each cycle (and from the
        idle wakeup), so a healed link or restarted peer is re-protected
        within ``repair_interval`` even if no new mutation ever arrives.
        Failed attempts are cheap — the wire link's dead-peer cooldown
        short-circuits the connect wait."""
        if not self._has_unsynced():
            return
        now = time.monotonic()
        if now < self._next_repair:
            return
        self._next_repair = now + self._repair_interval
        for successor in sorted(self._states):
            if not self._states[successor].synced:
                self._resync(successor)

    def _resync(self, successor: str) -> bool:
        """Replaces a successor's standby log with a fresh baseline."""
        state = self._state(successor)
        reason = state.reason
        seq, records = self._baseline_fn(successor)
        response = self._deliver_fn(
            successor, self.origin, self.epoch, records, True, seq
        )
        if response is None:  # successor unreachable (dead): retry later
            state.desync("transport")
            return False
        accepted, value = response
        if not accepted:
            # A reset delivery is only refused when the standby store has
            # been fenced to a NEWER origin epoch: this streamer is a
            # stale generation and must stop.
            raise StreamerFencedError(
                f"standby epoch {value} fences out streamer epoch "
                f"{self.epoch} for {self.origin}"
            )
        state.synced = True
        state.acked_seq = value
        self.resyncs += 1
        if self._on_resync is not None:
            try:
                self._on_resync(self.origin, successor, reason)
            except Exception:  # accounting must not break the stream
                pass
        recorder_lib.get_recorder().record(
            None,
            "replication_resync",
            origin=self.origin,
            successor=successor,
            baseline_seq=seq,
            records=len(records),
            reason=reason,
        )
        return True

    def _deliver_batch(self, batch: List[Record], overflowed: bool) -> None:
        if overflowed:
            for state in self._states.values():
                state.desync("overflow")
        per_successor: Dict[str, List[Record]] = {}
        for seq, opcode, payload in batch:
            study_key = wal_lib.study_key_of(opcode, payload)
            for successor in self._successors_fn(study_key):
                per_successor.setdefault(successor, []).append(
                    (seq, opcode, payload)
                )
        for successor, records in sorted(per_successor.items()):
            state = self._state(successor)
            if not state.synced:
                if not self._resync(successor):
                    continue  # unreachable; baseline again when it returns
                # The baseline already contains this batch's records (it
                # exported the live store, which applied them before the
                # hook fired): skip them rather than append stale records
                # behind newer baseline state.
                continue
            # Never send records at-or-below the successor's ack: after a
            # resync, queued records older than the baseline are already
            # folded into it.
            records = [r for r in records if r[0] > state.acked_seq]
            if not records:
                continue
            response = self._deliver_fn(
                successor, self.origin, self.epoch, records, False, 0
            )
            if response is None:
                state.desync("transport")
                continue
            accepted, value = response
            if not accepted:
                if value > self.epoch:
                    # Fenced: a newer generation of this origin exists.
                    raise StreamerFencedError(
                        f"standby epoch {value} fences out streamer epoch "
                        f"{self.epoch} for {self.origin}"
                    )
                # The receiver is BEHIND (it restarted with an old epoch
                # on disk): a baseline introduces the current epoch.
                state.desync("epoch_behind")
                continue
            state.acked_seq = value
            expected = records[-1][0]
            if value < expected:
                # The standby log is behind what we just sent: it was
                # wiped/recreated underneath us. Re-baseline.
                state.desync("ack_regressed")
        if self._on_lag is not None:
            try:
                self._on_lag(self.origin, self.lag())
            except Exception:
                pass


# -- recovery-source selection -----------------------------------------------


@dataclasses.dataclass
class StudyRecovery:
    """One study's chosen recovery source in a failover plan."""

    study: str
    source: str  # "standby" | "local"
    seq: int
    records: List[Tuple[int, bytes]]  # (opcode, payload), replay order


@dataclasses.dataclass
class RecoveryPlan:
    origin: str
    studies: List[StudyRecovery]
    local_torn: bool
    max_seq: int

    def source_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.studies:
            out[s.source] = out.get(s.source, 0) + 1
        return out


def plan_recovery(
    origin: str,
    local_records: Sequence[Tuple[int, int, bytes]],
    local_torn: bool,
    standby_views: Iterable[StandbyView],
    *,
    min_seq: int = 0,
    successors_fn: Optional[Callable[[str], Sequence[str]]] = None,
    holders: Optional[Sequence[str]] = None,
) -> RecoveryPlan:
    """Chooses, per study, the longest-valid-prefix recovery source.

    ``local_records`` are the dead origin's own WAL records (with
    sequence numbers; empty when its disk is gone — the shared-nothing
    case). ``standby_views`` are every live replica's standby logs for
    the origin, each carrying its ``baseline_seq``. Per study, the
    source whose knowledge reaches the highest sequence number wins; the
    local WAL wins only when STRICTLY longer (ties go to the standby —
    prefer the copy that lives on a live host).

    Three kinds of standby knowledge compete with the local records:
    explicit records (replay them), a history ending in DELETE_STUDY
    (the study is gone), and **absence at the baseline** — a study with
    no records in a log whose ``baseline_seq`` is higher than the local
    seq was absent from the origin's state at that point, which outranks
    a stale local prefix. The absence case is what makes a quarantined
    local WAL safe: when a handback tombstone fell into the corrupt
    suffix, the local prefix still shows the moved-away study as live,
    and replaying it would clobber the real owner's current copy.
    Absence claims only count from holders in the study's successor set
    (``successors_fn`` + ``holders``, when provided): other holders
    never receive the study's records, so their logs say nothing about
    it.

    Net-deleted studies are skipped on a full replay: the origin has
    nothing live to contribute, and a genuine user deletion loses
    nothing (the origin owned the study when it was deleted, so no other
    replica holds a live copy). ``min_seq`` drops records at-or-below an
    already-replayed watermark (the late-write catch-up path), so only
    the tail is re-applied — catch-up tails keep their deletes, which
    are real client RPCs that raced the failover.
    """
    views = list(standby_views)
    holder_ids = list(holders) if holders is not None else [None] * len(views)
    local_by_study = wal_lib.group_by_study(local_records)
    standby_by_study: Dict[str, List[Tuple[int, int, bytes]]] = {}
    view_studies: List[set] = []
    for view in views:
        grouped = wal_lib.group_by_study(view.records)
        view_studies.append(set(grouped))
        for study, records in grouped.items():
            best = standby_by_study.get(study)
            if best is None or (
                records and (not best or records[-1][0] > best[-1][0])
            ):
                standby_by_study[study] = list(records)

    def absence_seq(study: str) -> int:
        """The highest baseline seq among holders that WOULD hold the
        study's records yet have none: the origin's state at that seq did
        not contain the study."""
        eligible = None
        if successors_fn is not None:
            eligible = set(successors_fn(study))
        best = 0
        for view, holder, present in zip(views, holder_ids, view_studies):
            if eligible is not None and holder is not None:
                if holder not in eligible:
                    continue
            if study in present:
                continue
            best = max(best, view.baseline_seq)
        return best

    studies: List[StudyRecovery] = []
    max_seq = 0
    for study in sorted(set(local_by_study) | set(standby_by_study)):
        local = local_by_study.get(study, [])
        standby = standby_by_study.get(study, [])
        local_seq = local[-1][0] if local else 0
        standby_seq = standby[-1][0] if standby else 0
        if local and local_seq > standby_seq:
            source, chosen, seq = "local", local, local_seq
        elif standby:
            source, chosen, seq = "standby", standby, standby_seq
        else:
            source, chosen, seq = "local", local, local_seq
        if min_seq == 0 and absence_seq(study) >= seq:
            # A baseline taken at-or-after the chosen source's horizon
            # did not contain the study: it is absent from the origin's
            # authoritative state (handed back or deleted), and replaying
            # the stale copy would clobber the live owner's data.
            max_seq = max(max_seq, absence_seq(study))
            continue
        if (
            min_seq == 0
            and chosen
            and chosen[-1][1] == wal_lib.DELETE_STUDY
        ):
            max_seq = max(max_seq, seq)
            continue  # net-deleted on the origin: nothing live to restore
        tail = [
            (opcode, payload)
            for rec_seq, opcode, payload in chosen
            if rec_seq > min_seq
        ]
        if min_seq > 0 and not tail:
            continue  # catch-up pass: nothing new for this study
        max_seq = max(max_seq, seq)
        studies.append(StudyRecovery(study, source, seq, tail))
    return RecoveryPlan(origin, studies, local_torn, max_seq)


# -- the fleet-facing plane --------------------------------------------------


class AppendSink:
    """The typed ``PersistentDataStore.on_append`` target: one origin's
    handle into the replication plane.

    A class (not a closure) on purpose: the lock-order pass's static
    type resolution follows ctor/attribute annotations, so the
    store-lock → plane-lock → streamer-condition acquisition chain the
    hook creates is part of the static graph the runtime cross-check
    verifies against.
    """

    def __init__(self, origin: str, plane: "ReplicationPlane"):
        self._origin = origin
        self._plane: "ReplicationPlane" = plane

    def submit(self, seq: int, opcode: int, payload: bytes) -> None:
        self._plane.submit(self._origin, seq, opcode, payload)


class ReplicationPlane:
    """Owns the streamers + standby stores of one in-process tier.

    The ``ReplicaManager`` calls in with replica-shaped accessors; this
    class keeps all replication state and policy in one place so the
    manager's failover/revive code reads as topology operations.
    """

    def __init__(
        self,
        *,
        factor: int,
        queue_size: int,
        batch_max: int,
        router,
        get_replica: Callable[[str], Optional[object]],
        registry=None,
    ):
        self.factor = max(1, factor)
        self._queue_size = queue_size
        self._batch_max = batch_max
        self._router = router
        self._get_replica = get_replica
        self._streamers: Dict[str, ReplicationStreamer] = {}
        self._epochs: Dict[str, int] = {}
        self._lock = threading.Lock()  # leaf: streamer/epoch maps only
        self._lag_gauge = None
        self._depth_gauge = None
        self._resync_counter = None
        if registry is not None:
            self._lag_gauge = registry.gauge(
                "vizier_replication_lag",
                help="Appended-but-unacked standby records per origin.",
            )
            self._depth_gauge = registry.gauge(
                "vizier_replication_standby_depth",
                help="Standby-log records held, per origin and holder.",
            )
            self._resync_counter = registry.counter(
                "vizier_replication_resyncs",
                help="Standby-log re-baselines, per origin and reason "
                "(initial/overflow/transport/epoch_behind/ack_regressed/"
                "requested).",
            )

    # -- hooks the manager wires --------------------------------------------

    def make_standby(self, wal_dir: Optional[str]) -> StandbyStore:
        return StandbyStore(wal_dir)

    def submit(self, origin: str, seq: int, opcode: int, payload: bytes) -> None:
        """The ``PersistentDataStore.on_append`` feed: resolves the
        origin's CURRENT streamer per call, so a revive's fresh streamer
        takes over without rebuilding the datastore hook. Non-blocking."""
        with self._lock:
            streamer = self._streamers.get(origin)
        if streamer is not None:
            streamer.submit(seq, opcode, payload)

    def successors_for(self, study_key: str, origin: str) -> List[str]:
        return self._router.successors(study_key, origin, self.factor)

    # -- streamer lifecycle --------------------------------------------------

    def start_streamer(self, origin: str) -> ReplicationStreamer:
        """Builds (or rebuilds, bumping the epoch) the origin's streamer."""
        with self._lock:
            epoch = self._epochs.get(origin, 0) + 1
            self._epochs[origin] = epoch
            old = self._streamers.pop(origin, None)
        if old is not None:
            old.close()
        streamer = ReplicationStreamer(
            origin,
            epoch,
            successors_fn=lambda key: self.successors_for(key, origin),
            deliver_fn=self._deliver,
            baseline_fn=lambda successor: self._baseline(origin, successor),
            queue_size=self._queue_size,
            batch_max=self._batch_max,
            on_lag=self._record_lag,
            on_resync=self._record_resync,
        )
        with self._lock:
            self._streamers[origin] = streamer
        return streamer

    def epoch_of(self, origin: str) -> int:
        with self._lock:
            return self._epochs.get(origin, 0)

    def flush_origin(self, origin: str, timeout_secs: float = 10.0) -> bool:
        with self._lock:
            streamer = self._streamers.get(origin)
        if streamer is None:
            return True
        return streamer.flush(timeout_secs)

    def resync_into(self, successor: str) -> None:
        """Asks every OTHER origin's streamer to re-baseline ``successor``
        (called after a revive: the returning replica's standby logs are
        stale for every origin that mutated while it was down — or gone
        entirely when its disk was lost)."""
        with self._lock:
            streamers = dict(self._streamers)
        for origin, streamer in streamers.items():
            if origin != successor:
                streamer.request_resync(successor)

    def close_origin(self, origin: str) -> None:
        with self._lock:
            streamer = self._streamers.pop(origin, None)
        if streamer is not None:
            streamer.close()

    def close(self) -> None:
        with self._lock:
            streamers = list(self._streamers.values())
            self._streamers.clear()
        for streamer in streamers:
            streamer.close()

    # -- internals -----------------------------------------------------------

    def _deliver(
        self,
        successor_id: str,
        origin: str,
        epoch: int,
        records: Sequence[Record],
        reset: bool,
        baseline_seq: int,
    ) -> Optional[Tuple[bool, int]]:
        replica = self._get_replica(successor_id)
        standby = getattr(replica, "standby", None)
        if replica is None or standby is None or not replica.alive:
            return None  # unreachable: the streamer resyncs on return
        return standby.append_batch(
            origin, epoch, records, reset=reset, baseline_seq=baseline_seq
        )

    def _baseline(
        self, origin: str, successor_id: str
    ) -> Tuple[int, List[Record]]:
        """An atomic baseline of the origin store: all records when
        ``successor_id`` is empty (the initial-sync probe), else filtered
        to the studies that successor stands by for."""
        replica = self._get_replica(origin)
        datastore = getattr(replica, "datastore", None)
        export = getattr(datastore, "export_with_seq", None)
        if export is None:
            return 0, []
        seq, records = export()
        out: List[Record] = []
        for opcode, payload in records:
            if successor_id and successor_id not in self.successors_for(
                wal_lib.study_key_of(opcode, payload), origin
            ):
                continue
            out.append((seq, opcode, payload))
        return seq, out

    def _record_lag(self, origin: str, lag: int) -> None:
        if self._lag_gauge is not None:
            self._lag_gauge.set(float(lag), origin=origin)

    def _record_resync(self, origin: str, successor: str, reason: str) -> None:
        del successor  # label cardinality: (origin, reason) is enough
        if self._resync_counter is not None:
            self._resync_counter.inc(origin=origin, reason=reason)

    def streamer_stats(self) -> Dict[str, Dict[str, int]]:
        """origin -> {epoch, lag, resyncs, dropped} (JSON-ready)."""
        with self._lock:
            streamers = dict(self._streamers)
        return {
            origin: {
                "epoch": streamer.epoch,
                "lag": streamer.lag(),
                "resyncs": streamer.resyncs,
                "dropped": streamer.dropped,
            }
            for origin, streamer in sorted(streamers.items())
        }

    def record_depths(self) -> Dict[str, Dict[str, int]]:
        """holder -> origin -> standby depth (also refreshes the gauge)."""
        out: Dict[str, Dict[str, int]] = {}
        for rid in self._router.replica_ids:
            replica = self._get_replica(rid)
            standby = getattr(replica, "standby", None)
            if standby is None:
                continue
            depths = standby.depths()
            if depths:
                out[rid] = depths
            if self._depth_gauge is not None:
                for origin, depth in depths.items():
                    self._depth_gauge.set(
                        float(depth), origin=origin, holder=rid
                    )
        return out
