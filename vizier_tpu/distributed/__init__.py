"""Horizontally sharded service tier.

One ``VizierService`` replica serves one shard of the study population;
studies are assigned to replicas by rendezvous hashing of their resource
names (``routing.StudyRouter``), clients reach the owning replica through a
drop-in stub wrapper (``router_stub.RoutedVizierStub`` — ``VizierClient``
code is unchanged), each replica's RAM datastore persists through a
snapshot + write-ahead log (``wal.PersistentDataStore``) so replicas
restart warm, WAL appends stream to each study's rendezvous successors'
standby logs (``replication.py``) so failover needs **no shared
filesystem**, and ``replica_manager.ReplicaManager`` health-checks the
fleet and fails a dead replica's studies over to their rendezvous
successors — the reliability layer's retries absorb the transition.

Deployment topologies (docs/guides/running_the_service.md, "Sharded
deployment"):

- **in-process** — N ``VizierServicer`` replicas behind one
  ``ReplicaManager``, all feeding ONE shared Pythia (designer cache,
  coalescer, cross-study batch executor). No transport hop: the router IS
  the channel. This is the tier ``tools/service_throughput.py --replicas``
  measures and ``tools/chaos_ab.py --distributed`` kills replicas in.
- **subprocess / multi-host** — N ``DefaultVizierServer`` processes
  (``python -m vizier_tpu.distributed.replica_main``), routed over real
  gRPC channels; each process hosts its own Pythia, persists epoch-fenced
  standby logs for its rendezvous predecessors on its own disk, and
  streams its WAL appends to successors over the ``ReplicationService``
  gRPC surface (``replication_service.py``).
  ``subprocess_fleet.SubprocessReplicaManager`` spawns and manages the
  fleet with lease-based failure detection (heartbeat RPCs; death on
  lease expiry), fence-first failover from standby logs over the wire,
  and partition tolerance (``testing.netchaos``): a partitioned-away
  replica that comes back finds its stale appends rejected by fenced
  standby stores.

``ShardedDataStore`` is the datastore-granularity analogue: one service
process partitioning its studies across per-shard stores through the same
rendezvous hash.
"""

from vizier_tpu.distributed.config import DistributedConfig
from vizier_tpu.distributed.replica_manager import ReplicaManager
from vizier_tpu.distributed.replication import (
    ReplicationStreamer,
    StandbyStore,
)
from vizier_tpu.distributed.replication_service import (
    GrpcReplicationLink,
    ReplicaReplicationHost,
    ReplicationServicer,
)
from vizier_tpu.distributed.router_stub import RoutedVizierStub
from vizier_tpu.distributed.routing import StudyRouter
from vizier_tpu.distributed.sharded_datastore import ShardedDataStore
from vizier_tpu.distributed.subprocess_fleet import SubprocessReplicaManager
from vizier_tpu.distributed.wal import PersistentDataStore, WriteAheadLog

__all__ = [
    "DistributedConfig",
    "GrpcReplicationLink",
    "PersistentDataStore",
    "ReplicaManager",
    "ReplicaReplicationHost",
    "ReplicationServicer",
    "ReplicationStreamer",
    "RoutedVizierStub",
    "ShardedDataStore",
    "StandbyStore",
    "StudyRouter",
    "SubprocessReplicaManager",
    "WriteAheadLog",
]
