"""Cross-process replication: the gRPC surface over the standby machinery.

PR 13 built shared-nothing durability — streamed standby logs, epoch
fencing, recovery-source selection — but delivered it in-process: the
``ReplicationPlane``'s ``deliver_fn`` was a Python method call into a
sibling replica's ``StandbyStore``. This module puts the same protocol on
the wire so subprocess replicas (``replica_main``) replicate to each
other over real gRPC:

- :class:`ReplicationServicer` — the server body ``replica_main`` hosts
  next to ``VizierService`` (method table in ``service.grpc_stubs``).
  ``DeliverAppends``/``Baseline`` are thin shims over
  ``StandbyStore.append_batch`` (the SAME epoch-fencing code path the
  in-process plane uses, so fencing semantics are proven identical on
  both transports); ``Fence`` raises an origin's epoch without data;
  ``Heartbeat`` renews the manager's lease and piggybacks the fencing/
  resync counters; ``ExportStandby``/``ExportState``/``ApplyRecords``
  are the recovery plumbing a :class:`~vizier_tpu.distributed.
  subprocess_fleet.SubprocessReplicaManager` drives failover and revive
  copy-back through; ``Resync``/``FlushStream`` poke the replica's
  origin-side streamer.
- :class:`GrpcReplicationLink` — the wire ``deliver_fn``: one more
  implementation of the streamer's delivery contract. Transport faults
  are retried with a bounded, jittered ``reliability.RetryPolicy``
  (connection loss = a reconnect-and-retry, not a stream death); on
  exhaustion the delivery returns ``None`` and the streamer re-baselines
  the successor on its next sight (``vizier_replication_resyncs
  {reason="transport"}``) — the PR 13 overflow re-baseline generalized
  to "the link died".
- :class:`ReplicaReplicationHost` — the origin side of ONE subprocess
  replica: a liveness-blind rendezvous router over the fleet's replica
  ids (every process computes the same successor sets independently), a
  baseline exporter over the replica's own datastore, and the
  ``ReplicationStreamer`` feeding the link. :class:`ProcessAppendSink`
  is its typed ``PersistentDataStore.on_append`` hook.

Lock order: the servicer's counter lock and the link's stub-cache lock
are leaves; the host's streamer condition is a leaf under the datastore
lock exactly as in the in-process plane (``ProcessAppendSink.submit``
only enqueues). Nothing here calls back into router or store locks while
holding either.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from vizier_tpu.distributed import replication as replication_lib
from vizier_tpu.distributed import routing
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.reliability import retry as retry_lib
from vizier_tpu.service.protos import replication_service_pb2 as _pb
from vizier_tpu.testing import netchaos as netchaos_lib

_logger = logging.getLogger(__name__)

Record = replication_lib.Record


def records_to_proto(records: Sequence[Record], out) -> None:
    """Appends ``(seq, opcode, payload)`` tuples to a repeated
    ``ReplicationRecord`` field."""
    for seq, opcode, payload in records:
        out.add(seq=seq, opcode=opcode, payload=payload)


def records_from_proto(field) -> List[Record]:
    return [(r.seq, r.opcode, r.payload) for r in field]


def _is_transport_failure(error: BaseException) -> bool:
    """Transport-shaped failures worth a reconnect-and-retry."""
    if isinstance(error, ConnectionError):
        return True
    try:
        import grpc
    except Exception:  # pragma: no cover - grpc is in the image
        return False
    if isinstance(error, grpc.FutureTimeoutError):
        return True
    if isinstance(error, grpc.RpcError):
        code = error.code() if hasattr(error, "code") else None
        return code in (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
        )
    return False


class ReplicationServicer:
    """The ``vizier_tpu.ReplicationService`` server body.

    Wraps one replica's receiver-side :class:`~vizier_tpu.distributed.
    replication.StandbyStore`, its datastore (for the recovery plumbing),
    and — when the replica also streams — its origin-side
    :class:`ReplicaReplicationHost`. Methods take ``(request, context)``
    so they serve both through ``grpc_stubs.add_replication_servicer_to_
    server`` and in-process (context ``None``).
    """

    def __init__(
        self,
        replica_id: str,
        standby: replication_lib.StandbyStore,
        *,
        datastore=None,
        host: Optional["ReplicaReplicationHost"] = None,
    ):
        self.replica_id = replica_id
        self._standby = standby
        self._datastore = datastore
        self._host = host
        # Leaf lock: the fenced-rejection counter only (the standby store
        # and datastore serialize themselves).
        self._lock = threading.Lock()
        self._fenced_rejections = 0

    @property
    def fenced_rejections(self) -> int:
        with self._lock:
            return self._fenced_rejections

    # -- standby-log write protocol ----------------------------------------

    def _deliver(self, request, reset: bool):
        accepted, value = self._standby.append_batch(
            request.origin,
            request.epoch,
            records_from_proto(request.records),
            reset=reset,
            baseline_seq=request.baseline_seq,
        )
        if not accepted and value > request.epoch:
            # A stale generation of the origin tried to write behind a
            # fence — the split-brain write the epoch protocol exists to
            # reject. Counted (and surfaced via Heartbeat) so a
            # partition-then-heal run can assert fencing over the wire.
            with self._lock:
                self._fenced_rejections += 1
        return _pb.DeliverAppendsResponse(accepted=accepted, value=value)

    def DeliverAppends(self, request, context=None):
        del context
        return self._deliver(request, reset=request.reset)

    def Baseline(self, request, context=None):
        del context
        return self._deliver(request, reset=True)

    def Fence(self, request, context=None):
        del context
        self._standby.fence(request.origin, request.epoch)
        if self._host is not None and request.origin == self.replica_id:
            # Fencing a replica's OWN origin means a newer generation of
            # it exists somewhere: stop streaming rather than wait for the
            # first rejected delivery.
            self._host.fence()
        return _pb.FenceResponse(epoch=self._standby.epoch(request.origin))

    # -- lease renewal ------------------------------------------------------

    def Heartbeat(self, request, context=None):
        del request, context
        seq = 0
        if self._datastore is not None:
            try:
                seq = int(self._datastore.seq)
            except Exception:
                seq = 0
        return _pb.HeartbeatResponse(
            replica_id=self.replica_id,
            seq=seq,
            fenced_rejections=self.fenced_rejections,
            resyncs=self._host.resyncs if self._host is not None else 0,
        )

    # -- recovery plumbing ---------------------------------------------------

    def ExportStandby(self, request, context=None):
        del context
        view = self._standby.view_for(request.origin)
        response = _pb.ExportStandbyResponse(
            present=view is not None,
            epoch=self._standby.epoch(request.origin),
        )
        if view is not None:
            response.baseline_seq = view.baseline_seq
            records_to_proto(view.records, response.records)
        return response

    def ExportState(self, request, context=None):
        del context
        response = _pb.ExportStateResponse()
        if self._datastore is None:
            return response
        seq, records = self._datastore.export_with_seq()
        response.seq = seq
        wanted = set(request.studies)
        for opcode, payload in records:
            if wanted and wal_lib.study_key_of(opcode, payload) not in wanted:
                continue
            response.records.add(seq=seq, opcode=opcode, payload=payload)
        return response

    def ApplyRecords(self, request, context=None):
        del context
        applied = 0
        if self._datastore is not None:
            # Applying through the datastore re-logs (and re-replicates)
            # each record: a failover/copy-back handoff is durable on the
            # receiving replica's own disk the moment this RPC returns.
            for record in request.records:
                wal_lib.apply_record(
                    self._datastore, record.opcode, record.payload
                )
                applied += 1
        return _pb.ApplyRecordsResponse(applied=applied)

    # -- streamer pokes ------------------------------------------------------

    def Resync(self, request, context=None):
        del context
        if self._host is None:
            return _pb.ResyncResponse(requested=False)
        self._host.request_resync(request.successor)
        return _pb.ResyncResponse(requested=True)

    def FlushStream(self, request, context=None):
        del context
        if self._host is None:
            return _pb.FlushStreamResponse(flushed=True)
        timeout = request.timeout_secs or 10.0
        return _pb.FlushStreamResponse(flushed=self._host.flush(timeout))


# -- the wire deliver_fn ------------------------------------------------------


class GrpcReplicationLink:
    """Streamer deliveries over gRPC, with bounded reconnect-and-retry.

    One link per replica process; ``deliver`` matches the
    ``ReplicationStreamer`` delivery contract exactly, so the wire is just
    one more ``deliver_fn``. A transport fault (connection refused, server
    restarting, a netchaos drop) is retried on the policy's jittered
    backoff — gRPC's channel reconnects underneath — and on exhaustion the
    delivery reports ``None``: the streamer marks the successor unsynced
    and re-baselines it on next sight, so a dead link costs a resync,
    never a wedged stream or a silent gap.
    """

    def __init__(
        self,
        endpoints: Mapping[str, str],
        *,
        src_id: str = "client",
        retry_attempts: int = 3,
        retry_base_delay_secs: float = 0.05,
        retry_max_delay_secs: float = 0.5,
        connect_timeout_secs: float = 1.0,
        down_cooldown_secs: float = 2.0,
        seed: Optional[int] = None,
        netchaos: Optional[netchaos_lib.NetChaos] = None,
    ):
        self._endpoints = dict(endpoints)
        self._connect_timeout = connect_timeout_secs
        # Dead-peer cooldown: a peer that just failed transport-shaped is
        # skipped (fast ConnectionError, no connect wait) until the
        # cooldown passes. Without it, one dead successor stalls the
        # streamer's single-threaded delivery loop for a full
        # connect-timeout x retries on EVERY batch — starving the LIVE
        # successors of exactly the records a failover needs (observed:
        # the fence beat a stalled stream and acked writes lost the race).
        self._down_cooldown = down_cooldown_secs
        # netchaos seam: every RPC is traffic on the (src_id -> peer)
        # link of the fault schedule. Typed (not a closure) so the
        # lock-order pass sees the RPC-path → NetChaos-leaf-lock chain.
        self.src_id = src_id
        self._netchaos: Optional[netchaos_lib.NetChaos] = netchaos
        self._retry = retry_lib.RetryPolicy(
            max_attempts=max(1, retry_attempts),
            base_delay_secs=retry_base_delay_secs,
            max_delay_secs=retry_max_delay_secs,
            is_retryable=_is_transport_failure,
            rng=random.Random(seed),
        )
        self._lock = threading.Lock()  # leaf: stub cache + cooldowns only
        self._stubs: Dict[str, object] = {}
        self._down_until: Dict[str, float] = {}

    def set_endpoint(self, replica_id: str, endpoint: str) -> None:
        """Repoints a peer (its process restarted on a new port)."""
        with self._lock:
            self._endpoints[replica_id] = endpoint
            self._stubs.pop(replica_id, None)
            self._down_until.pop(replica_id, None)

    def clear_cooldown(self, replica_id: str) -> None:
        """Forgets a peer's dead-peer cooldown (a revive just restarted
        it; the next probe must try immediately, not wait out the old
        failure)."""
        with self._lock:
            self._down_until.pop(replica_id, None)

    def _check_cooldown(self, replica_id: str) -> None:
        with self._lock:
            until = self._down_until.get(replica_id, 0.0)
        if time.monotonic() < until:
            raise ConnectionError(
                f"replication link to {replica_id} in dead-peer cooldown"
            )

    def _note_outcome(self, replica_id: str, ok: bool) -> None:
        with self._lock:
            if ok:
                self._down_until.pop(replica_id, None)
            else:
                self._down_until[replica_id] = (
                    time.monotonic() + self._down_cooldown
                )

    def _stub(self, replica_id: str):
        with self._lock:
            stub = self._stubs.get(replica_id)
        if stub is not None:
            return stub
        from vizier_tpu.service import grpc_stubs

        endpoint = self._endpoints[replica_id]
        stub = grpc_stubs.create_replication_stub(
            endpoint, timeout=self._connect_timeout
        )
        with self._lock:
            self._stubs[replica_id] = stub
        return stub

    def _rpc(self, replica_id: str, method: str, request):
        """One attempt, routed through the netchaos link schedule. A
        duplicate strike runs the RPC twice (at-least-once delivery; the
        epoch/seq protocol on the receiver deduplicates) and promises the
        caller the SECOND copy's outcome."""
        if self._netchaos is not None:
            duplicate = self._netchaos.strike(self.src_id, replica_id)
            if duplicate:
                try:
                    getattr(self._stub(replica_id), method)(request)
                except Exception:
                    pass
        return getattr(self._stub(replica_id), method)(request)

    def call(self, replica_id: str, method: str, request):
        """One control RPC with the link's retry/reconnect policy.

        The bounded retry loop is inlined (the policy supplies the
        jittered backoff schedule) rather than routed through
        ``RetryPolicy.call`` — a direct ``self._rpc`` call keeps the
        RPC-path lock chain (netchaos leaf lock under whatever the
        caller holds) resolvable by the static lock-order pass.
        """
        self._check_cooldown(replica_id)
        attempts = max(1, self._retry.max_attempts)
        for attempt in range(attempts):
            try:
                response = self._rpc(replica_id, method, request)
            except BaseException as e:
                transport = _is_transport_failure(e)
                if attempt == attempts - 1 or not transport:
                    self._note_outcome(replica_id, ok=not transport)
                    raise
                delay = self._retry.delay_for_attempt(attempt)
                if delay > 0:
                    self._retry.sleep_fn(delay)
                continue
            self._note_outcome(replica_id, ok=True)
            return response
        raise AssertionError("unreachable")  # pragma: no cover

    def call_once(self, replica_id: str, method: str, request):
        """One control RPC with NO retries (heartbeat probes: a missed
        probe must cost one interval, not a retry storm — the lease
        already tolerates ``timeout / interval`` consecutive misses)."""
        self._check_cooldown(replica_id)
        try:
            response = self._rpc(replica_id, method, request)
        except BaseException as e:
            self._note_outcome(replica_id, ok=not _is_transport_failure(e))
            raise
        self._note_outcome(replica_id, ok=True)
        return response

    def deliver(
        self,
        successor: str,
        origin: str,
        epoch: int,
        records: Sequence[Record],
        reset: bool,
        baseline_seq: int,
    ) -> Optional[Tuple[bool, int]]:
        request = _pb.DeliverAppendsRequest(
            origin=origin,
            epoch=epoch,
            reset=reset,
            baseline_seq=baseline_seq,
        )
        records_to_proto(records, request.records)
        method = "Baseline" if reset else "DeliverAppends"
        try:
            response = self.call(successor, method, request)
        except Exception as e:
            # Unreachable after bounded retries: report None so the
            # streamer re-baselines when the successor returns.
            if not _is_transport_failure(e):
                _logger.warning(
                    "Replication delivery %s -> %s failed non-transport: %s",
                    origin,
                    successor,
                    e,
                )
            return None
        return bool(response.accepted), int(response.value)


class ProcessAppendSink:
    """The typed ``PersistentDataStore.on_append`` target of a subprocess
    replica: the cross-process sibling of ``replication.AppendSink``.

    A class (not a closure) for the same reason: the lock-order pass's
    static type resolution follows the ctor annotation, so the
    store-lock → streamer-condition chain the hook creates stays in the
    static graph.
    """

    def __init__(self, host: "ReplicaReplicationHost"):
        self._host: "ReplicaReplicationHost" = host

    def submit(self, seq: int, opcode: int, payload: bytes) -> None:
        self._host.submit(seq, opcode, payload)


class ReplicaReplicationHost:
    """The origin side of one subprocess replica's replication.

    Owns the process-local rendezvous router (liveness-blind, over the
    fleet's full id set, so every process independently computes the SAME
    per-study successor sets), the baseline exporter over the replica's
    own datastore, and the ``ReplicationStreamer`` whose deliveries ride
    ``GrpcReplicationLink``. The epoch comes from the process arguments:
    a revive restarts the process with the fenced epoch, so the fresh
    generation's first baseline announces it everywhere.
    """

    def __init__(
        self,
        replica_id: str,
        replica_ids: Sequence[str],
        *,
        datastore,
        link: GrpcReplicationLink,
        factor: int = 2,
        epoch: int = 1,
        queue_size: int = 4096,
        batch_max: int = 64,
        repair_interval_secs: float = 0.5,
        registry=None,
    ):
        self.replica_id = replica_id
        self._factor = max(1, factor)
        self._datastore = datastore
        self._link = link
        self._router = routing.StudyRouter(sorted(set(replica_ids)))
        self._resync_counter = None
        self._lag_gauge = None
        if registry is not None:
            self._resync_counter = registry.counter(
                "vizier_replication_resyncs",
                help="Standby-log re-baselines, per origin and reason.",
            )
            self._lag_gauge = registry.gauge(
                "vizier_replication_lag",
                help="Appended-but-unacked standby records per origin.",
            )
        self._streamer = replication_lib.ReplicationStreamer(
            replica_id,
            epoch,
            successors_fn=self._successors,
            deliver_fn=link.deliver,
            baseline_fn=self._baseline,
            queue_size=queue_size,
            batch_max=batch_max,
            repair_interval_secs=repair_interval_secs,
            on_lag=self._record_lag,
            on_resync=self._record_resync,
        )

    # -- streamer plumbing ---------------------------------------------------

    def _successors(self, study_key: str) -> List[str]:
        return self._router.successors(study_key, self.replica_id, self._factor)

    def _baseline(self, successor: str) -> Tuple[int, List[Record]]:
        seq, records = self._datastore.export_with_seq()
        out: List[Record] = []
        for opcode, payload in records:
            if successor and successor not in self._successors(
                wal_lib.study_key_of(opcode, payload)
            ):
                continue
            out.append((seq, opcode, payload))
        return seq, out

    def _record_lag(self, origin: str, lag: int) -> None:
        if self._lag_gauge is not None:
            self._lag_gauge.set(float(lag), origin=origin)

    def _record_resync(self, origin: str, successor: str, reason: str) -> None:
        del successor
        if self._resync_counter is not None:
            self._resync_counter.inc(origin=origin, reason=reason)

    # -- surface -------------------------------------------------------------

    def sink(self) -> ProcessAppendSink:
        return ProcessAppendSink(self)

    def submit(self, seq: int, opcode: int, payload: bytes) -> None:
        self._streamer.submit(seq, opcode, payload)

    def request_resync(self, successor: str) -> None:
        self._streamer.request_resync(successor)

    def flush(self, timeout_secs: float = 10.0) -> bool:
        return self._streamer.flush(timeout_secs)

    def fence(self) -> None:
        """Stops the streamer: a newer generation of this origin exists
        (a ``Fence`` RPC named our own id). The process keeps serving its
        other surfaces, but nothing it appends replicates any more —
        exactly the zombie posture a partitioned-away replica must take."""
        self._streamer.close()

    @property
    def fenced(self) -> bool:
        return self._streamer.fenced

    @property
    def resyncs(self) -> int:
        return self._streamer.resyncs

    @property
    def epoch(self) -> int:
        return self._streamer.epoch

    def lag(self) -> int:
        return self._streamer.lag()

    def close(self) -> None:
        self._streamer.close()
