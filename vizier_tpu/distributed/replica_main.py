"""One sharded-tier replica as a standalone gRPC server process.

``python -m vizier_tpu.distributed.replica_main --replica-id replica-0
--port 28090 [--wal-dir /data/vizier/replica-0]`` starts a
``DefaultVizierServer`` (Vizier + its own Pythia) whose datastore is a
snapshot+WAL ``PersistentDataStore`` when ``--wal-dir`` is given — the
process restarts warm from its directory. It prints ``READY <endpoint>``
on stdout once serving, which is what ``tools/service_throughput.py
--replica-mode subprocess`` waits for.

Clients reach the fleet through a client-side
:class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub` over the
replica endpoints (see ``vizier_client.environment_variables
.server_endpoints``); there is no central frontend to scale or fail.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replica-id", default="replica-0")
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    parser.add_argument("--wal-dir", default="", help="'' = RAM only")
    parser.add_argument(
        "--snapshot-interval", type=int, default=0, help="0 = config default"
    )
    parser.add_argument(
        "--obs-dump-dir",
        default=None,
        help="write <replica-id>-{spans.jsonl,metrics.json,recorder.json} "
        "here on shutdown for fleet merging (obs_report --fleet); "
        "default: $VIZIER_OBS_DUMP_DIR ('' = no dump)",
    )
    args = parser.parse_args(argv)

    # The replica serves studies, not accelerators-by-default: a dead TPU
    # tunnel must not hang jax init when the subprocess is CPU-bound work.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from vizier_tpu.analysis import registry as env_registry
    from vizier_tpu.distributed import wal as wal_lib
    from vizier_tpu.service import vizier_server

    obs_dump_dir = args.obs_dump_dir
    if obs_dump_dir is None:
        obs_dump_dir = env_registry.env_str("VIZIER_OBS_DUMP_DIR")

    datastore = None
    if args.wal_dir:
        datastore = wal_lib.PersistentDataStore(
            args.wal_dir,
            snapshot_interval=(args.snapshot_interval or None),
        )
        print(
            f"[{args.replica_id}] replayed {datastore.recovered_records} "
            f"WAL records (torn tail: {datastore.recovered_torn_tail})",
            file=sys.stderr,
            flush=True,
        )

    server = vizier_server.DefaultVizierServer(
        host=args.host,
        port=args.port or None,
        datastore=datastore,
    )
    # Tag this process's request spans so a merged fleet dump stays
    # attributable even if files are renamed.
    server.servicer.replica_id = args.replica_id
    print(f"READY {server.endpoint}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if obs_dump_dir:
        # Shutdown dump: this replica's span ring, metric snapshot, and
        # flight-recorder events, in the fleet merge's file layout.
        from vizier_tpu.observability import fleet as fleet_lib
        from vizier_tpu.observability import flight_recorder as recorder_lib
        from vizier_tpu.observability import tracing as tracing_lib

        written = fleet_lib.dump_process(
            obs_dump_dir,
            args.replica_id,
            tracer=tracing_lib.get_tracer(),
            registry=server.pythia_servicer.serving_runtime.metrics,
            recorder=recorder_lib.get_recorder(),
        )
        print(
            f"[{args.replica_id}] observability dump: "
            f"{', '.join(sorted(written.values()))}",
            file=sys.stderr,
            flush=True,
        )
    server.stop(grace=1.0)
    if datastore is not None:
        datastore.compact_now()
        datastore.close()


if __name__ == "__main__":
    main()
