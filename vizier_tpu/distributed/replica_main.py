"""One sharded-tier replica as a standalone gRPC server process.

``python -m vizier_tpu.distributed.replica_main --replica-id replica-0
--port 28090 [--wal-dir /data/vizier/replica-0]`` starts a
``DefaultVizierServer`` (Vizier + its own Pythia) whose datastore is a
snapshot+WAL ``PersistentDataStore`` when ``--wal-dir`` is given — the
process restarts warm from its directory. It prints ``READY <endpoint>``
on stdout once serving, which is what ``tools/service_throughput.py
--replica-mode subprocess`` waits for.

Clients reach the fleet through a client-side
:class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub` over the
replica endpoints (see ``vizier_client.environment_variables
.server_endpoints``); there is no central frontend to scale or fail.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replica-id", default="replica-0")
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    parser.add_argument("--wal-dir", default="", help="'' = RAM only")
    parser.add_argument(
        "--snapshot-interval", type=int, default=0, help="0 = config default"
    )
    args = parser.parse_args(argv)

    # The replica serves studies, not accelerators-by-default: a dead TPU
    # tunnel must not hang jax init when the subprocess is CPU-bound work.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from vizier_tpu.distributed import wal as wal_lib
    from vizier_tpu.service import vizier_server

    datastore = None
    if args.wal_dir:
        datastore = wal_lib.PersistentDataStore(
            args.wal_dir,
            snapshot_interval=(args.snapshot_interval or None),
        )
        print(
            f"[{args.replica_id}] replayed {datastore.recovered_records} "
            f"WAL records (torn tail: {datastore.recovered_torn_tail})",
            file=sys.stderr,
            flush=True,
        )

    server = vizier_server.DefaultVizierServer(
        host=args.host,
        port=args.port or None,
        datastore=datastore,
    )
    print(f"READY {server.endpoint}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=1.0)
    if datastore is not None:
        datastore.compact_now()
        datastore.close()


if __name__ == "__main__":
    main()
