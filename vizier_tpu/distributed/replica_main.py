"""One sharded-tier replica as a standalone gRPC server process.

``python -m vizier_tpu.distributed.replica_main --replica-id replica-0
--port 28090 [--wal-dir /data/vizier/replica-0]`` starts a
``DefaultVizierServer`` (Vizier + its own Pythia) whose datastore is a
snapshot+WAL ``PersistentDataStore`` when ``--wal-dir`` is given — the
process restarts warm from its directory. It prints ``READY <endpoint>``
on stdout once serving, which is what ``tools/service_throughput.py
--replica-mode subprocess`` (and the lease-based
``distributed.subprocess_fleet.SubprocessReplicaManager``) waits for.

With ``--peers replica-1=host:port,...`` (and a WAL dir) the replica
joins the **cross-process replication plane**: it hosts the
``ReplicationService`` gRPC surface next to ``VizierService`` — persisting
epoch-fenced standby logs for its rendezvous predecessors on its own disk
— and streams its own WAL appends to each study's rendezvous successors
over gRPC (``distributed.replication_service``). ``--replication-epoch``
is the generation a revive restarts the process at (the fleet manager
fences the old generation out first).

Graceful shutdown: SIGTERM/SIGINT drains in-flight RPCs through the gRPC
grace window, flushes the replication streamer, compacts + closes the WAL
and standby stores, and THEN writes the ``--obs-dump-dir`` observability
dump — so a terminated replica's dump reflects its final durable state.

Clients reach the fleet through a client-side
:class:`~vizier_tpu.distributed.router_stub.RoutedVizierStub` over the
replica endpoints (see ``vizier_client.environment_variables
.server_endpoints``); there is no central frontend to scale or fail.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def _parse_peers(spec: str):
    """``rid=host:port,rid=host:port`` -> ordered dict of peer endpoints."""
    peers = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        rid, _, endpoint = entry.partition("=")
        if not rid or not endpoint:
            raise SystemExit(f"Bad --peers entry: {entry!r}")
        peers[rid] = endpoint
    return peers


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replica-id", default="replica-0")
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, default=0, help="0 = pick a free port")
    parser.add_argument("--wal-dir", default="", help="'' = RAM only")
    parser.add_argument(
        "--snapshot-interval", type=int, default=0, help="0 = config default"
    )
    parser.add_argument(
        "--peers",
        default="",
        help="peer replicas as 'rid=host:port,...' (this id excluded or "
        "included, either way); with --wal-dir this arms cross-process "
        "WAL replication over the ReplicationService surface",
    )
    parser.add_argument(
        "--replication-factor", type=int, default=0, help="0 = config default"
    )
    parser.add_argument(
        "--replication-epoch",
        type=int,
        default=1,
        help="this generation's streamer epoch (a revive passes the "
        "fenced epoch so the fresh baseline announces it)",
    )
    parser.add_argument(
        "--compute-endpoint",
        default="",
        help="host:port of a shared Pythia compute server "
        "(distributed.pythia_server_main); arms the disaggregated "
        "compute tier for this frontend — Pythia dispatch goes remote "
        "with graceful local fallback. '' = $VIZIER_COMPUTE_TIER* "
        "switches decide (default: self-contained local Pythia)",
    )
    parser.add_argument(
        "--shutdown-grace",
        type=float,
        default=5.0,
        help="seconds SIGTERM waits for in-flight RPCs to drain",
    )
    parser.add_argument(
        "--obs-dump-dir",
        default=None,
        help="write <replica-id>-{spans.jsonl,metrics.json,recorder.json} "
        "here on shutdown for fleet merging (obs_report --fleet); "
        "default: $VIZIER_OBS_DUMP_DIR ('' = no dump)",
    )
    args = parser.parse_args(argv)

    # The replica serves studies, not accelerators-by-default: a dead TPU
    # tunnel must not hang jax init when the subprocess is CPU-bound work.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from vizier_tpu.analysis import registry as env_registry
    from vizier_tpu.distributed import config as config_lib
    from vizier_tpu.distributed import replication as replication_lib
    from vizier_tpu.distributed import replication_service as repl_service
    from vizier_tpu.distributed import wal as wal_lib
    from vizier_tpu.service import grpc_stubs, vizier_server
    from vizier_tpu.testing import netchaos as netchaos_lib

    obs_dump_dir = args.obs_dump_dir
    if obs_dump_dir is None:
        obs_dump_dir = env_registry.env_str("VIZIER_OBS_DUMP_DIR")

    dist_config = config_lib.DistributedConfig.from_env()
    peers = _parse_peers(args.peers)
    peers.pop(args.replica_id, None)
    replicate = bool(peers) and bool(args.wal_dir)

    standby = None
    host = None
    sink = None
    if replicate:
        # Receiver side first: reload whatever standby logs this replica
        # already holds for its peers (restart warm, same disk layout as
        # the in-process plane: <wal_dir>/standby/<origin>/).
        standby = replication_lib.StandbyStore(args.wal_dir)

    datastore = None
    if args.wal_dir:
        datastore = wal_lib.PersistentDataStore(
            args.wal_dir,
            snapshot_interval=(args.snapshot_interval or None),
            on_append=None,  # the sink attaches below, post-replay
        )
        print(
            f"[{args.replica_id}] replayed {datastore.recovered_records} "
            f"WAL records (torn tail: {datastore.recovered_torn_tail})",
            file=sys.stderr,
            flush=True,
        )

    server = vizier_server.DefaultVizierServer(
        host=args.host,
        port=args.port or None,
        datastore=datastore,
    )
    # Tag this process's request spans so a merged fleet dump stays
    # attributable even if files are renamed.
    server.servicer.replica_id = args.replica_id

    # Disaggregated compute tier (opt-in): route Pythia dispatch to the
    # shared compute server, keeping the local Pythia as the graceful
    # degradation path. With the tier off this is a no-op and the replica
    # is bit-identical to the self-contained topology.
    from vizier_tpu.distributed import compute_tier as compute_tier_lib

    pythia_endpoint = compute_tier_lib.maybe_wrap_pythia(
        server.pythia_servicer,
        replica_id=args.replica_id,
        endpoint=args.compute_endpoint,
    )
    if pythia_endpoint is not server.pythia_servicer:
        server.servicer.set_pythia(pythia_endpoint)
        print(
            f"[{args.replica_id}] compute tier armed: "
            f"{pythia_endpoint.stats()['endpoint']}",
            file=sys.stderr,
            flush=True,
        )

    if replicate:
        # Origin side: stream this replica's appends to each study's
        # rendezvous successors over gRPC. An optional VIZIER_NETCHAOS
        # schedule (seeded, parsed once) injects drops/delays/duplicates
        # on the outbound links — the in-replica arm of the network
        # fault-injection harness.
        net = None
        chaos_spec = env_registry.env_str("VIZIER_NETCHAOS")
        if chaos_spec:
            net = netchaos_lib.NetChaos.from_spec(chaos_spec)
        link = repl_service.GrpcReplicationLink(
            peers, src_id=args.replica_id, netchaos=net
        )
        registry = server.pythia_servicer.serving_runtime.metrics
        host = repl_service.ReplicaReplicationHost(
            args.replica_id,
            [args.replica_id, *peers],
            datastore=datastore,
            link=link,
            factor=args.replication_factor or dist_config.replication_factor,
            epoch=max(1, args.replication_epoch),
            queue_size=dist_config.replication_queue,
            batch_max=dist_config.replication_batch,
            registry=registry,
        )
        sink = host.sink()
        datastore.set_append_sink(sink)
    # The replication surface is served unconditionally (Heartbeat is the
    # lease-renewal probe even on tiers that do not replicate).
    replication_servicer = repl_service.ReplicationServicer(
        args.replica_id,
        standby if standby is not None else replication_lib.StandbyStore(),
        datastore=datastore,
        host=host,
    )
    grpc_stubs.add_replication_servicer_to_server(
        replication_servicer, server._server
    )

    print(f"READY {server.endpoint}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()

    # Graceful shutdown, in dependency order: (1) drain in-flight RPCs
    # through the gRPC grace window (no new appends after this), (2) flush
    # the replication streamer so every acked append reaches its standby
    # logs, (3) compact + close the WAL and standby stores (the durable
    # state is final), then (4) write the observability dump — the dump
    # describes the state the disk actually holds.
    server.stop(grace=args.shutdown_grace)
    if host is not None:
        host.flush(args.shutdown_grace)
        host.close()
    if datastore is not None:
        try:
            datastore.compact_now()
        except Exception as e:  # diverged store: close what we can
            print(
                f"[{args.replica_id}] shutdown compaction skipped: {e}",
                file=sys.stderr,
                flush=True,
            )
        datastore.close()
    if standby is not None:
        standby.close()
    if obs_dump_dir:
        # Shutdown dump: this replica's span ring, metric snapshot, and
        # flight-recorder events, in the fleet merge's file layout.
        from vizier_tpu.observability import fleet as fleet_lib
        from vizier_tpu.observability import flight_recorder as recorder_lib
        from vizier_tpu.observability import tracing as tracing_lib

        written = fleet_lib.dump_process(
            obs_dump_dir,
            args.replica_id,
            tracer=tracing_lib.get_tracer(),
            registry=server.pythia_servicer.serving_runtime.metrics,
            recorder=recorder_lib.get_recorder(),
        )
        print(
            f"[{args.replica_id}] observability dump: "
            f"{', '.join(sorted(written.values()))}",
            file=sys.stderr,
            flush=True,
        )


if __name__ == "__main__":
    main()
