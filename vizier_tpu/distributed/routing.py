"""Study-affinity routing: rendezvous hashing over service replicas.

Rendezvous (highest-random-weight) hashing instead of a ring: every
``(replica, study)`` pair gets a deterministic pseudo-random weight and a
study lives on its highest-weight live replica. Removing a replica remaps
ONLY that replica's studies (each falls to its second-ranked choice);
adding one steals only the studies that now rank it first — the minimal
disruption property a consistent-hash ring needs virtual nodes to
approximate, with no ring state at all.

Weights come from ``hashlib.blake2b`` over ``replica_id|study_key``, so the
assignment is stable across processes, interpreter restarts, and hosts —
a client-side router and a server-side ``ShardedDataStore`` computing the
placement independently always agree.

``StudyRouter`` is the shared placement + liveness table. Its lock is a
LEAF lock guarding dict/set bookkeeping only (no I/O, no callbacks under
it); it is declared in the lock-order pass's critical set to keep it that
way.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def rendezvous_weight(replica_id: str, study_key: str) -> int:
    """Deterministic 64-bit weight of placing ``study_key`` on ``replica_id``."""
    digest = hashlib.blake2b(
        f"{replica_id}|{study_key}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class NoLiveReplicaError(ConnectionError):
    """Every replica is marked down (transient: retries may heal it)."""


class StudyRouter:
    """Maps study resource names onto replica ids, tracking liveness."""

    def __init__(
        self,
        replica_ids: Sequence[str],
        *,
        routing: bool = True,
        route_cache_size: Optional[int] = None,
    ):
        if not replica_ids:
            raise ValueError("StudyRouter needs at least one replica id.")
        if len(set(replica_ids)) != len(replica_ids):
            raise ValueError(f"Duplicate replica ids: {list(replica_ids)}")
        self._replica_ids: Tuple[str, ...] = tuple(replica_ids)
        self._routing = routing
        self._lock = threading.Lock()
        self._down: set = set()
        # Placement cache: study_key -> (liveness epoch, replica). Routing
        # is pure given the liveness set, so a cached entry stays valid
        # until any replica changes state (the epoch bumps); this turns
        # the per-RPC route into a dict hit instead of N hashes + a sort.
        # LRU-bounded (VIZIER_DISTRIBUTED_ROUTE_CACHE_SIZE) so million-study
        # churn cannot grow it without bound: an evicted study just pays
        # the N-hash ranking again on its next request.
        if route_cache_size is None:
            from vizier_tpu.analysis import registry as _registry

            route_cache_size = _registry.env_int(
                "VIZIER_DISTRIBUTED_ROUTE_CACHE_SIZE", 65536
            )
        if route_cache_size < 1:
            raise ValueError(
                f"route_cache_size must be >= 1, got {route_cache_size}."
            )
        self._route_cache_size = route_cache_size
        self._epoch = 0
        self._route_cache: "collections.OrderedDict[str, Tuple[int, str]]" = (
            collections.OrderedDict()
        )

    # -- placement ---------------------------------------------------------

    def ranking(self, study_key: str) -> List[str]:
        """All replicas, best placement first (ignores liveness)."""
        if not self._routing:
            return list(self._replica_ids)
        return sorted(
            self._replica_ids,
            key=lambda rid: rendezvous_weight(rid, study_key),
            reverse=True,
        )

    def successors(
        self, study_key: str, origin: str, count: int
    ) -> List[str]:
        """The study's next-``count`` rendezvous choices after ``origin``.

        Liveness-BLIND on purpose: replication successor sets must stay
        stable while replicas bounce (a dead successor just misses
        deliveries until it returns and is re-baselined), and the first
        entry is exactly the replica :meth:`replica_for` falls to when
        ``origin`` dies — the standby log lives where the failover lands.
        """
        ranked = [rid for rid in self.ranking(study_key) if rid != origin]
        return ranked[: max(0, count)]

    def replica_for(self, study_key: str) -> str:
        """The live replica that owns ``study_key``.

        The rendezvous ranking restricted to live replicas: when the
        first-ranked replica is down, its studies fall to their
        second-ranked choice (and ONLY its studies move).
        """
        with self._lock:
            cached = self._route_cache.get(study_key)
            if cached is not None and cached[0] == self._epoch:
                self._route_cache.move_to_end(study_key)
                return cached[1]
            down = set(self._down)
            epoch = self._epoch
        for rid in self.ranking(study_key):
            if rid not in down:
                with self._lock:
                    if self._epoch == epoch:
                        self._route_cache[study_key] = (epoch, rid)
                        self._route_cache.move_to_end(study_key)
                        while len(self._route_cache) > self._route_cache_size:
                            self._route_cache.popitem(last=False)
                return rid
        raise NoLiveReplicaError(
            f"All {len(self._replica_ids)} replicas are down."
        )

    def assignments(self, study_keys: Sequence[str]) -> Dict[str, List[str]]:
        """replica id -> the subset of ``study_keys`` it currently owns."""
        out: Dict[str, List[str]] = {rid: [] for rid in self._replica_ids}
        for key in study_keys:
            out[self.replica_for(key)].append(key)
        return out

    # -- liveness ----------------------------------------------------------

    @property
    def replica_ids(self) -> Tuple[str, ...]:
        return self._replica_ids

    def live_replicas(self) -> List[str]:
        with self._lock:
            return [r for r in self._replica_ids if r not in self._down]

    def is_up(self, replica_id: str) -> bool:
        with self._lock:
            return replica_id not in self._down

    def mark_down(self, replica_id: str) -> bool:
        """Returns True when this call transitioned the replica to down."""
        self._check_known(replica_id)
        with self._lock:
            if replica_id in self._down:
                return False
            self._down.add(replica_id)
            self._epoch += 1  # invalidate every cached route
            return True

    def mark_up(self, replica_id: str) -> bool:
        """Returns True when this call transitioned the replica to up."""
        self._check_known(replica_id)
        with self._lock:
            if replica_id not in self._down:
                return False
            self._down.discard(replica_id)
            self._epoch += 1
            return True

    def last_route(self, study_key: str) -> Optional[str]:
        """The replica ``study_key`` last routed to (observability)."""
        with self._lock:
            cached = self._route_cache.get(study_key)
            return cached[1] if cached is not None else None

    def snapshot(self) -> Dict[str, str]:
        """replica id -> "up"/"down", for serving-stats dumps."""
        with self._lock:
            return {
                rid: ("down" if rid in self._down else "up")
                for rid in self._replica_ids
            }

    def _check_known(self, replica_id: str) -> None:
        if replica_id not in self._replica_ids:
            raise KeyError(f"Unknown replica id: {replica_id!r}")
