"""Static analysis suite: lock order, JAX discipline, env-switch registry.

Stdlib-only (``ast``-based — importable and runnable without jax). Run it
as a CLI (``python tools/check_analysis.py``) or through the tier-1 tests
(``tests/analysis/``); both share :func:`vizier_tpu.analysis.suite.run_suite`
and the checked-in ``baseline.toml``. See docs/guides/static_analysis.md.
"""

from vizier_tpu.analysis import registry
from vizier_tpu.analysis.common import Finding, Project
from vizier_tpu.analysis.suite import (
    ALL_PASSES,
    SuiteConfig,
    SuiteResult,
    format_report,
    load_config,
    run_suite,
)

__all__ = [
    "ALL_PASSES",
    "Finding",
    "Project",
    "SuiteConfig",
    "SuiteResult",
    "format_report",
    "load_config",
    "registry",
    "run_suite",
]
